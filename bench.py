#!/usr/bin/env python
"""Benchmark driver: PageRank + 4-hop BFS on a graph500-style R-MAT graph.

Prints ONE JSON line:
  {"metric": "pagerank_edges_per_sec_chip", "value": ..., "unit": "edges/s",
   "vs_baseline": ..., ...extras}

The primary metric is PageRank throughput (edges processed per second per
chip, over `PR_ITERS` supersteps, post-compilation) on the BENCH_SCALE
R-MAT graph — the BASELINE.json north-star workload shape. 4-hop BFS
wall-clock is reported alongside.

`vs_baseline`: the reference (JanusGraph FulgoraGraphComputer, a JVM
thread-pool BSP engine) publishes no numbers and cannot run in this
environment (BASELINE.md), so the recorded baseline is a *vectorized
numpy host implementation* of the identical supersteps measured in-process
— a deliberately strong stand-in (it is itself far faster than a
scan-per-superstep JVM engine would be), making the reported ratio
conservative.

Env knobs: BENCH_SCALE (default 22; graph500-s23 = BENCH_SCALE=23),
BENCH_EDGE_FACTOR (16), PR_ITERS (20), BENCH_STRATEGY
(auto|ell|segment|pallas — aggregation kernel, see olap/kernels.py).
"""

import json
import os
import sys
import time

import numpy as np


def host_pagerank_edges_per_sec(csr, iters: int = 5, damping: float = 0.85) -> float:
    """Vectorized numpy PageRank — the baseline proxy."""
    n = csr.num_vertices
    seg = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(csr.in_indptr)
    )
    src = csr.in_src.astype(np.int64)
    outdeg = np.maximum(csr.out_degree.astype(np.float64), 1.0)
    dangling_mask = csr.out_degree == 0
    rank = np.full(n, 1.0 / n)
    t0 = time.perf_counter()
    for _ in range(iters):
        contrib = rank / outdeg
        agg = np.bincount(seg, weights=contrib[src], minlength=n)
        dangling = rank[dangling_mask].sum()
        rank = (1.0 - damping) / n + damping * (agg + dangling / n)
    dt = time.perf_counter() - t0
    return iters * csr.num_edges / dt


def main() -> None:
    import jax

    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram, ShortestPathProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    platform = jax.devices()[0].platform
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    if platform == "cpu":
        scale = min(scale, int(os.environ.get("BENCH_SCALE", "16")))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    pr_iters = int(os.environ.get("PR_ITERS", "20"))

    t0 = time.perf_counter()
    csr = rmat_csr(scale, edge_factor)
    gen_s = time.perf_counter() - t0

    strategy = os.environ.get("BENCH_STRATEGY", "auto")
    ex = TPUExecutor(csr, strategy=strategy)

    # --- PageRank: the whole pr_iters-superstep run is ONE fused dispatch
    # (lax.while_loop on device). Warm run compiles; timed run re-executes
    # the cached executable (identical program params = identical cache key).
    timed = PageRankProgram(max_iterations=pr_iters, tol=0.0)
    ex.run(timed)
    t0 = time.perf_counter()
    result = ex.run(timed, sync_every=pr_iters)
    jax.block_until_ready(result["rank"])
    pr_s = time.perf_counter() - t0
    pr_eps = pr_iters * csr.num_edges / pr_s

    # --- 4-hop BFS (BSP frontier expansion), timed post-compile
    bfs_prog = ShortestPathProgram(seed_index=0, max_iterations=4)
    ex.run(bfs_prog)
    t0 = time.perf_counter()
    bfs_res = ex.run(bfs_prog, sync_every=4)
    jax.block_until_ready(bfs_res["distance"])
    bfs_s = time.perf_counter() - t0

    # --- host-numpy baseline proxy (see module docstring)
    base_iters = 3 if scale >= 22 else 5
    base_eps = host_pagerank_edges_per_sec(csr, iters=base_iters)

    print(
        json.dumps(
            {
                "metric": "pagerank_edges_per_sec_chip",
                "value": round(pr_eps, 1),
                "unit": "edges/s",
                "vs_baseline": round(pr_eps / base_eps, 3),
                "baseline": "numpy-host-pagerank (proxy; see bench.py docstring)",
                "platform": platform,
                "strategy": ex.strategy,
                "scale": scale,
                "edge_factor": edge_factor,
                "num_vertices": csr.num_vertices,
                "num_edges": csr.num_edges,
                "pr_iters": pr_iters,
                "pagerank_wall_s": round(pr_s, 3),
                "bfs_4hop_wall_s": round(bfs_s, 3),
                "graph_gen_s": round(gen_s, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
