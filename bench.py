#!/usr/bin/env python
"""Benchmark driver: PageRank + 4-hop BFS on a graph500-style R-MAT graph.

Prints ONE JSON line:
  {"metric": "pagerank_edges_per_sec_chip", "value": ..., "unit": "edges/s",
   "vs_baseline": ..., ...extras}

Supervisor/worker split: invoked with no args this script is a SUPERVISOR
that never imports jax itself.  It runs the actual benchmark (`--worker`)
in subprocesses: first against the ambient (TPU) backend with retry +
backoff — TPU tunnel initialization is known to be slow/flaky and can hang
the whole interpreter — then, as a clearly-labeled last resort, against
JAX_PLATFORMS=cpu.  Whatever happens, exactly one valid JSON line is
emitted on stdout.

The primary metric is PageRank throughput (edges processed per second per
chip, over `PR_ITERS` supersteps, post-compilation) on the BENCH_SCALE
R-MAT graph — the BASELINE.json north-star workload shape. 4-hop BFS
wall-clock is reported alongside.

`vs_baseline`: the reference (JanusGraph FulgoraGraphComputer, a JVM
thread-pool BSP engine) publishes no numbers and cannot run in this
environment (BASELINE.md), so the recorded baseline is a *vectorized
numpy host implementation* of the identical supersteps measured in-process
— a deliberately strong stand-in (it is itself far faster than a
scan-per-superstep JVM engine would be), making the reported ratio
conservative.

Env knobs: BENCH_SCALE (default 22; graph500-s23 = BENCH_SCALE=23),
BENCH_EDGE_FACTOR (16), PR_ITERS (20), BENCH_STRATEGY
(auto|ell|segment|pallas — aggregation kernel, see olap/kernels.py),
BENCH_BUDGET_S (total supervisor budget, default 2700),
BENCH_TPU_TIMEOUT_S (per-TPU-attempt cap, default 900),
BENCH_TPU_ATTEMPTS (default 2).
"""

import json
import os
import subprocess
import sys
import time

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------

def _run_worker(env: dict, timeout_s: float):
    """Run `bench.py --worker`; return parsed JSON result dict or None.

    The worker runs in its own session so a timeout kills the whole process
    group — a hung TPU-tunnel helper that inherited the stdout pipe would
    otherwise keep communicate() blocked past the budget."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        env=env,
        cwd=_REPO_DIR,
        stdout=subprocess.PIPE,
        start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench worker timed out after {timeout_s:.0f}s", file=sys.stderr)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        return None
    out = out.decode("utf-8", "replace") if out else ""
    for line in reversed(out.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
    print(f"bench worker rc={proc.returncode}, no JSON line", file=sys.stderr)
    return None


def supervise() -> int:
    budget = float(os.environ.get("BENCH_BUDGET_S", "2700"))
    tpu_cap = float(os.environ.get("BENCH_TPU_TIMEOUT_S", "900"))
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    cpu_reserve = 600.0
    deadline = time.monotonic() + budget

    # if the driver kills us (its own timeout), still emit one valid JSON
    # line before dying
    import signal

    def _on_term(_sig, _frm):
        print(json.dumps({
            "metric": "pagerank_edges_per_sec_chip",
            "value": 0.0,
            "unit": "edges/s",
            "vs_baseline": 0.0,
            "error": "bench supervisor received SIGTERM before completion",
        }))
        sys.stdout.flush()
        sys.exit(0)

    signal.signal(signal.SIGTERM, _on_term)

    result = None
    for i in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining < cpu_reserve + 120:
            break
        # first attempt gets the full cap; retries are short — a hang on
        # attempt 1 means the tunnel is down and retrying only burns budget,
        # while a fast init *failure* (the r1 mode) retries cheaply
        cap = tpu_cap if i == 0 else min(tpu_cap, 300.0)
        timeout_s = min(cap, remaining - cpu_reserve)
        print(
            f"bench: TPU attempt {i + 1}/{attempts} (timeout {timeout_s:.0f}s)",
            file=sys.stderr,
        )
        result = _run_worker(dict(os.environ), timeout_s)
        if result is not None:
            break
        if i + 1 < attempts:
            time.sleep(15 * (i + 1))

    if result is None:
        remaining = max(deadline - time.monotonic(), 300.0)
        print(
            "bench: TPU attempts exhausted — falling back to CPU "
            f"(timeout {remaining:.0f}s)",
            file=sys.stderr,
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        result = _run_worker(env, remaining)
        if result is not None:
            result["fallback"] = "cpu (TPU backend init failed/timed out)"

    if result is None:
        result = {
            "metric": "pagerank_edges_per_sec_chip",
            "value": 0.0,
            "unit": "edges/s",
            "vs_baseline": 0.0,
            "error": "all bench attempts failed (TPU and CPU fallback)",
        }
    # a late SIGTERM must not append a second (zero-value) JSON line after
    # the real result — last-line parsers would prefer it
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    print(json.dumps(result))
    sys.stdout.flush()
    return 0


# --------------------------------------------------------------------------
# worker (the actual benchmark; this half imports jax)
# --------------------------------------------------------------------------

def host_pagerank_edges_per_sec(csr, iters: int = 5, damping: float = 0.85) -> float:
    """Vectorized numpy PageRank — the baseline proxy."""
    import numpy as np

    n = csr.num_vertices
    seg = np.repeat(
        np.arange(n, dtype=np.int64), np.diff(csr.in_indptr)
    )
    src = csr.in_src.astype(np.int64)
    outdeg = np.maximum(csr.out_degree.astype(np.float64), 1.0)
    dangling_mask = csr.out_degree == 0
    rank = np.full(n, 1.0 / n)
    t0 = time.perf_counter()
    for _ in range(iters):
        contrib = rank / outdeg
        agg = np.bincount(seg, weights=contrib[src], minlength=n)
        dangling = rank[dangling_mask].sum()
        rank = (1.0 - damping) / n + damping * (agg + dangling / n)
    dt = time.perf_counter() - t0
    return iters * csr.num_edges / dt


def worker() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # env alone is insufficient: the ambient sitecustomize repoints
        # jax's platform config at interpreter start (config beats env)
        jax.config.update("jax_platforms", "cpu")

    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram, ShortestPathProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    platform = jax.devices()[0].platform
    if platform == "axon":  # axon = the TPU tunnel's PJRT plugin name
        platform = "tpu"
    print(f"bench worker: platform={platform}", file=sys.stderr)
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    if platform == "cpu":
        scale = min(scale, int(os.environ.get("BENCH_CPU_SCALE", "16")))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    pr_iters = int(os.environ.get("PR_ITERS", "20"))

    t0 = time.perf_counter()
    csr = rmat_csr(scale, edge_factor)
    gen_s = time.perf_counter() - t0
    print(
        f"bench worker: graph ready s{scale} |V|={csr.num_vertices} "
        f"|E|={csr.num_edges} ({gen_s:.1f}s)",
        file=sys.stderr,
    )

    strategy = os.environ.get("BENCH_STRATEGY", "auto")
    ex = TPUExecutor(csr, strategy=strategy)

    # --- PageRank: the whole pr_iters-superstep run is ONE fused dispatch
    # (lax.while_loop on device). Warm run compiles; timed run re-executes
    # the cached executable (identical program params = identical cache key).
    timed = PageRankProgram(max_iterations=pr_iters, tol=0.0)
    ex.run(timed)
    t0 = time.perf_counter()
    result = ex.run(timed, sync_every=pr_iters)
    jax.block_until_ready(result["rank"])
    pr_s = time.perf_counter() - t0
    pr_eps = pr_iters * csr.num_edges / pr_s
    print(
        f"bench worker: pagerank {pr_s:.3f}s ({pr_eps:.3e} edges/s)",
        file=sys.stderr,
    )

    # --- 4-hop BFS (BSP frontier expansion), timed post-compile
    bfs_prog = ShortestPathProgram(seed_index=0, max_iterations=4)
    ex.run(bfs_prog)
    t0 = time.perf_counter()
    bfs_res = ex.run(bfs_prog, sync_every=4)
    jax.block_until_ready(bfs_res["distance"])
    bfs_s = time.perf_counter() - t0

    # --- host-numpy baseline proxy (see module docstring)
    base_iters = 3 if scale >= 22 else 5
    base_eps = host_pagerank_edges_per_sec(csr, iters=base_iters)

    print(
        json.dumps(
            {
                "metric": "pagerank_edges_per_sec_chip",
                "value": round(pr_eps, 1),
                "unit": "edges/s",
                "vs_baseline": round(pr_eps / base_eps, 3),
                "baseline": "numpy-host-pagerank (proxy; see bench.py docstring)",
                "platform": platform,
                "strategy": ex.strategy,
                "scale": scale,
                "edge_factor": edge_factor,
                "num_vertices": csr.num_vertices,
                "num_edges": csr.num_edges,
                "pr_iters": pr_iters,
                "pagerank_wall_s": round(pr_s, 3),
                "pagerank_superstep_ms": round(1000.0 * pr_s / pr_iters, 3),
                "bfs_4hop_wall_s": round(bfs_s, 3),
                "graph_gen_s": round(gen_s, 2),
            }
        )
    )
    sys.stdout.flush()


def main() -> int:
    if "--worker" in sys.argv:
        worker()
        return 0
    return supervise()


if __name__ == "__main__":
    sys.exit(main())
