#!/usr/bin/env python
"""Benchmark driver: PageRank + 4-hop BFS on graph500-style R-MAT graphs.

Prints ONE JSON line:
  {"metric": "pagerank_edges_per_sec_chip", "value": ..., "unit": "edges/s",
   "vs_baseline": ..., ...extras}

Supervisor/worker split: invoked with no args this script is a SUPERVISOR
that never imports jax itself.  The actual benchmark (`--worker`) runs in a
subprocess and is STAGED: backend-init smoke test first, then per-scale
PageRank/BFS runs in increasing order (s16 -> s20 -> s22 -> s23 by
default).  The worker emits one flushed JSON line per completed stage on
stdout plus timestamped heartbeats on stderr, and the supervisor streams
them as they arrive — so a hang at any stage still leaves every earlier
stage's result recorded, and the artifact shows exactly where the hang
lives (init vs graph-gen vs transfer vs compile vs run).  A background
heartbeat thread ticks during backend init (the historically hanging
stage: the tunneled PJRT plugin's grant-claim loop — diagnosed round 3,
init blocks in jax.devices() before any user code can run).

The final supervisor line reports the LARGEST completed TPU scale (CPU
fallback only if no TPU stage ever completed), with per-stage results
under "stages".

`vs_baseline`: the reference (JanusGraph FulgoraGraphComputer, a JVM
thread-pool BSP engine) publishes no numbers and cannot run in this
environment (BASELINE.md), so the recorded baseline is a *vectorized
numpy host implementation* of the identical supersteps measured
in-process — a deliberately strong stand-in, making the ratio
conservative.

Env knobs: BENCH_SCALES (default "16,20,22,23" — graph500-s23 north
star last), BENCH_EDGE_FACTOR (16), PR_ITERS (20), BENCH_STRATEGY
(auto|ell|segment|pallas), BENCH_BUDGET_S (supervisor budget, default
2700), BENCH_INIT_TIMEOUT_S (cap on backend init before declaring the
tunnel dead; default sizes to the supervisor budget — a wedged claim
relay must not eat the budget the CPU fallback and prior_tpu_evidence
pointer need), BENCH_CPU_SCALE (fallback scale, 20),
BENCH_EXTRAS_SCALE (default 20 — the ladder rung that additionally runs
the CC / peer-pressure / 3-hop-count headline workloads; must appear in
BENCH_SCALES to fire, and its compile time comes out of BENCH_BUDGET_S
before the s23 rung), BENCH_STAGE_TIMEOUT_S (900; worker exits — with
every completed stage already emitted — when no phase completes for
this long: a wedged tunnel claim must not eat the ladder),
BENCH_DENSE_MAX_SCALE (21; dense-BFS comparison rungs above this are
skipped — their walls are the measured r3 gather-wall numbers and their
compiles are where the tunnel wedge bites).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------

class _WorkerRun:
    """Run `bench.py --worker`, streaming its per-stage JSON lines."""

    def __init__(self, env: dict):
        self.stages = []
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env,
            cwd=_REPO_DIR,
            stdout=subprocess.PIPE,
            start_new_session=True,
        )

    def stream(self, deadline_fn) -> None:
        """Read stage lines until EOF or deadline; kill on deadline.

        `deadline_fn()` is re-evaluated while streaming so the caller can
        extend the budget once productive stages start landing."""
        done = threading.Event()

        def _reader():
            for raw in self.proc.stdout:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "stage" in obj:
                    self.stages.append(obj)
                    print(f"bench: stage done: {line}", file=sys.stderr)
            done.set()

        t = threading.Thread(target=_reader, daemon=True)
        t.start()
        while not done.is_set():
            remaining = deadline_fn() - time.monotonic()
            if remaining <= 0:
                break
            done.wait(timeout=min(remaining, 10.0))
        if not done.is_set():
            print(
                f"bench: worker deadline reached with "
                f"{len(self.stages)} stages recorded — killing",
                file=sys.stderr,
            )
        self.kill()
        t.join(timeout=30)

    def kill(self) -> None:
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


def _final_result(stages, fallback_note=None):
    """Merge stage lines into the single output JSON line."""
    runs = [s for s in stages if s.get("stage") == "pagerank" and "value" in s]
    tpu_runs = [s for s in runs if s.get("platform") == "tpu"]
    best = None
    pool = tpu_runs or runs
    if pool:
        best = max(pool, key=lambda s: (s.get("scale", 0), s.get("value", 0)))
    out = {
        "metric": "pagerank_edges_per_sec_chip",
        "value": 0.0,
        "unit": "edges/s",
        "vs_baseline": 0.0,
        "baseline": "numpy-host-pagerank (proxy; see bench.py docstring)",
    }
    if best is not None:
        for k, v in best.items():
            if k not in ("stage", "metric"):
                out[k] = v
        out["value"] = best["value"]
    plat = best.get("platform") if best else None
    smoke = next(
        (s for s in stages
         if s.get("stage") == "smoke" and (plat is None or s.get("platform") == plat)),
        None,
    )
    if smoke:
        out["init_s"] = smoke.get("init_s")
        out["smoke_platform"] = smoke.get("platform")
    out["stages"] = [
        {k: v for k, v in s.items()} for s in stages
    ]
    if best is None:
        out["error"] = "no benchmark stage completed"
    if fallback_note:
        out["fallback"] = fallback_note
    if plat != "tpu":
        # the tunnel wedges for hours after any killed/hung claim (see
        # docs/tpu_notes.md) — when THIS run could not reach the TPU, point
        # at the most recent captured hardware artifact so the evidence
        # travels with the result
        evidence = os.path.join(_REPO_DIR, "bench_artifacts")
        if os.path.isdir(evidence):
            arts = sorted(
                os.listdir(evidence),
                key=lambda a: os.path.getmtime(os.path.join(evidence, a)),
            )
            # a full-ladder supervisor capture is the strongest evidence;
            # fall back to whatever hardware artifact is newest
            full = [a for a in arts if "supervisor_full" in a]
            if arts:
                out["prior_tpu_evidence"] = os.path.join(
                    "bench_artifacts", (full or arts)[-1]
                )
                out["prior_tpu_evidence_count"] = len(arts)
    return out


def _merge_stages(into: list, stages: list) -> None:
    """Append stage dicts not already merged (identity-deduped: a SIGTERM
    can land after stream() returned but before/around the merge)."""
    for s in stages:
        if not any(s is t for t in into):
            into.append(s)


def supervise() -> int:
    budget = float(os.environ.get("BENCH_BUDGET_S", "2700"))
    deadline = time.monotonic() + budget
    cpu_reserve = 420.0

    all_stages = []
    live = {"run": None}

    # if the driver kills us (its own timeout), emit one valid JSON line
    # with everything recorded so far FIRST (a wedged worker can be
    # unkillable/unreapable — the output contract must not depend on it),
    # then best-effort kill the worker group
    def _on_term(_sig, _frm):
        run = live.get("run")
        if run is not None:
            _merge_stages(all_stages, run.stages)
        print(json.dumps(_final_result(
            all_stages, fallback_note="supervisor SIGTERM before completion"
        )))
        sys.stdout.flush()
        if run is not None and run.proc.poll() is None:
            try:
                os.killpg(run.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)

    # --- TPU attempts: one patient staged worker (init is paid once;
    # per-stage results stream out incrementally, so a hang mid-ladder
    # still leaves earlier rungs recorded). A worker that dies FAST with
    # nothing recorded (transient tunnel flake) gets one cheap retry.
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    for i in range(attempts):
        if (deadline - cpu_reserve) - time.monotonic() < 120:
            print("bench: budget too small for a TPU attempt — skipping",
                  file=sys.stderr)
            break
        print(
            f"bench: staged TPU worker attempt {i + 1}/{attempts} "
            f"(deadline in {deadline - cpu_reserve - time.monotonic():.0f}s)",
            file=sys.stderr,
        )
        t_start = time.monotonic()
        env = dict(os.environ)
        # how long the supervisor will let this worker live (absent a
        # productive TPU rung): lets the worker size its init watchdog to
        # the REAL budget instead of a fixed 600s — round 3 gave up on a
        # slow tunnel at 600s with 1500+s of budget still unspent
        env["BENCH_WORKER_BUDGET_S"] = str(
            max(0.0, deadline - cpu_reserve - time.monotonic())
        )
        run = _WorkerRun(env)
        live["run"] = run

        def _tpu_deadline():
            # once a TPU pagerank rung has landed, the CPU fallback will
            # never run — release its reserve to the climbing ladder
            productive = any(
                s.get("stage") == "pagerank" and s.get("platform") == "tpu"
                for s in run.stages
            )
            return deadline - (0.0 if productive else cpu_reserve)

        run.stream(_tpu_deadline)
        _merge_stages(all_stages, run.stages)
        live["run"] = None
        died_fast = (time.monotonic() - t_start) < 120 and not run.stages
        if not died_fast:
            break
        time.sleep(15)

    # fallback only when NO pagerank rung completed anywhere: a completed
    # CPU rung means we were already on a CPU backend — rerunning it
    # byte-identically would just burn budget
    have_result = any(
        s.get("stage") == "pagerank" and "value" in s for s in all_stages
    )
    fallback_note = None
    if not have_result:
        remaining = max(deadline - time.monotonic(), 240.0)
        print(
            f"bench: no TPU pagerank stage — CPU fallback "
            f"(deadline in {remaining:.0f}s)",
            file=sys.stderr,
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("BENCH_CPU_SCALE", "20")
        cpu_deadline = time.monotonic() + remaining
        cpu_run = _WorkerRun(env)
        live["run"] = cpu_run
        cpu_run.stream(lambda: cpu_deadline)
        _merge_stages(all_stages, cpu_run.stages)
        live["run"] = None
        fallback_note = "cpu (no TPU stage completed; see stages for where init/run stopped)"

    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    print(json.dumps(_final_result(all_stages, fallback_note)))
    sys.stdout.flush()
    return 0


# --------------------------------------------------------------------------
# worker (the actual benchmark; this half imports jax)
# --------------------------------------------------------------------------

#: prior-artifact index for the regression sentinel (built lazily once):
#: BENCH_BASELINE_DIR overrides where prior artifacts are searched;
#: BENCH_REGRESSION=0 disables the compare step entirely
_BASELINE_INDEX = []


def _regression_sentinel(obj: dict) -> None:
    """Attach the `regression` verdict block to one emitted stage: deltas
    vs the best prior artifact for the same (stage, scale, platform,
    host-fallback) cell, or a no-op note when no prior cell matches
    (observability/benchdiff.py — `janusgraph_tpu benchdiff` is the same
    comparison as a CI gate)."""
    if os.environ.get("BENCH_REGRESSION", "1") == "0":
        return
    from janusgraph_tpu.observability.benchdiff import BaselineIndex

    if not _BASELINE_INDEX:
        root = os.path.dirname(os.path.abspath(__file__))
        dirs = [
            d for d in os.environ.get(
                "BENCH_BASELINE_DIR",
                os.pathsep.join(
                    [root, os.path.join(root, "bench_artifacts")]
                ),
            ).split(os.pathsep) if d
        ]
        _BASELINE_INDEX.append(BaselineIndex(dirs))
    _BASELINE_INDEX[0].attach_regression(obj)


def _emit(obj: dict) -> None:
    # every stage line carries the flight-recorder per-category counts at
    # emit time plus the stage's root trace id (stages run under a
    # bench.<stage> span — see _stage_span), so a BENCH_r*.json number
    # correlates straight to the black-box timeline and the span tree
    try:
        from janusgraph_tpu.observability import flight_recorder, tracer

        obj.setdefault("flight_counts", flight_recorder.counts())
        span = tracer.current()
        if span is not None:
            obj.setdefault("trace_id", f"{span.trace_id:016x}")
    except Exception:  # noqa: BLE001 - telemetry must never break the bench
        pass
    try:
        if "stage" in obj:
            # host core count joins the regression cell key: throughput
            # from a 1-core runner is not comparable to an 8-core one
            # (the SATURATE r01->r03 424->360 ops/s "regression")
            obj.setdefault("cpu_count", os.cpu_count())
        if "stage" in obj and "regression" not in obj:
            _regression_sentinel(obj)
    except Exception:  # noqa: BLE001 - the sentinel must never break the bench
        pass
    print(json.dumps(obj))
    sys.stdout.flush()


def _stage_span(name: str, **attrs):
    """Root span for one bench stage; _emit picks its trace_id up."""
    from janusgraph_tpu.observability import tracer

    return tracer.span(f"bench.{name}", **attrs)


#: last-progress timestamp for the stage watchdog (see worker()): _hb is
#: called after every phase that completes, so a silent gap this long
#: means a wedged device call (observed: the r5 s22 dense-BFS compile
#: hung the tunnel claim indefinitely and ate the remaining ladder)
_PROGRESS = {"t": time.monotonic()}


def _hb(msg: str, t0: float) -> None:
    _PROGRESS["t"] = time.monotonic()
    print(f"bench worker [{time.monotonic() - t0:8.1f}s] {msg}", file=sys.stderr, flush=True)


def host_pagerank_edges_per_sec(csr, iters: int = 5, damping: float = 0.85) -> float:
    """Vectorized numpy PageRank — the baseline proxy."""
    import numpy as np

    n = csr.num_vertices
    seg = np.repeat(np.arange(n, dtype=np.int64), np.diff(csr.in_indptr))
    src = csr.in_src.astype(np.int64)
    outdeg = np.maximum(csr.out_degree.astype(np.float64), 1.0)
    dangling_mask = csr.out_degree == 0
    rank = np.full(n, 1.0 / n)
    t0 = time.perf_counter()
    for _ in range(iters):
        contrib = rank / outdeg
        agg = np.bincount(seg, weights=contrib[src], minlength=n)
        dangling = rank[dangling_mask].sum()
        rank = (1.0 - damping) / n + damping * (agg + dangling / n)
    dt = time.perf_counter() - t0
    return iters * csr.num_edges / dt


def _cached_rmat_csr(scale, edge_factor, t0):
    """rmat_csr with an on-disk cache of the final CSR arrays: s23
    generation costs ~170s and the graph is seed-deterministic, so ladder
    re-runs (supervisor retries, end-of-round driver) should pay it once."""
    import numpy as np

    from janusgraph_tpu.olap.csr import CSRGraph
    from janusgraph_tpu.olap.generators import rmat_csr

    cache_dir = os.path.join(_REPO_DIR, ".bench_cache")
    path = os.path.join(cache_dir, f"rmat_s{scale}_ef{edge_factor}.npz")
    # reap orphaned tmp files from killed runs (pid-unique names are never
    # overwritten, and an s23 partial is multi-GB)
    try:
        for stale in os.listdir(cache_dir) if os.path.isdir(cache_dir) else []:
            if ".tmp.npz" in stale:
                sp = os.path.join(cache_dir, stale)
                # graphlint: wallclock -- file age vs mtime: both sides are wall stamps
                if time.time() - os.path.getmtime(sp) > 3600:
                    os.unlink(sp)
    except OSError:
        pass
    if os.path.exists(path):
        try:
            z = np.load(path)
            return CSRGraph(
                vertex_ids=z["vertex_ids"],
                out_indptr=z["out_indptr"],
                out_dst=z["out_dst"],
                in_indptr=z["in_indptr"],
                in_src=z["in_src"],
                out_degree=z["out_degree"],
            )
        except Exception as e:
            _hb(f"graph cache read failed ({e}) — regenerating", t0)
    csr = rmat_csr(scale, edge_factor)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # pid-unique tmp: concurrent ladder runs (supervisor retry + driver)
        # must not interleave writes into one tmp file before the rename
        tmp = f"{path}.{os.getpid()}.tmp.npz"
        np.savez(
            tmp,
            vertex_ids=csr.vertex_ids,
            out_indptr=csr.out_indptr,
            out_dst=csr.out_dst,
            in_indptr=csr.in_indptr,
            in_src=csr.in_src,
            out_degree=csr.out_degree,
        )
        os.replace(tmp, path)
    except Exception as e:
        _hb(f"graph cache write failed ({e})", t0)
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return csr


def _bench_scale(
    jax, platform, scale, edge_factor, pr_iters, strategy, t0, extras_scale
):
    """One ladder rung: generate, transfer, compile, run, report."""
    import numpy as np

    from janusgraph_tpu.olap.programs import PageRankProgram, ShortestPathProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    g0 = time.perf_counter()
    csr = _cached_rmat_csr(scale, edge_factor, t0)
    gen_s = time.perf_counter() - g0
    _hb(f"s{scale}: graph ready |V|={csr.num_vertices} |E|={csr.num_edges} "
        f"({gen_s:.1f}s)", t0)

    timed = PageRankProgram(max_iterations=pr_iters, tol=0.0)
    ell_fp = TPUExecutor.ell_footprint(csr)
    _hb(f"s{scale}: ell footprint {ell_fp['bytes']/2**30:.2f}GB "
        f"(pad {ell_fp['pad_ratio']:.2f}x)", t0)
    x0 = time.perf_counter()
    ex = TPUExecutor(csr, strategy=strategy)
    # force device transfer of the aggregation structures now so transfer
    # time is visible separately from compile time
    ex.prewarm(timed)
    transfer_s = time.perf_counter() - x0
    _hb(f"s{scale}: executor built, strategy={ex.strategy} "
        f"(transfer+pack {transfer_s:.1f}s)", t0)

    c0 = time.perf_counter()
    ex.run(timed)  # compile + first run
    compile_s = time.perf_counter() - c0
    _hb(f"s{scale}: pagerank compiled+warm ({compile_s:.1f}s)", t0)

    r0 = time.perf_counter()
    result = ex.run(timed, sync_every=pr_iters)
    jax.block_until_ready(result["rank"])
    pr_s = time.perf_counter() - r0
    pr_eps = pr_iters * csr.num_edges / pr_s
    _hb(f"s{scale}: pagerank {pr_s:.3f}s ({pr_eps:.3e} edges/s)", t0)

    # telemetry snapshot rides the artifact so BENCH_r*.json lines are
    # self-explaining: per-superstep records (wall, frontier, pad,
    # transfer, compile flags — and since PR 5 flops, bytes_accessed,
    # operational_intensity, roofline_utilization, cost_source per
    # superstep) from the registry-published run record. `roofline`
    # carries the device peaks + per-E_cap-tier aggregation the
    # utilization figures are computed against.
    run_rec = dict(ex.last_run_info)
    telemetry = {
        "superstep_records": run_rec.pop("superstep_records", [])[:32],
        "run": {k: v for k, v in run_rec.items() if k != "tiers"},
    }
    roofline = {
        **run_rec.get("roofline", {}),
        "by_tier": run_rec.get("roofline_by_tier", {}),
    }
    steps = telemetry["superstep_records"]
    if steps:
        utils = [
            r["roofline_utilization"] for r in steps
            if r.get("roofline_utilization") is not None
        ]
        roofline["operational_intensity"] = steps[-1].get(
            "operational_intensity"
        )
        roofline["utilization_mean"] = (
            round(sum(utils) / len(utils), 6) if utils else None
        )
        roofline["cost_source"] = steps[-1].get("cost_source")

    # ISSUE 6: the tuner's decision block + a pure-ELL vs hybrid A/B in
    # the SAME round on the SAME graph — the measured proof behind the
    # decision (pad ratio + superstep wall per layout). The headline run
    # above already measured whatever the tuner picked; only the missing
    # side(s) pay an extra compile+run here.
    autotune_rec = run_rec.get("autotune")
    ab = {}
    if os.environ.get("BENCH_AB", "1") != "0":
        resolved = run_rec.get("strategy_resolved")
        measured = {
            resolved: (1000.0 * pr_s / pr_iters, run_rec.get("pad_ratio")),
        }
        for strat in ("ell", "hybrid"):
            if strat in measured:
                continue
            ex_b = TPUExecutor(csr, strategy=strat)
            ex_b.run(timed)  # compile + warm (persistent cache amortizes)
            b0 = time.perf_counter()
            out_b = ex_b.run(timed, sync_every=pr_iters)
            jax.block_until_ready(out_b["rank"])
            b_s = time.perf_counter() - b0
            measured[strat] = (
                1000.0 * b_s / pr_iters,
                ex_b.last_run_info.get("pad_ratio"),
            )
            _hb(f"s{scale}: A/B {strat} {b_s:.3f}s "
                f"(pad {measured[strat][1]})", t0)
            del ex_b, out_b
        if "ell" in measured and "hybrid" in measured:
            ell_ms, ell_pad = measured["ell"]
            hyb_ms, hyb_pad = measured["hybrid"]
            ab = {
                "ell_superstep_ms": round(ell_ms, 3),
                "hybrid_superstep_ms": round(hyb_ms, 3),
                "ell_pad_ratio": ell_pad,
                "hybrid_pad_ratio": hyb_pad,
                "hybrid_speedup": round(ell_ms / max(hyb_ms, 1e-9), 3),
                "headline_strategy": resolved,
            }

    base_iters = 3 if scale >= 20 else 5
    base_eps = host_pagerank_edges_per_sec(csr, iters=base_iters)

    # Fulgora-analogue architecture baseline (VERDICT r3 #5): the
    # reference's threaded per-vertex hash-map BSP, measured — only at
    # modest scales (pure-python per-edge cost; s20 = ~4.3s/superstep)
    fulgora_fields = {}
    if scale <= 20 and os.environ.get("BENCH_FULGORA", "1") != "0":
        from janusgraph_tpu.olap.fulgora_baseline import (
            measure_fulgora_baseline,
        )

        fb = measure_fulgora_baseline(
            csr, iterations=3 if scale <= 16 else 1
        )
        fulgora_fields = {
            "fulgora_analogue_eps": round(fb["edges_per_sec"], 1),
            "vs_fulgora_analogue": round(pr_eps / fb["edges_per_sec"], 1),
            "fulgora_note": "python analogue of "
                "FulgoraGraphComputer.java:210-230 (GIL-bound; "
                "see olap/fulgora_baseline.py)",
        }
        _hb(f"s{scale}: fulgora-analogue {fb['edges_per_sec']:.3e} edges/s "
            f"(tpu/cpu path is {pr_eps / fb['edges_per_sec']:.0f}x)", t0)

    # the pagerank stage emits BEFORE the BFS section: a wedged device
    # call later in the rung (observed r5: the s22 dense-BFS compile hung
    # the tunnel claim) must not lose the rung's headline measurement
    _emit({
        "stage": "pagerank",
        "value": round(pr_eps, 1),
        "vs_baseline": round(pr_eps / base_eps, 3),
        **fulgora_fields,
        "platform": platform,
        "strategy": ex.strategy,
        "scale": scale,
        "edge_factor": edge_factor,
        "num_vertices": csr.num_vertices,
        "num_edges": csr.num_edges,
        "pr_iters": pr_iters,
        "pagerank_wall_s": round(pr_s, 3),
        "pagerank_superstep_ms": round(1000.0 * pr_s / pr_iters, 3),
        "graph_gen_s": round(gen_s, 2),
        "transfer_pack_s": round(transfer_s, 2),
        "compile_s": round(compile_s, 2),
        # one-time setup vs steady state: graph-gen is disk-cached
        # (.bench_cache), compiles persist (.jax_cache), transfer is paid
        # once per executor lifetime — steady-state cost is the run walls
        "setup_once_s": round(gen_s + transfer_s + compile_s, 2),
        "setup_amortization": "gen+compile cached across runs; "
                              "transfer once per executor",
        "ell_bytes": ell_fp["bytes"],
        "ell_pad_ratio": round(ell_fp["pad_ratio"], 3),
        # run-resolved layout's pad (the ell_pad_ratio above is the pure-
        # ELL footprint estimate the rounds have always tracked)
        "pad_ratio": run_rec.get("pad_ratio"),
        "strategy_resolved": run_rec.get("strategy_resolved"),
        "autotune": autotune_rec,
        "ab": ab,
        "roofline": roofline,
        "telemetry": telemetry,
    })

    # BFS both ways: frontier-compacted (the default; olap/frontier.py) and
    # the dense BSP path it replaces — the delta is the VERDICT r3 #1 claim.
    # Seed at the max-out-degree hub: seed 0 can be a SINK on R-MAT draws
    # (observed at s20: out-degree 0 -> a one-hop no-op "benchmark"), and
    # hub-seeded 4-hop reaches most of the graph — the honest workload.
    bfs_seed = int(np.argmax(csr.out_degree))
    bfs_prog = ShortestPathProgram(seed_index=bfs_seed, max_iterations=4)
    ex.run(bfs_prog)  # warm: compiles the per-tier step executables
    b0 = time.perf_counter()
    bfs_res = ex.run(bfs_prog)
    jax.block_until_ready(bfs_res["distance"])
    bfs_s = time.perf_counter() - b0
    _hb(f"s{scale}: bfs-4hop frontier {bfs_s:.3f}s", t0)
    _emit({
        "stage": "bfs",
        "platform": platform,
        "scale": scale,
        "bfs_4hop_wall_s": round(bfs_s, 3),
        "bfs_strategy": ex.last_run_info.get("path", "unknown"),
        "bfs_seed": bfs_seed,
        "bfs_frontier_tiers": [
            {k: t[k] for k in ("hop", "frontier", "edges", "E_cap")}
            for t in ex.last_run_info.get("tiers", [])
        ],
    })

    # dense comparison capped by default: the dense executables at the top
    # rungs are exactly the gather-wall walls the r3 artifacts measured
    # (s23 dense 4-hop 7.6-8.3s), and their compile is where the tunnel
    # wedge bit — keep the ladder's critical path off it
    dense_max = int(os.environ.get("BENCH_DENSE_MAX_SCALE", "21"))
    if scale <= dense_max:
        ex.run(bfs_prog, frontier="off")
        b0 = time.perf_counter()
        bfs_dense = ex.run(bfs_prog, sync_every=4, frontier="off")
        jax.block_until_ready(bfs_dense["distance"])
        bfs_dense_s = time.perf_counter() - b0
        _hb(f"s{scale}: bfs-4hop dense {bfs_dense_s:.3f}s "
            f"(frontier speedup {bfs_dense_s / max(bfs_s, 1e-9):.1f}x)", t0)
        _emit({
            "stage": "bfs_dense",
            "platform": platform,
            "scale": scale,
            "bfs_dense_4hop_wall_s": round(bfs_dense_s, 3),
            "bfs_frontier_speedup": round(
                bfs_dense_s / max(bfs_s, 1e-9), 2
            ),
        })

    # Remaining BASELINE.md headline workloads (configs #2/#4/#5) at ONE
    # ladder scale: ConnectedComponent, PeerPressure label propagation
    # (phase-alternating -> host-loop path), and the 3-hop
    # TraversalVertexProgram-analogue count. Gated so the budget cost is
    # bounded; compile cache amortizes re-runs.
    # On the CPU FALLBACK the extras run at the CHEAP rung (s16) instead of
    # being skipped, so all five BASELINE workload shapes still produce
    # numbers (VERDICT r4 weak #5) — the s20 peer-pressure compile alone
    # runs minutes on host XLA and would eat the whole fallback reserve
    # (measured round 4), but s16 fits. The rung is chosen (and clamped)
    # once in worker() and passed in.
    if scale == extras_scale:
        from janusgraph_tpu.olap.programs import (
            ConnectedComponentsProgram,
            PeerPressureProgram,
            TraversalCountProgram,
        )

        def _workload(name, prog, result_key=None, post=None, **runkw):
            ex.run(prog, **runkw)  # compile + warm the SAME configuration
            r0 = time.perf_counter()
            res = ex.run(prog, **runkw)
            if result_key is not None:
                np.asarray(res[result_key])  # ensure fetched before stopping
            wall = round(time.perf_counter() - r0, 3)
            line = {
                "stage": "workload", "workload": name,
                "platform": platform, "scale": scale, "wall_s": wall,
            }
            if post is not None:
                line.update(post(res))
            _hb(f"s{scale}: {name} {wall}s", t0)
            _emit(line)  # one line per workload: a later hang loses nothing

        # min-label propagation converges within the component diameter;
        # 64 covers R-MAT's O(log n) diameter with a wide margin at any
        # ladder scale, and terminate_device stops the loop at fixpoint
        _workload(
            "connected_components",
            ConnectedComponentsProgram(max_iterations=64),
            result_key="component",
            post=lambda res: {
                "components": int(len(np.unique(np.asarray(res["component"])))),
                "iter_cap": 64,
            },
        )
        # phase-alternating combiner -> host-loop path; sync_every matters
        _workload(
            "peer_pressure",
            PeerPressureProgram(rounds=5),
            result_key="cluster",
            sync_every=5,
        )
        _workload(
            "traversal_3hop_count",
            TraversalCountProgram(hops=3),
            result_key="count",
            post=lambda res: {"paths": float(np.asarray(res["count"]).sum())},
        )
        # filtered 3-hop: mid-chain has()-filter via device mask (the
        # TraversalVertexProgram-with-HasStep shape; VERDICT r3 #4)
        from janusgraph_tpu.olap.programs.olap_traversal import (
            OLAPTraversalProgram,
            PropertyFilter,
            TraversalStep,
            evaluate_filter_mask,
        )
        from janusgraph_tpu.core.predicates import Cmp

        prop_rng = np.random.default_rng(scale)
        csr.properties["score"] = prop_rng.uniform(
            0, 10, csr.num_vertices
        ).astype(np.float32)
        flt = (PropertyFilter("score", Cmp.GREATER_THAN, 5.0),)
        fmask = evaluate_filter_mask(csr, flt)
        steps_f = (
            TraversalStep("out"),
            TraversalStep("out", None, flt),
            TraversalStep("out"),
        )
        masks = np.stack(
            [np.ones(csr.num_vertices, np.float32), fmask,
             np.ones(csr.num_vertices, np.float32)], axis=1,
        )
        _workload(
            "filtered_3hop",
            OLAPTraversalProgram(steps_f, step_masks=masks),
            result_key="count",
            post=lambda res: {
                "paths": float(np.asarray(res["count"]).sum()),
                "filter_selectivity": round(float(fmask.mean()), 3),
            },
        )
        # path()-carrying OLAP traversal (VERDICT r4 #4): device reach
        # masks + host backward enumeration, seeded (full-V 3-hop path
        # enumeration is combinatorial; the count sum prices it)
        from janusgraph_tpu.olap.programs.olap_traversal import (
            enumerate_paths,
        )

        rng_p = np.random.default_rng(7)
        pseeds = tuple(
            int(s) for s in rng_p.choice(csr.num_vertices, 8, replace=False)
        )
        prog_p = OLAPTraversalProgram(
            (TraversalStep("out"), TraversalStep("out"),
             TraversalStep("out")),
            seed_indices=pseeds, record_reach=True,
        )
        ex.run(prog_p)
        r0 = time.perf_counter()
        res_p = ex.run(prog_p)
        device_wall = round(time.perf_counter() - r0, 3)
        r0 = time.perf_counter()
        sample = list(enumerate_paths(csr, prog_p, res_p, limit=10_000))
        enum_wall = round(time.perf_counter() - r0, 3)
        _hb(f"s{scale}: paths_3hop device {device_wall}s "
            f"enum[{len(sample)}] {enum_wall}s", t0)
        _emit({
            "stage": "workload", "workload": "paths_3hop_seeded",
            "platform": platform, "scale": scale,
            "wall_s": device_wall, "enum_wall_s": enum_wall,
            "seeds": len(pseeds), "paths_enumerated": len(sample),
            # f64 accumulator; per-vertex f32 counts cap exactness at 2^24
            # per vertex — beyond that the total is an estimate
            "paths_total": float(
                np.asarray(res_p["count"], np.float64).sum()
            ),
        })

        # LDBC-SNB-shaped proxy (BASELINE configs #2/#5 datasets): CC +
        # filtered 3-hop on a community-structured heavy-tail graph, one
        # scale below the R-MAT rung (same |E| order)
        from janusgraph_tpu.olap.generators import ldbc_snb_csr

        lcsr = ldbc_snb_csr(scale)
        _hb(f"s{scale}: ldbc-shaped proxy |V|={lcsr.num_vertices} "
            f"|E|={lcsr.num_edges}", t0)
        lex = TPUExecutor(lcsr, strategy=strategy)

        def _lworkload(name, prog, result_key, post=None, **runkw):
            lex.run(prog, **runkw)
            r0 = time.perf_counter()
            res = lex.run(prog, **runkw)
            np.asarray(res[result_key])
            wall = round(time.perf_counter() - r0, 3)
            line = {
                "stage": "workload", "workload": name, "dataset": "ldbc-shaped",
                "platform": platform, "scale": scale, "wall_s": wall,
                "num_edges": lcsr.num_edges,
            }
            if post is not None:
                line.update(post(res))
            _hb(f"s{scale}: {name} {wall}s", t0)
            _emit(line)

        _lworkload(
            "connected_components_ldbc",
            ConnectedComponentsProgram(max_iterations=64),
            "component",
            post=lambda res: {
                "components": int(
                    len(np.unique(np.asarray(res["component"])))
                ),
            },
        )
        lmask = evaluate_filter_mask(
            lcsr, (PropertyFilter("creation_day", Cmp.GREATER_THAN, 1825),)
        )
        _lworkload(
            "filtered_3hop_ldbc",
            OLAPTraversalProgram(
                (
                    TraversalStep("out"),
                    TraversalStep(
                        "out", None,
                        (PropertyFilter("creation_day", Cmp.GREATER_THAN,
                                        1825),),
                    ),
                    TraversalStep("out"),
                ),
                step_masks=np.stack(
                    [np.ones(lcsr.num_vertices, np.float32), lmask,
                     np.ones(lcsr.num_vertices, np.float32)], axis=1,
                ),
            ),
            "count",
            post=lambda res: {"paths": float(np.asarray(res["count"]).sum())},
        )
        del lex, lcsr

    # dense-feature tier stage (ISSUE 7, optional: BENCH_DENSE=1): the
    # 2-layer GCN forward — a fused gather->aggregate->matmul superstep —
    # at the extras rung, with the per-superstep MXU accounting and a
    # same-round ELL vs hybrid A/B so the artifact carries both layouts'
    # measured pad + wall for the [n, d] message class
    if scale == extras_scale and os.environ.get("BENCH_DENSE", "0") == "1":
        from janusgraph_tpu.olap.programs import GCNForwardProgram

        d_dim = int(os.environ.get("BENCH_DENSE_DIM", "32"))
        layers = int(os.environ.get("BENCH_DENSE_LAYERS", "2"))
        mk = lambda: GCNForwardProgram(  # noqa: E731
            feature_dim=d_dim, hidden_dim=d_dim, out_dim=d_dim,
            num_layers=layers,
        )
        dense_ab = {}
        dense_mxu = {}
        dense_steps = []
        for strat in ("ell", "hybrid"):
            ex_d = TPUExecutor(csr, strategy=strat)
            ex_d.run(mk())  # compile + warm
            d0 = time.perf_counter()
            out_d = ex_d.run(mk(), sync_every=layers)
            jax.block_until_ready(out_d["h"])
            d_s = time.perf_counter() - d0
            inf = ex_d.last_run_info
            dense_ab[strat] = {
                "superstep_ms": round(1000.0 * d_s / layers, 3),
                "pad_ratio": inf.get("pad_ratio"),
                "mxu_utilization_mean": (
                    (inf.get("mxu") or {}).get("mean_utilization")
                ),
            }
            if strat == "hybrid":
                dense_mxu = inf.get("mxu") or {}
                dense_steps = [
                    {
                        k: r.get(k)
                        for k in ("step", "wall_ms", "mxu_flops",
                                  "mxu_utilization",
                                  "roofline_utilization")
                    }
                    for r in inf.get("superstep_records", [])[:16]
                ]
            _hb(f"s{scale}: dense-gcn {strat} {d_s:.3f}s "
                f"(pad {dense_ab[strat]['pad_ratio']})", t0)
            del ex_d, out_d
        e_ms = dense_ab["ell"]["superstep_ms"]
        h_ms = dense_ab["hybrid"]["superstep_ms"]
        _emit({
            "stage": "dense_gcn",
            "platform": platform,
            "scale": scale,
            "feature_dim": d_dim,
            "num_layers": layers,
            "gcn_superstep_ms": h_ms,
            "mxu": dense_mxu,
            "superstep_records": dense_steps,
            "ab": {
                "ell": dense_ab["ell"],
                "hybrid": dense_ab["hybrid"],
                "hybrid_speedup": round(e_ms / max(h_ms, 1e-9), 3),
            },
        })
    del ex, csr


def worker() -> None:
    t0 = time.monotonic()
    _hb("interpreter up", t0)

    # heartbeat + watchdog thread: backend init historically hangs inside
    # jax.devices() (tunnel grant-claim loop) — tick so the supervisor's
    # artifact distinguishes init-hang from silence, and give up past
    # BENCH_INIT_TIMEOUT_S so a dead tunnel doesn't eat the whole budget
    init_done = threading.Event()
    init_env = os.environ.get("BENCH_INIT_TIMEOUT_S")
    init_cap = float(init_env) if init_env is not None else None
    worker_budget = float(os.environ.get("BENCH_WORKER_BUDGET_S", "0"))
    if init_cap is None:
        # default: wait as long as the supervisor's budget allows, keeping
        # ~400s so a late-arriving backend can still land the first ladder
        # rung (s16+s20 measured well under that with warm caches). An
        # EXPLICIT BENCH_INIT_TIMEOUT_S is honored verbatim — it exists to
        # fail over to CPU fast on a known-dead tunnel.
        init_cap = max(600.0, worker_budget - 400.0)

    def _ticker():
        while not init_done.wait(20.0):
            waited = time.monotonic() - t0
            _hb("waiting on backend init (jax.devices)...", t0)
            if waited > init_cap:
                _hb(f"backend init exceeded {init_cap:.0f}s — giving up", t0)
                _emit({"stage": "error",
                       "error": f"backend init exceeded {init_cap:.0f}s"})
                os._exit(3)

    threading.Thread(target=_ticker, daemon=True).start()

    import jax

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # env alone is insufficient: the ambient sitecustomize repoints
        # jax's platform config at interpreter start (config beats env)
        jax.config.update("jax_platforms", "cpu")

    # persistent compilation cache: the bucket-aggregate executables are
    # compile-heavy (~1min at s20+); re-runs of the same ladder (supervisor
    # retries, end-of-round driver run) should pay that once per shape
    if os.environ.get("BENCH_COMPILE_CACHE", "1") != "0":
        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(_REPO_DIR, ".jax_cache"),
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        except Exception as e:  # cache is an optimization, never fatal
            _hb(f"compile cache unavailable: {e}", t0)

    i0 = time.perf_counter()
    devs = jax.devices()
    init_s = time.perf_counter() - i0
    init_done.set()

    # stage watchdog: every completed phase heartbeats through _hb; a
    # silent gap past BENCH_STAGE_TIMEOUT_S means a device call wedged
    # (r5: s22 dense-BFS compile hung the tunnel claim for 15+ min) —
    # exit so the already-emitted stages become the artifact instead of
    # the supervisor burning its whole budget on the hang. 900s default
    # clears the longest legitimate gaps (s23 graph gen ~170s, big
    # compiles ~240s) with margin.
    stage_cap = float(os.environ.get("BENCH_STAGE_TIMEOUT_S", "900"))
    if stage_cap > 0:
        def _stage_watchdog():
            while True:
                time.sleep(30.0)
                gap = time.monotonic() - _PROGRESS["t"]
                if gap > stage_cap:
                    _hb(f"no progress for {gap:.0f}s — wedged device "
                        "call, exiting with recorded stages", t0)
                    _emit({
                        "stage": "error",
                        "error": f"stage watchdog: no progress for "
                                 f"{gap:.0f}s (wedged device call)",
                    })
                    os._exit(3)

        threading.Thread(target=_stage_watchdog, daemon=True).start()
    platform = devs[0].platform
    if platform == "axon":  # axon = the TPU tunnel's PJRT plugin name
        platform = "tpu"
    _hb(f"backend up: platform={platform} devices={len(devs)} "
        f"({init_s:.1f}s)", t0)

    # smoke: one tiny matmul proves the data path end to end
    import jax.numpy as jnp

    s0 = time.perf_counter()
    x = jnp.ones((512, 512), dtype=jnp.bfloat16)
    y = float(jnp.float32((x @ x).sum()))
    smoke_s = time.perf_counter() - s0
    _hb(f"smoke matmul ok ({smoke_s:.1f}s, sum={y:.0f})", t0)
    _emit({
        "stage": "smoke",
        "platform": platform,
        "init_s": round(init_s, 1),
        "matmul_s": round(smoke_s, 1),
    })

    if os.environ.get("BENCH_SCALES"):
        scales = [int(s) for s in os.environ["BENCH_SCALES"].split(",")]
    elif os.environ.get("BENCH_SCALE"):  # single-scale back-compat (cli.py)
        scales = [int(os.environ["BENCH_SCALE"])]
    else:
        scales = [16, 20, 22, 23]
    # the one rung where the BASELINE workload extras fire (computed HERE,
    # passed down — the worker's clamping and _bench_scale's gate must
    # agree or the extras silently never run)
    extras_env = os.environ.get("BENCH_EXTRAS_SCALE")
    if platform == "cpu":
        # clamp the ladder to the CPU cap: the cheap extras rung (s16,
        # where the five BASELINE workload shapes run — see _bench_scale)
        # plus the largest affordable pagerank rung. Frontier BFS + lazy
        # transfer made s20 cheap even on host.
        cap = int(os.environ.get("BENCH_CPU_SCALE", "20"))
        extras_scale = min(int(extras_env) if extras_env else 16, cap)
        scales = sorted({extras_scale, min(max(scales), cap)})
    else:
        extras_scale = int(extras_env) if extras_env else 20
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    pr_iters = int(os.environ.get("PR_ITERS", "20"))
    strategy = os.environ.get("BENCH_STRATEGY", "auto")

    for scale in scales:
        try:
            with _stage_span("rung", scale=scale):
                _bench_scale(
                    jax, platform, scale, edge_factor, pr_iters, strategy,
                    t0, extras_scale,
                )
        except Exception as e:  # report and stop climbing
            _hb(f"s{scale}: FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "error",
                "scale": scale,
                "platform": platform,
                "error": f"{type(e).__name__}: {e}"[:500],
            })
            break

    # BASELINE dataset-fidelity rows (configs #2/#4)
    if os.environ.get("BENCH_DATASETS", "1") != "0":
        try:
            with _stage_span("datasets"):
                _datasets_stage(jax, platform, t0)
        except Exception as e:
            _hb(f"datasets stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "dataset", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # OLTP micro-bench: host-side, platform-independent, bounded by the
    # edge cap (~10-20s for both backends)
    if os.environ.get("BENCH_OLTP", "1") != "0":
        try:
            with _stage_span("oltp"):
                _oltp_stage(t0)
        except Exception as e:
            _hb(f"oltp stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "oltp", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # pipelined wire-protocol A/B (ISSUE 11): remote multiquery
    # throughput, synchronous vs pipelined framing, with a depth sweep
    # and a simulated storage-node service time (loopback-zero-latency
    # cells ride along for transparency)
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        try:
            with _stage_span("oltp_pipeline"):
                _oltp_pipeline_stage(t0)
        except Exception as e:
            _hb(f"oltp_pipeline stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "oltp_pipeline", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # spillover stage (ISSUE 12, optional: BENCH_SPILLOVER=1): step-walk
    # vs spilled A/B of 2/3/4-hop traversal bursts at s16 with per-shape
    # wall + promotion trace; results asserted set-equal in-stage and the
    # cells written to bench_artifacts/r9_spillover_ab_*.jsonl
    if os.environ.get("BENCH_SPILLOVER", "0") == "1":
        try:
            with _stage_span("oltp_spillover"):
                _oltp_spillover_stage(t0)
        except Exception as e:
            _hb(f"oltp_spillover stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "oltp_spillover", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # streaming-freshness stage (ISSUE 14, optional: BENCH_STREAM=1):
    # sustained write bursts vs a rolling PageRank — delta refresh vs
    # full repack A/B with in-stage bitwise assertions and the staleness
    # window per round; artifact bench_artifacts/r11_stream_*.jsonl
    if os.environ.get("BENCH_STREAM", "0") == "1":
        try:
            with _stage_span("streaming_freshness"):
                _streaming_freshness_stage(t0)
        except Exception as e:
            _hb(
                f"streaming_freshness stage FAILED "
                f"{type(e).__name__}: {e}", t0,
            )
            _emit({
                "stage": "streaming_freshness", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # chaos stage (ISSUE 3, optional: BENCH_CHAOS=1): seeded fault
    # injection over an OLTP workload with a torn commit + recovery,
    # recording recovered-op counts and recovery latency so BENCH_*.json
    # artifacts track robustness cost over rounds
    if os.environ.get("BENCH_CHAOS", "0") == "1":
        try:
            with _stage_span("chaos"):
                _chaos_stage(t0)
        except Exception as e:
            _hb(f"chaos stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "chaos", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # multi-chip chaos stage (ISSUE 8, optional: MULTICHIP_CHAOS=1): the
    # seeded 8-device-dryrun soak — shard preemption + collective timeout
    # + one torn manifest write, completed via cross-shard auto-resume with
    # bitwise-identical state — recorded into the MULTICHIP_r* artifact
    # vocabulary (recovered_supersteps, resume_ms, shard_skew, per-shard
    # ledger totals)
    if os.environ.get("MULTICHIP_CHAOS", "0") == "1":
        try:
            with _stage_span("multichip_chaos"):
                _multichip_chaos_stage(t0)
        except Exception as e:
            _hb(f"multichip_chaos stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "multichip_chaos", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # multi-chip exchange A/B (ISSUE 9, optional: MULTICHIP=1): eager
    # (a2a boundary values / ring streaming) vs propagation-blocked halo
    # exchange — superstep_ms, exchange bytes, batches per superstep per
    # cell, blocked cells certified bitwise against the numpy replay
    # oracle, dense-feature sharded numbers when BENCH_DENSE=1 — the
    # MULTICHIP_r07 artifact vocabulary
    if os.environ.get("MULTICHIP", "0") == "1":
        try:
            with _stage_span("multichip_ab"):
                _multichip_ab_stage(t0)
        except Exception as e:
            _hb(f"multichip_ab stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "multichip_ab", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # saturation stage (ISSUE 10, optional: SATURATE=1): closed-loop
    # offered-load ramp through saturation against a remote-store-backed
    # query server with admission control — per-level goodput/p99/
    # shed-rate + brownout transitions, written to SATURATE_r01.json.
    # Acceptance: goodput at 2x the saturation offered load within 10% of
    # peak (no congestion collapse), every shed carrying Retry-After,
    # zero hung connections.
    if os.environ.get("SATURATE", "0") == "1":
        try:
            with _stage_span("saturate"):
                _saturate_stage(t0)
        except Exception as e:
            _hb(f"saturate stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "saturate", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # fleet chaos stage (ISSUE 15 + 17, optional: FLEET=1): closed-loop
    # ramp against a 3-replica fleet over ONE shared backend, seeded
    # replica-kill + restart mid-traffic (storage/faults.py fleet kinds),
    # artifact FLEET_r02.json with per-replica goodput/p99/brownout lanes,
    # a router-failover-latency headline, the federated incident timeline
    # (kill -> mark_dead -> re-pin -> warm-up, validated Chrome trace),
    # and a stitched cross-replica failover trace. Acceptance: goodput >=
    # 0.6x pre-kill during failover, >= 0.9x after re-convergence, zero
    # hung connections, zero surfaced errors, federation scrape overhead
    # < 1% of request wall.
    if os.environ.get("FLEET", "0") == "1":
        try:
            with _stage_span("fleet_chaos"):
                _fleet_chaos_stage(t0)
        except Exception as e:
            _hb(f"fleet stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "fleet_chaos", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # durable-CDC leader failover (ISSUE 18, rides the FLEET gate or
    # runs alone via FLEET_CDC=1): a leader streams every commit into
    # the segmented CDC log while a follower bootstraps from a shard
    # checkpoint and pulls continuously; the seeded fault plan kills the
    # leader mid-write-storm and the follower promotes from the log.
    # Artifact FLEET_r03.json. Acceptance: zero surfaced errors, bounded
    # staleness, promoted state bitwise-identical to a fresh scan, and
    # the kill -> promote -> caught_up incident-phase grammar.
    if os.environ.get("FLEET", "0") == "1" or (
        os.environ.get("FLEET_CDC", "0") == "1"
    ):
        try:
            with _stage_span("fleet_cdc_failover"):
                _fleet_cdc_failover_stage(t0)
        except Exception as e:
            _hb(f"fleet cdc stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "fleet_cdc_failover", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # seeded stall forensics (ISSUE 19, rides the FLEET gate or runs
    # alone via FLEET_STALL=1): a seeded stalled-lock fault must produce
    # a watchdog lock_convoy flight event naming the holding frame and a
    # complete atomic forensics bundle, with a byte-reproducible fault
    # journal per seed. Artifact FLEET_r04.json.
    if os.environ.get("FLEET", "0") == "1" or (
        os.environ.get("FLEET_STALL", "0") == "1"
    ):
        try:
            with _stage_span("fleet_stall_forensics"):
                _stall_forensics_stage(t0)
        except Exception as e:
            _hb(f"stall forensics stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "fleet_stall_forensics", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # streaming-telemetry push-vs-poll A/B (ISSUE 20, rides the FLEET
    # gate or runs alone via FLEET_PUSH=1): a live replica streams
    # flight events over /watch to a push-mode federation while the
    # poll baseline only refreshes at tick boundaries; a seeded
    # replica kill mid-stream must lose ZERO events (cursor resume on
    # renegotiation) and the killed replica's forensics bundle must be
    # retrievable off-host after the death. Artifact FLEET_r05.json.
    # Acceptance: push event p99 <= 0.1x the poll interval, bus
    # self-cost < 1% on both the wall and CPU clocks.
    if os.environ.get("FLEET", "0") == "1" or (
        os.environ.get("FLEET_PUSH", "0") == "1"
    ):
        try:
            with _stage_span("fleet_push_poll"):
                _fleet_push_stage(t0)
        except Exception as e:
            _hb(f"fleet push stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "fleet_push_poll", "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })

    # pallas kernel evidence (VERDICT r2 #5): compiled run at s16 with
    # parity vs the ell result; failure is recorded, not fatal. The stage
    # runs LAST and under a watchdog: a hung Mosaic compile through the
    # tunnel (observed: the r3 s16 run wedged here and burned the remaining
    # budget) can only cost PALLAS_TIMEOUT_S now, and since everything else
    # already emitted, the watchdog may simply exit the process.
    if platform == "tpu" and os.environ.get("BENCH_PALLAS", "1") != "0":
        cap = float(os.environ.get("BENCH_PALLAS_TIMEOUT_S", "240"))
        done = threading.Event()

        def _pallas_watchdog():
            if not done.wait(cap):
                _hb(f"pallas stage exceeded {cap:.0f}s — exiting", t0)
                _emit({
                    "stage": "pallas",
                    "ok": False,
                    "error": f"watchdog: pallas stage exceeded {cap:.0f}s "
                             "(hung compile/run)",
                })
                os._exit(0)

        threading.Thread(target=_pallas_watchdog, daemon=True).start()
        try:
            with _stage_span("pallas"):
                _pallas_stage(jax, pr_iters, t0)
        except Exception as e:
            _hb(f"pallas stage FAILED {type(e).__name__}: {e}", t0)
            _emit({
                "stage": "pallas",
                "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
            })
        done.set()


def _chaos_stage(t0):
    """Seeded chaos soak (storage/faults.py): N transactions through
    injected temporary faults + one torn batch, crash, reopen with
    torn-commit recovery, and finish. Emits recovered-op counts (retries
    absorbed below the workload) and recovery latency so robustness cost
    is a tracked number, not folklore."""
    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.exceptions import (
        InjectedCrashError,
        TemporaryBackendError,
    )
    from janusgraph_tpu.observability import registry
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    n_txs = int(os.environ.get("BENCH_CHAOS_TXS", "300"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "42"))
    base = {
        "ids.authority-wait-ms": 0.0,
        "locks.wait-ms": 0.0,
        "tx.log-tx": True,
        "tx.max-commit-time-ms": 0.0,
        "storage.backoff-base-ms": 1.0,
        "storage.backoff-max-ms": 4.0,
    }
    chaos = {
        **base,
        "storage.faults.enabled": True,
        "storage.faults.seed": seed,
        "storage.faults.read-error-rate": 0.02,
        "storage.faults.write-error-rate": 0.02,
        "storage.faults.torn-mutation-at": n_txs // 2,
        "storage.faults.lock-expiry-at": n_txs // 3,
    }
    retries_before = registry.get_count("storage.backend_op.retries")
    mgr = InMemoryStoreManager()
    w0 = time.perf_counter()
    graph = JanusGraphTPU(chaos, store_manager=mgr)
    plan = graph.fault_plan
    mgmt = graph.management()
    mgmt.make_property_key("uid", int)
    mgmt.build_composite_index("chaosByUid", ["uid"], unique=True)

    def write(g, i):
        retries = 12
        for attempt in range(retries):
            tx = g.new_transaction()
            try:
                tx.add_vertex(uid=i)
                tx.commit()
                return
            except TemporaryBackendError:
                if tx.is_open:
                    tx.rollback()
                if attempt == retries - 1:
                    raise

    crashed_at = None
    for i in range(n_txs):
        try:
            write(graph, i)
        except InjectedCrashError:
            crashed_at = i
            break
    r0 = time.perf_counter()
    graph2 = JanusGraphTPU(base, store_manager=mgr)  # recovery runs here
    recovery_ms = (time.perf_counter() - r0) * 1000.0
    for i in range((crashed_at + 1) if crashed_at is not None else n_txs,
                   n_txs):
        write(graph2, i)
    txc = graph2.new_transaction(read_only=True)
    present = sum(
        1 for i in range(n_txs)
        if graph2.index_lookup(txc, "chaosByUid", (i,))
    )
    txc.rollback()
    injected = {}
    for e in plan.journal:
        injected[e["kind"]] = injected.get(e["kind"], 0) + 1
    rec = graph2.last_torn_recovery or {}
    _emit({
        "stage": "chaos",
        "ok": present == n_txs,
        "seed": seed,
        "txs": n_txs,
        "crashed_at": crashed_at,
        "vertices_present": present,
        "injected": injected,
        "recovered_ops": registry.get_count("storage.backend_op.retries")
        - retries_before,
        "torn_replayed": len(rec.get("replayed", ())),
        "torn_rolled_back": len(rec.get("rolled_back", ())),
        "recovery_open_ms": round(recovery_ms, 2),
        "wall_s": round(time.perf_counter() - w0, 3),
        **_chaos_flight_dump(),
    })
    graph2.close()
    _hb(f"chaos stage ok ({present}/{n_txs} present)", t0)


def _multichip_chaos_stage(t0):
    """8-virtual-device chaos soak via the hermetic dryrun subprocess
    (__graft_entry__._chaos_multichip_inproc): injected shard preemption,
    collective timeout, straggler skew, and a torn manifest write, all
    absorbed by sharded-checkpoint auto-resume with bitwise-identical
    final state on {sharded x ell/segment, cpu x ell/hybrid}. The
    subprocess re-execs with the forced CPU mesh, so this stage is safe
    to run from a TPU-configured bench process."""
    import json
    import subprocess
    import sys
    import tempfile

    n_dev = int(os.environ.get("MULTICHIP_CHAOS_DEVICES", "8"))
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as d:
        out_path = os.path.join(d, "multichip_chaos.json")
        env = dict(os.environ)
        env["MULTICHIP_CHAOS"] = "1"
        env["MULTICHIP_OUT"] = out_path
        w0 = time.perf_counter()
        res = subprocess.run(
            [sys.executable, "-c",
             f"import __graft_entry__ as ge; ge.dryrun_multichip({n_dev})"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=float(os.environ.get("MULTICHIP_CHAOS_TIMEOUT_S", "600")),
        )
        wall_s = time.perf_counter() - w0
        if res.returncode != 0 or not os.path.exists(out_path):
            _emit({
                "stage": "multichip_chaos", "ok": False,
                "rc": res.returncode,
                "error": (res.stderr or "")[-500:],
            })
            _hb(f"multichip_chaos FAILED rc={res.returncode}", t0)
            return
        with open(out_path) as f:
            chaos = json.load(f)
    _emit({
        "stage": "multichip_chaos",
        "ok": True,
        "wall_s": round(wall_s, 3),
        **chaos,
    })
    _hb(
        f"multichip_chaos ok (recovered_supersteps="
        f"{chaos['recovered_supersteps']}, skew={chaos['shard_skew']})",
        t0,
    )


def _multichip_ab_stage(t0):
    """Eager-vs-blocked exchange A/B on the 8-virtual-device mesh via the
    hermetic dryrun subprocess (__graft_entry__._ab_multichip_inproc):
    per-cell superstep_ms + exchange elems/bytes/batches for
    {a2a-ell, a2a-segment, ring-segment, blocked-ell, blocked-segment}
    scalar PageRank cells, dense-feature GCN cells on the fan-in graph
    when BENCH_DENSE=1, blocked cells certified bitwise against
    halo.replay_superstep, BFS bitwise blocked-vs-eager."""
    import json
    import subprocess
    import sys
    import tempfile

    n_dev = int(os.environ.get("MULTICHIP_DEVICES", "8"))
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as d:
        out_path = os.path.join(d, "multichip_ab.json")
        env = dict(os.environ)
        env["MULTICHIP_OUT"] = out_path
        env.setdefault("BENCH_DENSE", "1")
        w0 = time.perf_counter()
        res = subprocess.run(
            [sys.executable, "-c",
             "import __graft_entry__ as ge; "
             f"ge.dryrun_multichip_ab({n_dev})"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=float(os.environ.get("MULTICHIP_AB_TIMEOUT_S", "900")),
        )
        wall_s = time.perf_counter() - w0
        if res.returncode != 0 or not os.path.exists(out_path):
            _emit({
                "stage": "multichip_ab", "ok": False,
                "rc": res.returncode,
                "error": (res.stderr or "")[-500:],
            })
            _hb(f"multichip_ab FAILED rc={res.returncode}", t0)
            return
        with open(out_path) as f:
            ab = json.load(f)
    _emit({
        "stage": "multichip_ab",
        "ok": True,
        "wall_s": round(wall_s, 3),
        **ab,
    })
    hd = ab.get("headline", {})
    _hb(
        "multichip_ab ok (dense blocked-vs-eager "
        f"{hd.get('dense_speedup_blocked_vs_eager')}x, "
        f"batches {hd.get('batches_blocked')} vs ring "
        f"{hd.get('batches_ring_eager')})",
        t0,
    )


def _chaos_flight_dump() -> dict:
    """BENCH_CHAOS extra: write a flight-recorder dump of the chaos run
    and record its size + write latency, so the artifact tracks the cost
    of the black box itself over rounds."""
    from janusgraph_tpu.observability import flight_recorder

    d0 = time.perf_counter()
    path = flight_recorder.dump(reason="bench-chaos")
    dump_ms = (time.perf_counter() - d0) * 1000.0
    if path is None:
        return {"flight_dump": None}
    return {
        "flight_dump": path,
        "flight_dump_bytes": os.path.getsize(path),
        "flight_dump_ms": round(dump_ms, 3),
        "flight_dump_events": flight_recorder.occupancy,
    }


def _datasets_stage(jax, platform, t0):
    """BASELINE dataset-fidelity rows (VERDICT r4 #6): ConnectedComponents
    on the LDBC-SF1-SIZED SNB-shaped proxy (config #2) and PeerPressure on
    the Twitter-2010-shaped power-law proxy (config #4). On TPU the LDBC
    proxy is the documented SF1 size (3.2M vertices / 17.3M edges) and the
    Twitter proxy runs 2M vertices / 73M edges; the CPU fallback runs the
    same SHAPES scaled down so the rows always produce numbers."""
    import numpy as np

    from janusgraph_tpu.olap.generators import ldbc_sf_csr, twitter_csr
    from janusgraph_tpu.olap.programs import (
        ConnectedComponentsProgram,
        PeerPressureProgram,
    )
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    if platform == "tpu":
        ldbc_kw = {"sf": 1, "scale_down": 1}
        tw_n, tw_ef = 1 << 21, 35.0
    else:
        ldbc_kw = {"sf": 1, "scale_down": 8}
        tw_n, tw_ef = 1 << 16, 35.0

    g0 = time.perf_counter()
    lcsr = ldbc_sf_csr(**ldbc_kw)
    _hb(f"datasets: ldbc-sf1 proxy |V|={lcsr.num_vertices} "
        f"|E|={lcsr.num_edges} ({time.perf_counter() - g0:.1f}s)", t0)
    ex = TPUExecutor(lcsr)
    prog = ConnectedComponentsProgram(max_iterations=64)
    ex.run(prog)
    r0 = time.perf_counter()
    res = ex.run(prog)
    comp = np.asarray(res["component"])
    wall = round(time.perf_counter() - r0, 3)
    _emit({
        "stage": "dataset", "workload": "connected_components",
        "dataset": "ldbc-sf1-shaped", "baseline_config": 2,
        "platform": platform, "num_vertices": lcsr.num_vertices,
        "num_edges": lcsr.num_edges, "wall_s": wall,
        "scale_down": ldbc_kw["scale_down"],
        "components": int(len(np.unique(comp))),
        "path": ex.last_run_info.get("path"),
    })
    _hb(f"datasets: ldbc-sf1 CC {wall}s", t0)
    del ex, lcsr, res

    g0 = time.perf_counter()
    tcsr = twitter_csr(tw_n, tw_ef)
    _hb(f"datasets: twitter-shaped proxy |V|={tcsr.num_vertices} "
        f"|E|={tcsr.num_edges} ({time.perf_counter() - g0:.1f}s)", t0)
    ex = TPUExecutor(tcsr)
    pp = PeerPressureProgram(rounds=5)
    ex.run(pp, sync_every=5)
    r0 = time.perf_counter()
    res = ex.run(pp, sync_every=5)
    cl = np.asarray(res["cluster"])
    wall = round(time.perf_counter() - r0, 3)
    _emit({
        "stage": "dataset", "workload": "peer_pressure",
        "dataset": "twitter2010-shaped", "baseline_config": 4,
        "platform": platform, "num_vertices": tcsr.num_vertices,
        "num_edges": tcsr.num_edges, "wall_s": wall,
        "clusters": int(len(np.unique(cl))),
    })
    _hb(f"datasets: twitter peer-pressure {wall}s", t0)
    del ex, tcsr, res


def _saturate_stage(t0):
    """Closed-loop saturation ramp (ISSUE 10 acceptance): offered load
    (client concurrency) doubles per level against a remote-store-backed
    server with cost-aware admission; per-level goodput, latency
    percentiles, shed rate, and brownout rung land in the artifact. The
    defense holds when goodput past saturation stays within 10% of peak
    — no congestion collapse — with every shed carrying Retry-After and
    zero hung connections."""
    import threading as _threading

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.driver import JanusGraphClient
    from janusgraph_tpu.driver.client import RemoteError
    from janusgraph_tpu.observability import flight_recorder, registry
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer
    from janusgraph_tpu.server.admission import AdmissionController
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import RemoteStoreServer

    levels = [
        int(x) for x in os.environ.get(
            "SATURATE_LEVELS", "1,2,4,8,16,32,64"
        ).split(",")
    ]
    level_s = float(os.environ.get("SATURATE_LEVEL_S", "3.0"))
    n_vertices = int(os.environ.get("SATURATE_VERTICES", "256"))
    out_path = os.environ.get(
        "SATURATE_OUT", os.path.join(_REPO_DIR, "SATURATE_r01.json")
    )
    # simulated per-op storage-node service time (SATURATE_STORE_LAT_US):
    # with real storage latency the request handlers' concurrent reads
    # cross the adaptive gate and ride the PIPELINED framing — the r02
    # re-run proves the AIMD limiter and price book re-converge on
    # pipelined latencies (0 = loopback, the r01 configuration)
    store_lat_us = float(os.environ.get("SATURATE_STORE_LAT_US", "0"))

    # the serving path under test: remote KCVS backend (the r05 slowest
    # link) behind the query server, admission tuned for an early knee so
    # the ramp actually crosses saturation inside the level ladder
    backing = InMemoryStoreManager()
    kcvs = RemoteStoreServer(
        _LatencyManager(backing, store_lat_us / 1e6)
        if store_lat_us else backing,
        pipeline_workers=32,
    ).start()
    host, port = kcvs.address
    graph = open_graph({
        "ids.authority-wait-ms": 0.0,
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    })
    graph.management().make_edge_label("knows")
    tx = graph.new_transaction()
    ids = [tx.add_vertex().id for _ in range(n_vertices)]
    for i in range(n_vertices):
        a = tx.get_vertex(ids[i])
        b = tx.get_vertex(ids[(i * 7 + 1) % n_vertices])
        tx.add_edge(a, "knows", b)
    tx.commit()
    manager = JanusGraphManager()
    manager.put_graph("graph", graph)
    ctl = AdmissionController(
        initial_limit=int(os.environ.get("SATURATE_LIMIT_INIT", "4")),
        min_limit=1,
        max_limit=int(os.environ.get("SATURATE_LIMIT_MAX", "8")),
        queue_bound=int(os.environ.get("SATURATE_QUEUE", "8")),
        retry_after_base_s=0.02, retry_after_max_s=0.5,
        brownout_window_s=2.0, brownout_enter_sheds=50,
        brownout_exit_s=4.0, brownout_dwell_s=1.0,
    )
    # latency-queueing service times (storage-latency dominated) need a
    # tighter AIMD latency threshold than the CPU-bound r01 profile: the
    # decrease must fire before queue growth doubles the median
    ctl.limiter.threshold = float(
        os.environ.get("SATURATE_AIMD_THRESHOLD", "2.0")
    )
    # the observability plane rides the ramp: a 1 s sampling cadence puts
    # several history windows inside each level, the SLO engine evaluates
    # per window, and the sampler's measured self-overhead
    # (observability.history.overhead_ms) becomes an acceptance number
    from janusgraph_tpu.observability import history, slo_engine

    history.reset()
    history.configure(interval_s=1.0)
    # the continuous sampling profiler rides the ramp too (the server
    # starts it): flame windows seal in lockstep with the 1 s history
    # windows, and its measured self-cost (wall AND cpu, 1-core honest)
    # is gated in-stage below — <1% CPU or the stage fails
    from janusgraph_tpu.observability import sampling_profiler

    sampling_profiler.reset()
    sampling_profiler.configure(
        hz=float(os.environ.get("SATURATE_PROFILE_HZ", "20")),
        max_windows=256,
    )
    server = JanusGraphServer(
        manager=manager, admission=ctl, request_timeout_s=30.0,
    ).start()

    flight_recorder.reset()
    # a deep ring for the ramp: slow-span events from thousands of slowed
    # requests must not evict the brownout transitions the artifact wants
    flight_recorder.configure(capacity=8192)
    per_level = []
    hung_total = 0
    sheds_missing_retry_after = 0
    try:
        for conc in levels:
            counts = {"ok": 0, "shed": 0, "timeout": 0, "error": 0}
            lat_ms = []
            lock = _threading.Lock()
            stop_at = time.monotonic() + level_s

            def _worker(widx):
                nonlocal sheds_missing_retry_after
                client = JanusGraphClient(
                    port=server.port, retry_budget_capacity=0,
                )
                rng = widx * 31
                while time.monotonic() < stop_at:
                    rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
                    vid = ids[rng % n_vertices]
                    q0 = time.perf_counter()
                    try:
                        client.submit(
                            f"g.V({vid}).out('knows').count()",
                            deadline_ms=10_000,
                        )
                        with lock:
                            counts["ok"] += 1
                            lat_ms.append(
                                (time.perf_counter() - q0) * 1000.0
                            )
                    except RemoteError as e:
                        with lock:
                            if e.status == "shed":
                                counts["shed"] += 1
                                if e.retry_after_s is None:
                                    sheds_missing_retry_after += 1
                            elif e.status == "timeout":
                                counts["timeout"] += 1
                            else:
                                counts["error"] += 1
                        # honor the (jittered) Retry-After hint like a
                        # well-behaved client; keeps the closed loop from
                        # degenerating into a hot shed spin
                        if e.status == "shed" and e.retry_after_s:
                            time.sleep(min(e.retry_after_s, 0.1))
                    except Exception:  # noqa: BLE001 - hang bucket
                        with lock:
                            counts["error"] += 1

            threads = [
                _threading.Thread(target=_worker, args=(i,))
                for i in range(conc)
            ]
            t_level = time.monotonic()
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=level_s + 30.0)
            hung = sum(1 for th in threads if th.is_alive())
            hung_total += hung
            wall = time.monotonic() - t_level
            lat_ms.sort()
            line = {
                "offered_concurrency": conc,
                "wall_s": round(wall, 3),
                "completed": counts["ok"],
                "goodput_per_s": round(counts["ok"] / wall, 1),
                "shed": counts["shed"],
                "shed_per_s": round(counts["shed"] / wall, 1),
                "timeouts": counts["timeout"],
                "errors": counts["error"],
                "hung_connections": hung,
                "p50_ms": round(
                    lat_ms[len(lat_ms) // 2], 2
                ) if lat_ms else None,
                "p99_ms": round(
                    lat_ms[int(len(lat_ms) * 0.99)], 2
                ) if lat_ms else None,
                "admission_limit": int(
                    registry.snapshot().get(
                        "server.admission.limit", {}
                    ).get("value", 0)
                ),
                "brownout_rung": ctl.brownout.rung,
            }
            per_level.append(line)
            _hb(
                f"saturate@{conc}: {line['goodput_per_s']:.0f} ok/s "
                f"{line['shed_per_s']:.0f} shed/s p99 {line['p99_ms']}ms "
                f"rung {line['brownout_rung']}", t0,
            )
    finally:
        server.stop()
        graph.close()
        kcvs.stop()

    # saturation = the knee: the FIRST offered load reaching 95% of peak
    # goodput (closed-loop goodput is flat past the knee, so "the level
    # with max goodput" would just pick measurement noise inside the
    # plateau); acceptance compares goodput at 2x that offered load
    # against the peak
    peak = max(per_level, key=lambda r: r["goodput_per_s"])
    knee = next(
        r for r in per_level
        if r["goodput_per_s"] >= 0.95 * peak["goodput_per_s"]
    )
    knee_conc = knee["offered_concurrency"]
    twice = next(
        (r for r in per_level
         if r["offered_concurrency"] >= 2 * knee_conc),
        per_level[-1],
    )
    ratio = (
        twice["goodput_per_s"] / peak["goodput_per_s"]
        if peak["goodput_per_s"] else 0.0
    )
    brownout_events = [
        {k: e[k] for k in ("rung", "direction", "reason", "seq")}
        for e in flight_recorder.events("brownout")
    ]
    from janusgraph_tpu.storage.pipeline import pipeline_health_block

    snap = registry.snapshot()
    pipe_block = pipeline_health_block(snap)
    # history-sampler self-overhead acceptance: the TOTAL wall the
    # sampler spent across the ramp must stay under 1% of the TOTAL
    # request wall the replica served in the same span — observability
    # whose cost is a visible fraction of the serving work has no place
    # on a serving replica (ISSUE 13 acceptance)
    sample_t = snap.get("observability.history.sample", {})
    req_t = snap.get("server.request.wall", {})
    total_sample_ms = float(sample_t.get("total_ms", 0.0) or 0.0)
    total_req_ms = float(req_t.get("total_ms", 0.0) or 0.0)
    overhead_ratio = (
        total_sample_ms / total_req_ms if total_req_ms > 0 else 0.0
    )
    history_block = {
        "samples": int(sample_t.get("count", 0) or 0),
        "windows_retained": len(history.windows()),
        "mean_sample_ms": round(
            float(sample_t.get("mean_ms", 0.0) or 0.0), 4
        ),
        "total_sample_ms": round(total_sample_ms, 3),
        "last_overhead_ms": float(
            snap.get("observability.history.overhead_ms", {})
            .get("value", 0.0)
        ),
        "total_request_ms": round(total_req_ms, 1),
        "overhead_over_request_wall": round(overhead_ratio, 6),
        "ok": bool(overhead_ratio < 0.01),
    }
    slo_block = slo_engine.snapshot()
    # continuous-profiler acceptance (ISSUE 19): the sampler's measured
    # self-CPU across the ramp must stay under 1% of one core, the
    # sampler must still be accounted for (not silently dead), and the
    # merged flame (top stacks) lands in the artifact so benchdiff can
    # attribute a future regression frame-by-frame
    sampling_profiler.seal_window(seq=-1)
    prof = sampling_profiler.status()
    merged_flame = sampling_profiler.merged_stacks()
    flame_top = dict(sorted(
        merged_flame.items(), key=lambda kv: (-kv[1], kv[0])
    )[:40])
    profiler_block = {
        "hz": prof["hz"],
        "samples": prof["samples"],
        "windows_sealed": prof["windows_sealed"],
        "distinct_stacks": len(merged_flame),
        "overhead_cpu_pct": prof["overhead_cpu_pct"],
        "overhead_wall_pct": prof["overhead_wall_pct"],
        "died": prof["died"],
        "ok": bool(
            prof["overhead_cpu_pct"] < 1.0 and prof["died"] is None
        ),
    }
    report = {
        "stage": "saturate",
        "store_latency_us": store_lat_us,
        "scenario": {
            "levels": levels, "level_s": level_s,
            "vertices": n_vertices,
            "limit_init": int(os.environ.get("SATURATE_LIMIT_INIT", "4")),
            "aimd_threshold": float(
                os.environ.get("SATURATE_AIMD_THRESHOLD", "2.0")
            ),
            "limit_max": int(os.environ.get("SATURATE_LIMIT_MAX", "8")),
            "queue_bound": int(os.environ.get("SATURATE_QUEUE", "8")),
        },
        "pipeline": pipe_block,
        "history": history_block,
        "profiler": profiler_block,
        "flame": flame_top,
        "slo": slo_block,
        "levels": per_level,
        "peak_goodput_per_s": peak["goodput_per_s"],
        "peak_offered_concurrency": peak["offered_concurrency"],
        "saturation_offered_concurrency": knee_conc,
        "goodput_at_2x_saturation_per_s": twice["goodput_per_s"],
        "goodput_at_2x_offered_concurrency": twice["offered_concurrency"],
        "goodput_2x_over_peak": round(ratio, 4),
        "no_congestion_collapse": bool(ratio >= 0.9),
        "sheds_missing_retry_after": sheds_missing_retry_after,
        "hung_connections": hung_total,
        "brownout_transitions": brownout_events,
        "ok": bool(
            ratio >= 0.9
            and sheds_missing_retry_after == 0
            and hung_total == 0
            and history_block["ok"]
            and profiler_block["ok"]
        ),
    }
    with open(out_path + ".tmp", "w") as f:
        json.dump(report, f, indent=2)
    os.replace(out_path + ".tmp", out_path)
    report["artifact"] = out_path
    _emit(report)


def _stall_holding_frame(seconds: float) -> None:
    """The seeded stall body: a NAMED frame that holds the lock while
    sleeping, so the watchdog's owner_stack evidence can be asserted to
    name the frame that was actually holding."""
    time.sleep(seconds)


def _stall_forensics_stage(t0):
    """Seeded stall -> watchdog -> flight -> bundle (ISSUE 19
    acceptance): a seeded ``stalled_lock`` fault wedges an instrumented
    lock's owner mid-episode; the stall watchdog must flight a
    ``lock_convoy`` event whose owner_stack names the holding frame, a
    complete forensics bundle must land atomically on disk, and the
    fault journal must be byte-reproducible per seed (two runs, same
    seed, byte-compared)."""
    import shutil
    import tempfile
    import threading as _threading

    from janusgraph_tpu.observability import (
        bundle_writer, flight_recorder, sampling_profiler, watchdog,
    )
    from janusgraph_tpu.observability.continuous import InstrumentedLock
    from janusgraph_tpu.storage.faults import FaultPlan

    out_path = os.environ.get(
        "FLEET_STALL_OUT", os.path.join(_REPO_DIR, "FLEET_r04.json")
    )
    stall_ms = float(os.environ.get("STALL_FORENSICS_MS", "1200"))
    seed = int(os.environ.get("STALL_FORENSICS_SEED", "1234"))
    _BUNDLE_KEYS = {
        "reason", "ts", "pid", "flame_windows", "profiler", "flight",
        "timeseries", "stacks", "requests", "watchdog",
    }

    def _run_once(run_seed):
        """One seeded episode; returns (journal bytes, run report)."""
        flight_recorder.reset()
        sampling_profiler.reset()
        sampling_profiler.configure(hz=50.0, max_windows=64)
        sampling_profiler.start()
        watchdog.reset()
        watchdog.configure(interval_s=0.1, stall_s=0.4)
        bdir = tempfile.mkdtemp(prefix="jg-stall-bundle-")
        bundle_writer.reset()
        bundle_writer.configure(directory=bdir, min_interval_s=0.0)
        plan = FaultPlan(
            seed=run_seed, stall_lock_at=0, stall_lock_ms=stall_ms,
        )
        lk = InstrumentedLock("stall-forensics", watchdog=watchdog)
        watchdog.start()
        held_at = [0.0]

        def _holder():
            with lk:
                held_at[0] = time.monotonic()
                hold_ms = plan.stalled_lock(lock="stall-forensics")
                _stall_holding_frame(hold_ms / 1000.0)

        def _waiter():
            with lk:
                pass

        th_h = _threading.Thread(target=_holder, name="stall-holder")
        th_h.start()
        time.sleep(0.1)  # the holder must win the lock first
        th_w = _threading.Thread(target=_waiter, name="stall-waiter")
        th_w.start()
        # poll until the convoy flights (or the episode ends)
        detect_ms = None
        deadline = time.monotonic() + stall_ms / 1000.0 + 10.0
        while time.monotonic() < deadline:
            if flight_recorder.events("lock_convoy"):
                detect_ms = round(
                    (time.monotonic() - held_at[0]) * 1000.0, 1
                )
                break
            time.sleep(0.02)
        th_h.join(timeout=30.0)
        th_w.join(timeout=30.0)
        watchdog.stop()
        sampling_profiler.stop()
        convoys = flight_recorder.events("lock_convoy")
        bundle = bundle_writer.latest()
        tmp_left = [
            n for n in os.listdir(bdir) if n.endswith(".tmp")
        ]
        shutil.rmtree(bdir, ignore_errors=True)
        bundle_writer.reset()
        journal = json.dumps(plan.journal, sort_keys=True)
        names_frame = any(
            "_stall_holding_frame" in (e.get("owner_stack") or "")
            for e in convoys
        )
        run = {
            "seed": run_seed,
            "convoys_flighted": len(convoys),
            "detect_ms": detect_ms,
            "owner_stack_names_holding_frame": names_frame,
            "owner_stack": (
                convoys[0].get("owner_stack") if convoys else None
            ),
            "bundle_written": bundle is not None,
            "bundle_reason": bundle.get("reason") if bundle else None,
            "bundle_complete": bool(
                bundle and _BUNDLE_KEYS.issubset(bundle)
            ),
            "torn_tmp_files": len(tmp_left),
            "hung_threads": int(th_h.is_alive()) + int(th_w.is_alive()),
        }
        return journal, run

    j1, r1 = _run_once(seed)
    j2, r2 = _run_once(seed)
    runs = [r1, r2]
    byte_equal = j1 == j2
    detect = [r["detect_ms"] for r in runs if r["detect_ms"] is not None]
    report = {
        "stage": "fleet_stall_forensics",
        "seed": seed,
        "stall_ms": stall_ms,
        "runs": runs,
        "journal": json.loads(j1),
        "journal_bytes_equal": byte_equal,
        "detect_ms": max(detect) if detect else None,
        "ok": bool(
            byte_equal
            and all(
                r["convoys_flighted"] >= 1
                and r["owner_stack_names_holding_frame"]
                and r["bundle_complete"]
                and r["torn_tmp_files"] == 0
                and r["hung_threads"] == 0
                for r in runs
            )
        ),
    }
    _hb(
        f"stall-forensics: detect {report['detect_ms']}ms "
        f"journal-equal {byte_equal} ok {report['ok']}", t0,
    )
    with open(out_path + ".tmp", "w") as f:
        json.dump(report, f, indent=2)
    os.replace(out_path + ".tmp", out_path)
    report["artifact"] = out_path
    _emit(report)


def _fleet_push_stage(t0):
    """Streaming-telemetry push-vs-poll A/B (ISSUE 20 acceptance): one
    live replica pumps flight events at a fixed rate while (a) a
    poll-mode federation sees them only at tick boundaries — the PR 17
    freshness baseline — and (b) a push-mode federation receives them
    over a real ``/watch`` WebSocket the moment they flight. The seeded
    fault plan kills the replica mid-stream (after its forensics bundle
    is announced on the bus and shipped off-host) and restarts it; the
    renegotiated channel must resume from its flight cursor so ZERO
    pumped events are lost and none duplicate. Gates: push event p99
    <= 0.1x the poll interval, bus self-cost < 1% on both the wall and
    the CPU clock, and the dead replica's bundle still retrievable from
    ``GET /fleet/bundles``. Artifact FLEET_r05.json."""
    import shutil
    import tempfile
    import threading as _threading
    import urllib.request

    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.observability import (
        FleetFederation,
        bundle_writer,
        flight_recorder,
        telemetry_bus,
    )
    from janusgraph_tpu.observability.identity import (
        replica_name,
        set_replica,
    )
    from janusgraph_tpu.observability.timeseries import history
    from janusgraph_tpu.server import (
        FleetRouter,
        JanusGraphManager,
        JanusGraphServer,
    )
    from janusgraph_tpu.server.fleet import FleetFrontend
    from janusgraph_tpu.storage.faults import FaultPlan

    out_path = os.environ.get(
        "FLEET_PUSH_OUT", os.path.join(_REPO_DIR, "FLEET_r05.json")
    )
    poll_interval_s = float(os.environ.get("PUSH_POLL_INTERVAL_S", "0.5"))
    event_hz = float(os.environ.get("PUSH_EVENT_HZ", "25"))
    phase_s = float(os.environ.get("PUSH_PHASE_S", "6"))
    seed = int(os.environ.get("PUSH_SEED", "7"))
    kill_at = int(os.environ.get("PUSH_KILL_AT", "4"))
    restart_at = int(os.environ.get("PUSH_RESTART_AT", "8"))

    plan = FaultPlan(
        seed=seed, replica_kill_at=kill_at,
        replica_restart_at=restart_at,
    )
    flight_recorder.reset()
    flight_recorder.configure(capacity=8192)
    history.reset()
    telemetry_bus.reset()
    prev_identity = replica_name()
    set_replica("r0")
    bdir = tempfile.mkdtemp(prefix="jg-push-bundle-")

    graph = JanusGraphTPU({"ids.authority-wait-ms": 0.0})
    manager = JanusGraphManager()
    manager.put_graph("graph", graph)
    router = FleetRouter()
    servers = []

    def _start_server():
        server = JanusGraphServer(
            manager=manager, replica_name="r0", bundle_dir=bdir,
            request_timeout_s=30.0,
        ).start()
        servers.append(server)
        if "r0" in router.replicas():
            router.rejoin_replica("r0", "127.0.0.1", server.port)
            router.probe("r0")
        else:
            router.add_replica("r0", "127.0.0.1", server.port)
        return server

    # pump: one thread flighting `bench_push` events at event_hz; every
    # recorded (seq, wall-ts) pair is banked for the lag/loss accounting
    ev_lock = _threading.Lock()
    recorded = []  # (seq, wall ts)
    stop_pump = _threading.Event()

    def _pump():
        period = 1.0 / max(1e-6, event_hz)
        nxt = time.monotonic()
        while not stop_pump.is_set():
            try:
                e = flight_recorder.record("bench_push", bench=1)
                with ev_lock:
                    recorded.append((e["seq"], e["ts"]))
            except Exception:  # noqa: BLE001 - survive teardown races
                pass
            nxt += period
            time.sleep(max(0.0, nxt - time.monotonic()))

    def _pump_phase():
        stop_pump.clear()
        th = _threading.Thread(target=_pump, daemon=True)
        th.start()
        return th

    fed_poll = fed_push = frontend = None
    poll_lags = []
    push_seen = []  # (seq, lag_ms)
    push_lock = _threading.Lock()
    report = {"stage": "fleet_push_poll", "seed": seed}
    try:
        server = _start_server()
        router.probe()
        bundle_writer.reset()
        bundle_writer.configure(directory=bdir, min_interval_s=0.0)

        # ------------- phase A: poll baseline (tick-boundary freshness)
        # the poll transport cannot see an event before the tick that
        # scrapes past it completes — its freshness is the tick cadence
        fed_poll = FleetFederation(
            router, interval_s=poll_interval_s, push_enabled=False,
        )
        th = _pump_phase()
        accounted = 0
        t_end = time.monotonic() + phase_s
        while time.monotonic() < t_end:
            time.sleep(poll_interval_s)
            fed_poll.tick()
            tc = time.time()
            with ev_lock:
                fresh = [ts for _, ts in recorded[accounted:] if ts <= tc]
                accounted += len(fresh)
            poll_lags.extend(
                (tc - ts) * 1000.0 for ts in fresh  # graphlint: wallclock -- tick-boundary freshness lag over event stamps
            )
        stop_pump.set()
        th.join(timeout=10.0)
        poll_events = len(recorded)
        _hb(
            f"push-poll: poll baseline {len(poll_lags)} lag samples over "
            f"{poll_events} events", t0,
        )

        # ------------------- phase B: push transport with seeded chaos
        fed_push = FleetFederation(
            router, interval_s=poll_interval_s, push_enabled=True,
            bundle_min_interval_s=0.0,
        )
        frontend = FleetFrontend(router, federation=fed_push).start()
        orig_on_event = fed_push._on_push_event

        def _spy(channel, event):
            if str(event.get("category", "")) == "bench_push":
                ts = event.get("ts")
                lag_ms = (
                    (time.time() - float(ts)) * 1000.0  # graphlint: wallclock -- push freshness lag over event stamps (in-process: zero offset)
                    if isinstance(ts, (int, float)) else None
                )
                with push_lock:
                    push_seen.append((int(event.get("seq", 0)), lag_ms))
            orig_on_event(channel, event)

        fed_push._on_push_event = _spy
        fed_push.tick()  # negotiates the /watch channel; live from here
        if "r0" not in fed_push.push_status()["channels"]:
            raise RuntimeError("push channel failed to negotiate")

        bus0 = telemetry_bus.status()
        wall0 = time.monotonic()
        push_start = len(recorded)
        th = _pump_phase()
        outage = [None, None]  # [kill wall ts, reconnect wall ts]
        bundle_after_kill = None
        t_end = time.monotonic() + phase_s
        bucket = 0
        # the loop overruns phase_s only to let the restarted replica
        # renegotiate; the bucket cap bounds a reconnection that never
        # lands (gated as a failure below, not a hang)
        while (
            time.monotonic() < t_end or (outage[0] and not outage[1])
        ) and bucket < 64:
            time.sleep(poll_interval_s)
            for event in plan.fleet_hook(1):
                if event["kind"] == "replica_kill":
                    # the dying replica's pager announces its bundle on
                    # the bus on the way down; the push channel ships it
                    # off-host before the process is gone
                    bundle_writer.capture(reason="bench-kill", force=True)
                    ship_deadline = time.monotonic() + 10.0
                    while (
                        fed_push.bundles.get("r0") is None
                        and time.monotonic() < ship_deadline
                    ):
                        time.sleep(0.05)
                    outage[0] = time.time()
                    server.stop()
                    # crash detection: two consecutive probe misses
                    router.probe("r0")
                    router.probe("r0")
                    _hb(f"push-poll: killed r0 @bucket {bucket}", t0)
                elif event["kind"] == "replica_restart":
                    server = _start_server()
                    _hb(f"push-poll: restarted r0 @bucket {bucket}", t0)
            fed_push.tick()
            if outage[0] and not outage[1]:
                chan = fed_push.push_status()["channels"].get("r0")
                if chan and chan.get("connected"):
                    outage[1] = time.time()
            if outage[0] and bundle_after_kill is None:
                # off-host forensics endpoint, queried AFTER the death:
                # the shipped bundle must outlive its replica
                try:
                    with urllib.request.urlopen(
                        "http://127.0.0.1:%d/fleet/bundles?replica=r0"
                        % frontend.port, timeout=10,
                    ) as resp:
                        bundle_after_kill = json.loads(
                            resp.read().decode("utf-8")
                        )
                except Exception as e:  # noqa: BLE001 - a miss gates `ok`
                    bundle_after_kill = {
                        "status": f"{type(e).__name__}: {e}"[:200],
                    }
            bucket += 1
        stop_pump.set()
        th.join(timeout=10.0)
        with ev_lock:
            pushed = recorded[push_start:]
        # settle: tick until the resumed channel has replayed everything
        # the outage hid (or 10 s — a loss, gated below)
        settle_deadline = time.monotonic() + 10.0
        while time.monotonic() < settle_deadline:
            with push_lock:
                seen_set = {s for s, _ in push_seen}
            if all(s in seen_set for s, _ in pushed):
                break
            fed_push.tick()
            time.sleep(0.2)
        wall_ms = (time.monotonic() - wall0) * 1000.0
        bus1 = telemetry_bus.status()
    finally:
        stop_pump.set()
        if frontend is not None:
            frontend.stop()
        if fed_push is not None:
            fed_push.stop()
        router.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 - already stopped
                pass
        try:
            graph.close()
        except Exception:  # noqa: BLE001 - torn by the seeded kill
            pass
        bundle_writer.reset()
        telemetry_bus.reset()
        history.reset()
        flight_recorder.reset()
        set_replica(prev_identity)
        shutil.rmtree(bdir, ignore_errors=True)

    # ------------------------------------------------------- accounting
    with push_lock:
        seen = list(push_seen)
    pushed_seqs = [s for s, _ in pushed]
    seen_seqs = [s for s, _ in seen]
    seen_set = set(seen_seqs)
    lost = [s for s in pushed_seqs if s not in seen_set]
    duplicated = len(seen_seqs) - len(seen_set)
    # steady-state freshness excludes the outage window: events flighted
    # while no channel existed are REPLAYED on resume (recovery, counted
    # for loss, not for live latency)
    ts_by_seq = dict(pushed)
    outage_lo = (outage[0] - 0.1) if outage[0] else None
    outage_hi = outage[1] if outage[1] else float("inf")
    steady = [
        lag for s, lag in seen
        if lag is not None and s in ts_by_seq
        and not (
            outage_lo is not None
            and outage_lo <= ts_by_seq[s] <= outage_hi
        )
    ]
    replayed = sum(
        1 for s in pushed_seqs
        if outage_lo is not None
        and outage_lo <= ts_by_seq[s] <= outage_hi
    )

    def _p99(samples):
        if not samples:
            return float("inf")
        ss = sorted(samples)
        return round(ss[min(len(ss) - 1, int(0.99 * (len(ss) - 1)))], 3)

    poll_p99 = _p99(poll_lags)
    push_p99 = _p99(steady)
    poll_interval_ms = poll_interval_s * 1000.0
    # both self-cost clocks against elapsed wall on ONE core — the
    # sampling profiler's honest denominator (a mostly-idle process
    # makes a process-CPU denominator punish the bus for the idleness
    # around it, not for its own bill)
    bus_wall_ms = bus1["overhead_wall_ms"] - bus0["overhead_wall_ms"]
    bus_cpu_ms = bus1["overhead_cpu_ms"] - bus0["overhead_cpu_ms"]
    bus_wall_pct = bus_wall_ms / max(1e-9, wall_ms) * 100.0
    bus_cpu_pct = bus_cpu_ms / max(1e-9, wall_ms) * 100.0
    bundle_retrieved = bool(
        isinstance(bundle_after_kill, dict)
        and "status" not in bundle_after_kill
        and bundle_after_kill.get("bundle")
    )
    report.update({
        "poll_interval_ms": poll_interval_ms,
        "event_hz": event_hz,
        "phase_s": phase_s,
        "journal": plan.journal,
        "poll": {
            "events": poll_events,
            "lag_samples": len(poll_lags),
            "poll_event_p99_ms": poll_p99,
        },
        "push": {
            "events": len(pushed_seqs),
            "steady_lag_samples": len(steady),
            "replayed_through_outage": replayed,
            "events_lost": len(lost),
            "events_duplicated": duplicated,
            "outage_s": (
                round(outage[1] - outage[0], 3)  # graphlint: wallclock -- outage span over the two wall stamps bracketing it
                if outage[0] and outage[1] else None
            ),
            "push_event_p99_ms": push_p99,
        },
        "poll_event_p99_ms": poll_p99,
        "push_event_p99_ms": push_p99,
        "push_vs_poll_speedup": (
            round(poll_p99 / push_p99, 1) if push_p99 > 0 else None
        ),
        "events_lost": len(lost),
        "events_duplicated": duplicated,
        "bus_wall_overhead_ms": round(bus_wall_ms, 3),
        "bus_cpu_overhead_ms": round(bus_cpu_ms, 3),
        "bus_wall_overhead_pct": round(bus_wall_pct, 4),
        "bus_cpu_overhead_pct": round(bus_cpu_pct, 4),
        "bus_dropped": bus1["dropped"],
        "bundle_retrievable_after_kill": bundle_retrieved,
        "ok": bool(
            push_p99 <= 0.1 * poll_interval_ms
            and not lost
            and duplicated == 0
            and bus_wall_pct < 1.0
            and bus_cpu_pct < 1.0
            and bundle_retrieved
            and outage[0] is not None
            and outage[1] is not None
        ),
    })
    _hb(
        f"push-poll: push p99 {push_p99}ms vs poll p99 {poll_p99}ms "
        f"lost {len(lost)} dup {duplicated} "
        f"bus {report['bus_cpu_overhead_pct']}% cpu "
        f"ok {report['ok']}", t0,
    )
    with open(out_path + ".tmp", "w") as f:
        json.dump(report, f, indent=2)
    os.replace(out_path + ".tmp", out_path)
    report["artifact"] = out_path
    _emit(report)


def _fleet_chaos_stage(t0):
    """Fleet-level chaos certification (ISSUE 15 acceptance, extended by
    ISSUE 17): a 3-replica serving fleet over ONE shared storage backend
    takes closed-loop traffic through the consistent-hash/least-loaded
    router while the seeded fault plan kills one replica mid-traffic and
    restarts it (warm-up from the shard-checkpoint snapshot pack). The
    observability federation rides along — one tick per bucket over the
    HTTP fleet — and the artifact additionally carries the stitched
    failover forensics: the merged incident timeline (kill -> mark_dead
    -> re-pin -> warm-up phases, validated Chrome-trace document), a
    failed-over request's stitched route/attempt trace, and the scrape
    overhead gated at < 1 % of request wall."""
    import tempfile
    import threading as _threading

    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.observability import (
        FleetFederation,
        flight_recorder,
        registry,
    )
    from janusgraph_tpu.observability.identity import (
        replica_name,
        set_replica,
    )
    from janusgraph_tpu.observability.spans import tracer
    from janusgraph_tpu.observability.timeline import validate_chrome_trace
    from janusgraph_tpu.observability.timeseries import history
    from janusgraph_tpu.server import (
        FleetRouter,
        JanusGraphManager,
        JanusGraphServer,
        StateGossip,
    )
    from janusgraph_tpu.server.fleet import (
        NoReplicaAvailable,
        export_snapshot,
        warm_replica,
    )
    from janusgraph_tpu.storage.faults import FaultPlan
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    n_replicas = int(os.environ.get("FLEET_REPLICAS", "3"))
    workers = int(os.environ.get("FLEET_WORKERS", "8"))
    bucket_s = float(os.environ.get("FLEET_BUCKET_S", "0.5"))
    n_vertices = int(os.environ.get("FLEET_VERTICES", "256"))
    kill_at = int(os.environ.get("FLEET_KILL_AT", "6"))
    restart_at = int(os.environ.get("FLEET_RESTART_AT", "14"))
    n_buckets = int(os.environ.get("FLEET_BUCKETS", "24"))
    seed = int(os.environ.get("FLEET_SEED", "42"))
    out_path = os.environ.get(
        "FLEET_OUT", os.path.join(_REPO_DIR, "FLEET_r02.json")
    )

    shared = InMemoryStoreManager()
    base_cfg = {
        "ids.authority-wait-ms": 0.0,
        "locks.wait-ms": 0.0,
        "computer.delta": True,
    }
    graphs = [
        JanusGraphTPU(dict(base_cfg), store_manager=shared)
        for _ in range(n_replicas)
    ]
    graphs[0].management().make_edge_label("knows")
    tx = graphs[0].new_transaction()
    ids = [tx.add_vertex().id for _ in range(n_vertices)]
    for i in range(n_vertices):
        tx.add_edge(
            tx.get_vertex(ids[i]), "knows",
            tx.get_vertex(ids[(i * 7 + 1) % n_vertices]),
        )
    tx.commit()

    flight_recorder.reset()
    flight_recorder.configure(capacity=8192)
    # one process serves every replica port: the federation's
    # producer-keyed scrape cursor needs a non-empty shared identity to
    # merge the shared history ring exactly once
    prev_identity = replica_name()
    set_replica("fleet-proc")
    history.reset()
    # the stitched-failover evidence is ONE route span among the
    # thousands this stage generates; the default 256-root ring evicts
    # it within a bucket at this request rate
    tracer.configure(max_roots=8192)
    plan = FaultPlan(
        seed=seed, replica_kill_at=kill_at, replica_restart_at=restart_at,
    )
    router = FleetRouter(
        retry_budget_capacity=1e9, retry_budget_refill_per_s=1e9,
    )
    servers = {}
    gossips = {}

    def _start_replica(i, graph, warm_dir=None):
        if warm_dir:
            warm_replica(graph, warm_dir, replica=f"r{i}")
        manager = JanusGraphManager()
        manager.put_graph("graph", graph)
        server = JanusGraphServer(
            manager=manager, replica_name=f"r{i}",
            history_enabled=False, slo_enabled=False,
            request_timeout_s=30.0,
        ).start()
        gossip = StateGossip(f"r{i}", server.admission, timeout_s=2.0)
        server.gossip = gossip
        servers[f"r{i}"] = server
        gossips[f"r{i}"] = gossip
        if f"r{i}" in router.replicas():
            router.rejoin_replica(f"r{i}", "127.0.0.1", server.port)
            router.probe(f"r{i}")
        else:
            router.add_replica(f"r{i}", "127.0.0.1", server.port)
        return server

    for i, graph in enumerate(graphs):
        _start_replica(i, graph)
    urls = {
        name: f"http://127.0.0.1:{s.port}" for name, s in servers.items()
    }
    for name, gossip in gossips.items():
        gossip.set_peers([u for n2, u in urls.items() if n2 != name])
    router.probe()

    # the observability federation over the same HTTP fleet, ticked at
    # its production cadence (not per-bucket — the overhead this stage
    # certifies is the cadence a real frontend pays), scraping
    # /timeseries?raw=1 on every live replica. No sampler thread: the
    # driver ticks deterministically.
    fed_interval = float(os.environ.get("FLEET_FED_INTERVAL_S", "2.0"))
    tick_every = max(1, int(round(fed_interval / bucket_s)))
    federation = FleetFederation(router, interval_s=fed_interval)
    fleet_windows = []

    def _find_stitched():
        # a fleet.route span whose attempt children span >= 2 replicas:
        # the failed-over request as ONE stitched trace. Captured during
        # the run — the span ring evicts old roots under traffic.
        for root in reversed(tracer.recent("fleet.route")):
            attempts = [
                c for c in root.children if c.name == "fleet.attempt"
            ]
            replicas_tried = {
                a.attrs.get("replica") for a in attempts
            }
            if len(attempts) >= 2 and len(replicas_tried) >= 2:
                return {
                    "trace_id": f"{root.trace_id:016x}",
                    "verdict": root.attrs.get("verdict"),
                    "attempts": [
                        {
                            "replica": a.attrs.get("replica"),
                            "verdict": a.attrs.get("verdict"),
                        }
                        for a in attempts
                    ],
                }
        return None

    stop = _threading.Event()
    lock = _threading.Lock()
    counts = {"ok": 0, "errors": 0}
    bucket_ok = []  # per-bucket fleet completions
    errors_detail = []

    def _worker(widx):
        rng = widx * 131 + 7
        while not stop.is_set():
            rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
            vid = ids[rng % n_vertices]
            try:
                router.submit(
                    f"g.V({vid}).out('knows').count()",
                    deadline_ms=10_000, key=str(vid),
                )
                with lock:
                    counts["ok"] += 1
            except NoReplicaAvailable as e:
                with lock:
                    counts["errors"] += 1
                    if len(errors_detail) < 8:
                        errors_detail.append(str(e)[:200])
            except Exception as e:  # noqa: BLE001 - surfaced = failed
                with lock:
                    counts["errors"] += 1
                    if len(errors_detail) < 8:
                        errors_detail.append(
                            f"{type(e).__name__}: {e}"[:200]
                        )

    threads = [
        _threading.Thread(target=_worker, args=(w,))
        for w in range(workers)
    ]
    for th in threads:
        th.start()

    target_name = f"r{plan.replica_target(n_replicas)}"
    kill_bucket = restart_bucket = None
    lanes = []
    warm_dir = tempfile.mkdtemp(prefix="fleet_warm_")
    last_ok = 0
    incident = None
    trace_valid = False
    stitched = None
    try:
        for b in range(n_buckets):
            t_b = time.monotonic()
            # the seeded fleet fault plan decides this tick's events; the
            # driver executes them (kill = hard stop, the crash path)
            for event in plan.fleet_hook(n_replicas):
                victim = f"r{event['replica']}"
                if event["kind"] == "replica_kill":
                    kill_bucket = b
                    survivor = next(
                        g for i2, g in enumerate(graphs)
                        if f"r{i2}" != victim
                    )
                    # export the warm-up pack from a SURVIVOR before the
                    # kill lands — the restart path hydrates from it
                    export_snapshot(survivor, warm_dir, num_shards=2)
                    servers[victim].stop()
                    gossips[victim].stop()
                    _hb(f"fleet: killed {victim} @bucket {b}", t0)
                elif event["kind"] == "replica_restart":
                    restart_bucket = b
                    idx = int(event["replica"])
                    # a FRESH graph handle over the shared backend — the
                    # rejoining process — warmed from the checkpoint pack
                    graph = JanusGraphTPU(
                        dict(base_cfg), store_manager=shared
                    )
                    graphs[idx] = graph
                    _start_replica(idx, graph, warm_dir=warm_dir)
                    _hb(f"fleet: restarted {victim} @bucket {b}", t0)
            router.probe()
            time.sleep(max(0.0, bucket_s - (time.monotonic() - t_b)))
            # one history window per bucket (the producer cadence); one
            # federation tick per fed_interval (the scraper cadence)
            history.sample()
            if (b + 1) % tick_every == 0:
                fw = federation.tick()
                fleet_windows.append({
                    "seq": fw["seq"], "partial": fw["partial"],
                    "missing": fw["missing"], "outliers": fw["outliers"],
                    "replicas": fw["replicas"],
                })
            if stitched is None and kill_bucket is not None:
                stitched = _find_stitched()
            with lock:
                ok_now = counts["ok"]
            per_replica = {
                name: dict(h.stats)
                for name, h in router.replicas().items()
            }
            lanes.append({
                "bucket": b,
                "ok": ok_now - last_ok,
                "goodput_per_s": round((ok_now - last_ok) / bucket_s, 1),
                "replicas": {
                    name: {
                        "ok_total": st["ok"],
                        "shed_total": st["shed"],
                        "state": router.replicas()[name].state,
                        "brownout_rung": (
                            (router.replicas()[name].health.get(
                                "admission"
                            ) or {}).get("brownout_rung")
                        ),
                    }
                    for name, st in per_replica.items()
                },
            })
            last_ok = ok_now
        # forensics while the fleet is still up: the incident report
        # pulls every live replica's flight ring over HTTP
        incident = federation.incident(window_s=0)
        try:
            validate_chrome_trace(incident["trace"])
            trace_valid = True
        except Exception as e:  # noqa: BLE001 - recorded, gates `ok`
            trace_valid = False
            errors_detail.append(f"incident trace invalid: {e}"[:200])
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=10.0)
        hung = sum(1 for th in threads if th.is_alive())
        router.stop()
        for gossip in gossips.values():
            gossip.stop()
        for server in servers.values():
            try:
                server.stop()
            except Exception:  # noqa: BLE001 - already stopped
                pass
        for graph in graphs:
            try:
                graph.close()
            except Exception:  # noqa: BLE001 - victim graph may be torn
                pass
        set_replica(prev_identity)
        tracer.configure(max_roots=256)

    kb = kill_bucket if kill_bucket is not None else n_buckets // 4
    rb = restart_bucket if restart_bucket is not None else (
        3 * n_buckets // 4
    )
    pre = [r["goodput_per_s"] for r in lanes[1:kb]] or [0.0]
    during = [
        r["goodput_per_s"] for r in lanes[kb: min(kb + 4, len(lanes))]
    ] or [0.0]
    post = [r["goodput_per_s"] for r in lanes[rb + 1:]] or [0.0]
    pre_g = sum(pre) / len(pre)
    during_g = sum(during) / len(during)
    post_g = sum(post) / len(post)
    snap = registry.snapshot()
    failover_t = snap.get("fleet.router.failover", {})

    # ---- ISSUE 17: stitched failover trace + federation accounting ----
    if stitched is None:
        stitched = _find_stitched()
    scrape_wall_ms = float(
        snap.get("fleet.federation.scrape", {}).get("total_ms", 0.0)
        or 0.0
    )
    # the gate compares the CPU the federation consumed against the
    # request wall the fleet delivered: on this 1-core runner the
    # scrape's own wall is dominated by scheduler queueing behind the
    # saturating closed-loop workers (idle fetch: ~0.7 ms), which is
    # load the federation did not cause
    scrape_ms = float(
        snap.get("fleet.federation.scrape_cpu", {}).get("total_ms", 0.0)
        or 0.0
    )
    request_ms = float(
        snap.get("server.request.wall", {}).get("total_ms", 0.0) or 0.0
    )
    overhead_pct = (
        100.0 * scrape_ms / request_ms if request_ms else float("inf")
    )
    phases = [
        p["phase"] for p in (incident or {}).get("phases", [])
    ]
    # the failover grammar, reconstructed across rings: kill, then
    # mark_dead, then BOTH the re-pin and the warm-up (a restarting
    # replica hydrates before it rejoins the ring, so their mutual
    # order is the implementation's, not the grammar's)
    phases_ok = False
    if "kill" in phases:
        i = phases.index("kill")
        if "mark_dead" in phases[i + 1:]:
            j = phases.index("mark_dead", i + 1)
            tail = phases[j + 1:]
            phases_ok = "re_pin" in tail and "warm_up" in tail
    incident_block = None
    if incident is not None:
        incident_block = {
            "partial": incident["partial"],
            "missing": incident["missing"],
            "event_count": len(incident["events"]),
            "events": incident["events"][:200],
            "phases": incident["phases"],
            "trace_valid": trace_valid,
            "trace": incident["trace"],
        }
    federation_block = {
        "ticks": len(fleet_windows),
        "partial_windows": sum(
            1 for w in fleet_windows if w["partial"]
        ),
        "outlier_flags": sum(
            len(w["outliers"]) for w in fleet_windows
        ),
        "windows": fleet_windows,
        "offsets": federation.offsets.snapshot(),
        "scrape_cpu_total_ms": round(scrape_ms, 3),
        "scrape_wall_total_ms": round(scrape_wall_ms, 3),
        "request_wall_total_ms": round(request_ms, 1),
        "scrape_overhead_pct": round(overhead_pct, 4),
        "scrape_overhead_ok": bool(overhead_pct < 1.0),
        "slo": federation.slo.snapshot(),
    }
    report = {
        "stage": "fleet_chaos",
        "scenario": {
            "replicas": n_replicas, "workers": workers,
            "bucket_s": bucket_s, "buckets": n_buckets,
            "seed": seed, "target": target_name,
            "kill_bucket": kill_bucket, "restart_bucket": restart_bucket,
        },
        "fault_journal": plan.journal[:32],
        "lanes": lanes,
        "pre_kill_goodput_per_s": round(pre_g, 1),
        "during_kill_goodput_per_s": round(during_g, 1),
        "recovered_goodput_per_s": round(post_g, 1),
        "goodput_during_kill_over_prekill": round(
            during_g / pre_g if pre_g else 0.0, 4
        ),
        "goodput_recovered_over_prekill": round(
            post_g / pre_g if pre_g else 0.0, 4
        ),
        "failover_count": int(failover_t.get("count", 0) or 0),
        "failover_mean_ms": round(
            float(failover_t.get("mean_ms", 0.0) or 0.0), 2
        ),
        "failover_p99_ms": round(
            float(failover_t.get("p99_ms", 0.0) or 0.0), 2
        ),
        "router_retries": snap.get(
            "fleet.router.retries", {}
        ).get("count", 0),
        "replica_deaths": snap.get(
            "fleet.router.replica_deaths", {}
        ).get("count", 0),
        "warmup_hits": snap.get("fleet.warmup.hits", {}).get("count", 0),
        "errors_surfaced": counts["errors"],
        "errors_detail": errors_detail,
        "hung_connections": hung,
        "federation": federation_block,
        "incident": incident_block,
        "stitched_trace": stitched,
        "phases_ok": phases_ok,
        "ok": bool(
            during_g >= 0.6 * pre_g
            and post_g >= 0.9 * pre_g
            and counts["errors"] == 0
            and hung == 0
            and trace_valid
            and phases_ok
            and stitched is not None
            and overhead_pct < 1.0
        ),
    }
    with open(out_path + ".tmp", "w") as f:
        json.dump(report, f, indent=2)
    os.replace(out_path + ".tmp", out_path)
    report["artifact"] = out_path
    # lanes / incident events / fleet windows are bulky in the
    # heartbeat stream; emit a trimmed line
    emitted = {
        k: v for k, v in report.items()
        if k not in ("lanes", "incident", "federation")
    }
    if incident_block is not None:
        emitted["incident"] = {
            "partial": incident_block["partial"],
            "phases": incident_block["phases"],
            "event_count": incident_block["event_count"],
            "trace_valid": trace_valid,
        }
    emitted["federation"] = {
        k: v for k, v in federation_block.items()
        if k not in ("windows", "offsets", "slo")
    }
    _emit(emitted)


def _fleet_cdc_failover_stage(t0):
    """Durable-CDC leader failover certification (ISSUE 18): a leader
    replica streams every commit into the segmented CDC log
    (storage/cdc.py) while a follower replica bootstraps from a shard
    checkpoint, anchors a replay cursor at the checkpoint epoch, and
    pulls continuously; hinted reads (max-staleness) land on the
    follower while unhinted traffic stays leader-only. The seeded fault
    plan kills the leader mid-write-storm; the follower force-pulls the
    remaining records, promotes, and MUST end bitwise-identical to a
    fresh scan of the store — the property the whole log exists to
    guarantee. Gates, asserted in-stage: zero surfaced request errors,
    follower staleness bounded, byte-equal CSR after promotion, and the
    kill -> promote -> caught_up incident-phase grammar reconstructed
    by the observability federation."""
    import tempfile
    import threading as _threading

    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.observability import (
        FleetFederation,
        flight_recorder,
        registry,
    )
    from janusgraph_tpu.observability.identity import (
        replica_name,
        set_replica,
    )
    from janusgraph_tpu.olap.csr import load_csr, load_csr_snapshot
    from janusgraph_tpu.olap.sharded_checkpoint import save_csr_checkpoint
    from janusgraph_tpu.server import (
        FleetRouter,
        JanusGraphManager,
        JanusGraphServer,
    )
    from janusgraph_tpu.server.fleet import CDCFollower, NoReplicaAvailable
    from janusgraph_tpu.storage.cdc import CDCReader, LeaderCDCState
    from janusgraph_tpu.storage.faults import FaultPlan
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    workers = int(os.environ.get("FLEETCDC_WORKERS", "4"))
    bucket_s = float(os.environ.get("FLEETCDC_BUCKET_S", "0.25"))
    n_vertices = int(os.environ.get("FLEETCDC_VERTICES", "192"))
    kill_at = int(os.environ.get("FLEETCDC_KILL_AT", "8"))
    n_buckets = int(os.environ.get("FLEETCDC_BUCKETS", "20"))
    seed = int(os.environ.get("FLEETCDC_SEED", "42"))
    staleness_bound_ms = float(
        os.environ.get("FLEETCDC_STALENESS_MS", "10000")
    )
    out_path = os.environ.get(
        "FLEETCDC_OUT", os.path.join(_REPO_DIR, "FLEET_r03.json")
    )

    shared = InMemoryStoreManager()
    cdc_dir = tempfile.mkdtemp(prefix="fleet_cdc_")
    ckpt_dir = tempfile.mkdtemp(prefix="fleet_cdc_ckpt_")
    base_cfg = {
        "ids.authority-wait-ms": 0.0,
        "locks.wait-ms": 0.0,
        "computer.delta": True,
    }
    leader_cfg = dict(
        base_cfg, **{
            "storage.cdc.dir": cdc_dir,
            "storage.cdc.segment-records": 64,
        }
    )
    g_leader = JanusGraphTPU(leader_cfg, store_manager=shared)
    g_leader.management().make_edge_label("knows")
    tx = g_leader.new_transaction()
    ids = [tx.add_vertex().id for _ in range(n_vertices)]
    for i in range(n_vertices):
        tx.add_edge(
            tx.get_vertex(ids[i]), "knows",
            tx.get_vertex(ids[(i * 7 + 1) % n_vertices]),
        )
    tx.commit()
    # the follower's bootstrap pack: shard checkpoint at the seed epoch
    csr0, epoch0 = load_csr_snapshot(g_leader)
    save_csr_checkpoint(ckpt_dir, csr0, epoch0, num_shards=2)

    flight_recorder.reset()
    flight_recorder.configure(capacity=8192)
    prev_identity = replica_name()
    set_replica("fleet-proc")

    plan = FaultPlan(seed=seed, replica_kill_at=kill_at)
    # the seeded plan picks the kill target; the LEADER takes that name,
    # so the certified scenario is always leader-death, deterministically
    leader_idx = plan.replica_target(2)
    leader_name = f"r{leader_idx}"
    follower_name = f"r{1 - leader_idx}"

    g_follower = JanusGraphTPU(dict(base_cfg), store_manager=shared)
    follower = CDCFollower(
        CDCReader(cdc_dir), ckpt_dir, graph=g_follower,
        idm=g_follower.idm, name=follower_name,
        max_staleness_ms=staleness_bound_ms,
    )
    if not follower.bootstrap():
        raise RuntimeError("follower bootstrap failed")

    servers = {}

    def _start(name, graph, cdc_state):
        manager = JanusGraphManager()
        manager.put_graph("graph", graph)
        server = JanusGraphServer(
            manager=manager, replica_name=name,
            history_enabled=False, slo_enabled=False,
            request_timeout_s=30.0,
        ).start()
        server.cdc_state = cdc_state
        servers[name] = server
        return server

    _start(leader_name, g_leader, LeaderCDCState(g_leader.cdc_log))
    _start(follower_name, g_follower, follower)
    router = FleetRouter(
        retry_budget_capacity=1e9, retry_budget_refill_per_s=1e9,
    )
    for name, server in servers.items():
        router.add_replica(name, "127.0.0.1", server.port)
    router.probe()
    federation = FleetFederation(router, interval_s=bucket_s)

    stop = _threading.Event()
    writer_stop = _threading.Event()
    lock = _threading.Lock()
    counts = {"ok": 0, "errors": 0, "writes": 0}
    errors_detail = []

    def _reader(widx):
        # even workers hint a staleness budget (follower-eligible);
        # odd workers stay unhinted (leader-only by contract)
        hint = staleness_bound_ms if widx % 2 == 0 else None
        rng = widx * 131 + 7
        while not stop.is_set():
            rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
            vid = ids[rng % n_vertices]
            try:
                router.submit(
                    f"g.V({vid}).out('knows').count()",
                    deadline_ms=10_000, key=str(vid),
                    max_staleness_ms=hint,
                )
                with lock:
                    counts["ok"] += 1
            except NoReplicaAvailable as e:
                with lock:
                    counts["errors"] += 1
                    if len(errors_detail) < 8:
                        errors_detail.append(str(e)[:200])
            except Exception as e:  # noqa: BLE001 - surfaced = failed
                with lock:
                    counts["errors"] += 1
                    if len(errors_detail) < 8:
                        errors_detail.append(
                            f"{type(e).__name__}: {e}"[:200]
                        )

    def _writer():
        # the write storm: every commit lands one CDC record; the
        # leader's death interrupts this loop mid-stream
        rng = 97
        while not writer_stop.is_set():
            rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
            wtx = g_leader.new_transaction()
            for k in range(8):
                a = ids[(rng + k * 31) % n_vertices]
                b = ids[(rng + k * 53 + 1) % n_vertices]
                wtx.add_edge(
                    wtx.get_vertex(a), "knows", wtx.get_vertex(b),
                )
            wtx.commit()
            with lock:
                counts["writes"] += 1
            time.sleep(0.005)

    threads = [
        _threading.Thread(target=_reader, args=(w,)) for w in range(workers)
    ]
    wthread = _threading.Thread(target=_writer)
    for th in threads:
        th.start()
    wthread.start()

    fr_before = registry.snapshot().get(
        "fleet.router.follower_reads", {}
    ).get("count", 0)
    lanes = []
    staleness_samples = []
    kill_bucket = None
    promote_report = None
    last_ok = 0
    incident = None
    try:
        for b in range(n_buckets):
            t_b = time.monotonic()
            for event in plan.fleet_hook(2):
                if event["kind"] != "replica_kill":
                    continue
                kill_bucket = b
                # the crash path: stop the storm AND the leader, then
                # the follower promotes from the durable log alone
                writer_stop.set()
                wthread.join(timeout=10.0)
                servers[leader_name].stop()
                _hb(f"fleet-cdc: killed leader {leader_name} @b{b}", t0)
                promote_report = follower.promote()
                _hb(
                    "fleet-cdc: promoted "
                    f"{follower_name} in "
                    f"{promote_report['promote_ms']:.1f}ms "
                    f"(applied={promote_report['applied']})", t0,
                )
            router.probe()
            follower.pull()
            stale_s = follower.staleness_s()
            if stale_s != float("inf"):
                staleness_samples.append(stale_s * 1000.0)
            time.sleep(max(0.0, bucket_s - (time.monotonic() - t_b)))
            with lock:
                ok_now = counts["ok"]
            lanes.append({
                "bucket": b,
                "ok": ok_now - last_ok,
                "goodput_per_s": round((ok_now - last_ok) / bucket_s, 1),
                "staleness_ms": round(stale_s * 1000.0, 3) if (
                    stale_s != float("inf")
                ) else None,
                "follower_role": follower.role,
                "lag_records": follower.lag_records(),
            })
            last_ok = ok_now
        # the incident narrative while the survivor still serves: the
        # federation merges the live flight rings over HTTP
        incident = federation.incident(window_s=0)
    finally:
        stop.set()
        writer_stop.set()
        for th in threads:
            th.join(timeout=10.0)
        if wthread.is_alive():
            wthread.join(timeout=10.0)
        hung = sum(1 for th in threads if th.is_alive())
        router.stop()
        for server in servers.values():
            try:
                server.stop()
            except Exception:  # noqa: BLE001 - leader already dead
                pass

    # ---- the tentpole property, asserted in-stage: the promoted
    # follower's CSR is bitwise-identical to a FRESH scan of the store
    # at the same epoch (checkpoint + replayed CDC == ground truth) ----
    g_verify = JanusGraphTPU(dict(base_cfg), store_manager=shared)
    try:
        truth = load_csr(g_verify)
        fcsr = follower.csr
        bitwise_equal = all(
            (getattr(fcsr, lane) == getattr(truth, lane)).all()
            for lane in (
                "vertex_ids", "out_indptr", "in_indptr",
                "out_dst", "in_src",
            )
        )
    finally:
        g_verify.close()
        for graph in (g_leader, g_follower):
            try:
                graph.close()
            except Exception:  # noqa: BLE001 - victim graph may be torn
                pass
        set_replica(prev_identity)

    snap = registry.snapshot()
    follower_reads = int(
        snap.get("fleet.router.follower_reads", {}).get("count", 0)
        or 0
    ) - int(fr_before or 0)
    staleness_samples.sort()
    stale_p99 = (
        staleness_samples[
            min(
                len(staleness_samples) - 1,
                int(0.99 * (len(staleness_samples) - 1)),
            )
        ] if staleness_samples else float("inf")
    )
    phases = [p["phase"] for p in (incident or {}).get("phases", [])]
    # the failover grammar this stage certifies: kill, then promote,
    # then the promoted replica proves itself caught up
    phases_ok = False
    if "kill" in phases:
        i = phases.index("kill")
        tail = phases[i + 1:]
        phases_ok = "promote" in tail and "caught_up" in tail
    report = {
        "stage": "fleet_cdc_failover",
        "scenario": {
            "workers": workers, "bucket_s": bucket_s,
            "buckets": n_buckets, "seed": seed,
            "leader": leader_name, "follower": follower_name,
            "kill_bucket": kill_bucket, "vertices": n_vertices,
            "staleness_bound_ms": staleness_bound_ms,
        },
        "fault_journal": plan.journal[:32],
        "lanes": lanes,
        "writes_committed": counts["writes"],
        "cdc": follower.healthz_block(),
        "promote_ms": round(
            float(promote_report["promote_ms"]), 2
        ) if promote_report else None,
        "promote_applied": (
            promote_report["applied"] if promote_report else None
        ),
        "staleness_p99_ms": round(stale_p99, 3) if (
            stale_p99 != float("inf")
        ) else None,
        "follower_reads": follower_reads,
        "follower_read_share": round(
            follower_reads / counts["ok"] if counts["ok"] else 0.0, 4
        ),
        "rebootstraps": follower.rebootstraps,
        "bitwise_equal": bool(bitwise_equal),
        "errors_surfaced": counts["errors"],
        "errors_detail": errors_detail,
        "hung_connections": hung,
        "phases": (incident or {}).get("phases", []),
        "phases_ok": phases_ok,
        "ok": bool(
            counts["errors"] == 0
            and hung == 0
            and bitwise_equal
            and promote_report is not None
            and promote_report.get("ok")
            and stale_p99 <= staleness_bound_ms
            and phases_ok
        ),
    }
    with open(out_path + ".tmp", "w") as f:
        json.dump(report, f, indent=2)
    os.replace(out_path + ".tmp", out_path)
    report["artifact"] = out_path
    emitted = {k: v for k, v in report.items() if k != "lanes"}
    _emit(emitted)


def _oltp_stage(t0):
    """OLTP throughput micro-bench (VERDICT r4 #7): tx-path batched addEdge
    commits/s and multiQuery reads/s on the inmemory and remote backends.
    The reference publishes no OLTP numbers (SURVEY §6) — this establishes
    the framework's own regression baseline. Reference hot loops:
    StandardJanusGraph.java:674-830 (commit), StandardJanusGraphTx.java:1118
    (multiQuery). Host-side pure-Python: platform-independent, so it runs
    on the CPU fallback too."""
    import numpy as np

    from janusgraph_tpu.core.codecs import Direction
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.olap.generators import rmat_csr

    scale = int(os.environ.get("BENCH_OLTP_SCALE", "16"))
    edge_cap = int(os.environ.get("BENCH_OLTP_EDGE_CAP", "100000"))
    batch = 5000
    csr = rmat_csr(scale, 16)
    src = np.repeat(
        np.arange(csr.num_vertices), np.diff(csr.out_indptr)
    )[:edge_cap]
    dst = csr.out_dst[:edge_cap]

    def _measure(backend_name, cfg):
        # per-backend store latency histograms attach to the stage line
        # (reset between backends so the snapshots don't mix)
        from janusgraph_tpu.util.metrics import metrics as _reg

        _reg.reset()
        cfg = dict(cfg, **{"metrics.enabled": True})
        g = open_graph(cfg)
        g.management().make_edge_label("knows")
        v0 = time.perf_counter()
        tx = g.new_transaction()
        ids = [tx.add_vertex().id for _ in range(csr.num_vertices)]
        tx.commit()
        vertex_s = time.perf_counter() - v0

        e0 = time.perf_counter()
        commits = 0
        pending = 0
        tx = g.new_transaction()
        for i in range(len(src)):
            sv = tx.get_vertex(ids[src[i]])
            dv = tx.get_vertex(ids[dst[i]])
            tx.add_edge(sv, "knows", dv)
            pending += 1
            if pending == batch:
                tx.commit()
                commits += 1
                pending = 0
                tx = g.new_transaction()
        if pending:
            tx.commit()
            commits += 1
        else:
            tx.rollback()
        edge_s = time.perf_counter() - e0

        rng = np.random.default_rng(0)
        sample = rng.choice(ids, size=2000, replace=False)
        q0 = time.perf_counter()
        tx = g.new_transaction()
        vs = [tx.get_vertex(int(i)) for i in sample]
        tx.prefetch(vs, Direction.OUT, ("knows",))  # the multiQuery batch
        edges_read = 0
        for v in vs:
            edges_read += sum(
                1 for _ in tx.get_edges(v, Direction.OUT, ("knows",))
            )
        query_s = time.perf_counter() - q0
        tx.rollback()

        # traversal burst through the DSL so the query-digest table has
        # shapes to rank: three distinct shapes, many literals each — the
        # top-3 digests attach to this stage's artifact line
        from janusgraph_tpu.observability.profiler import digest_table

        digest_table.reset()
        src_g = g.traversal()
        for vid in sample[:40]:
            src_g.V(int(vid)).out("knows").count()
        for vid in sample[:20]:
            src_g.V(int(vid)).out("knows").out("knows").count()
        for vid in sample[:10]:
            src_g.V(int(vid)).both("knows").limit(5).to_list()
        src_g.tx.rollback()
        g.close()
        store_hists = {
            name: {
                "count": m["count"],
                "p50_ms": round(m["p50_ms"], 4),
                "p95_ms": round(m["p95_ms"], 4),
                "p99_ms": round(m["p99_ms"], 4),
                "total_ms": round(m["total_ms"], 2),
            }
            for name, m in _reg.snapshot().items()
            if m["type"] == "timer" and name.startswith(("storage.", "tx."))
        }
        line = {
            "stage": "oltp", "backend": backend_name, "scale": scale,
            "vertices": csr.num_vertices, "edges_written": len(src),
            "commit_batch": batch,
            "add_vertex_per_s": round(csr.num_vertices / vertex_s, 1),
            "add_edge_per_s": round(len(src) / edge_s, 1),
            "commits_per_s": round(commits / edge_s, 2),
            "multiquery_vertices_per_s": round(len(vs) / query_s, 1),
            "multiquery_edges_read": edges_read,
            # top-3 query digests by total cost (shape, count, total/p50/
            # p95 wall, cells) from the traversal burst above
            "telemetry": {
                "store_histograms": store_hists,
                "query_digests": digest_table.top(3),
            },
        }
        _hb(
            f"oltp[{backend_name}]: {line['add_edge_per_s']:.0f} addEdge/s "
            f"{line['commits_per_s']:.1f} commits/s "
            f"{line['multiquery_vertices_per_s']:.0f} mq-vertices/s", t0,
        )
        _emit(line)

    _measure("inmemory", {"storage.backend": "inmemory"})

    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import RemoteStoreServer

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    try:
        _measure("remote", {
            "storage.backend": "remote",
            "storage.hostname": host,
            "storage.port": port,
        })
    finally:
        server.stop()


class _LatencyStore:
    """Per-op simulated storage-node service time: every KCVS call pays
    a fixed sleep (media + replication + fabric RTT of a REAL storage
    node — the loopback in-process server otherwise answers in ~30 us,
    which no deployed Cassandra/HBase-class backend does). The sleep
    releases the GIL exactly like real socket/disk waits."""

    def __init__(self, inner, lat_s):
        self._inner = inner
        self._lat_s = lat_s

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def get_slice(self, *a, **k):
        time.sleep(self._lat_s)
        return self._inner.get_slice(*a, **k)

    def get_slice_multi(self, *a, **k):
        time.sleep(self._lat_s)
        return self._inner.get_slice_multi(*a, **k)

    def mutate(self, *a, **k):
        time.sleep(self._lat_s)
        return self._inner.mutate(*a, **k)


class _LatencyManager:
    def __init__(self, inner, lat_s):
        self._inner = inner
        self._lat_s = lat_s

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def open_database(self, name):
        return _LatencyStore(self._inner.open_database(name), self._lat_s)

    def mutate_many(self, *a, **k):
        time.sleep(self._lat_s)
        return self._inner.mutate_many(*a, **k)


def _oltp_spillover_stage(t0):
    """OLTP->OLAP spillover A/B (ISSUE 12 acceptance): a burst of 2/3/4-hop
    ``g.V(seeds).out('knows')^h.count()`` traversals at s16, step-walk
    (planner disabled) vs spilled (promoted onto the OLAP executor over
    the cached CSR snapshot), median of 3 timed runs each after warmup.
    Results are asserted set-equal in-stage (count AND the dedup'd
    endpoint-id set), the promotion trace rides the artifact line, and
    every cell appends to bench_artifacts/r9_spillover_ab_<ts>.jsonl."""
    import statistics as _stats

    import numpy as np

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.observability import registry
    from janusgraph_tpu.observability.profiler import digest_table
    from janusgraph_tpu.olap.generators import rmat_csr

    scale = int(os.environ.get("BENCH_SPILLOVER_SCALE", "16"))
    edge_cap = int(os.environ.get("BENCH_SPILLOVER_EDGES", "400000"))
    n_seeds = int(os.environ.get("BENCH_SPILLOVER_SEEDS", "24"))
    batch = 10_000
    csr = rmat_csr(scale, 16)
    src = np.repeat(
        np.arange(csr.num_vertices), np.diff(csr.out_indptr)
    )[:edge_cap]
    dst = csr.out_dst[:edge_cap]

    digest_table.reset()
    g = open_graph({
        "storage.backend": "inmemory",
        "computer.spillover": True,
        "computer.spillover-min-cost-ms": float(
            os.environ.get("BENCH_SPILLOVER_MIN_COST_MS", "5")
        ),
        "computer.spillover-min-seen": 2,
    })
    g.management().make_edge_label("knows")
    b0 = time.perf_counter()
    tx = g.new_transaction()
    ids = [tx.add_vertex().id for _ in range(csr.num_vertices)]
    tx.commit()
    tx = g.new_transaction()
    pending = 0
    for i in range(len(src)):
        sv = tx.get_vertex(ids[src[i]])
        dv = tx.get_vertex(ids[dst[i]])
        tx.add_edge(sv, "knows", dv)
        pending += 1
        if pending == batch:
            tx.commit()
            pending = 0
            tx = g.new_transaction()
    if pending:
        tx.commit()
    else:
        tx.rollback()
    build_s = time.perf_counter() - b0
    _hb(
        f"oltp_spillover: built s{scale} graph ({csr.num_vertices} v, "
        f"{len(src)} e) in {build_s:.1f}s", t0,
    )

    rng = np.random.default_rng(7)
    # seed selection: moderate-fanout vertices whose 4-hop traverser
    # total (computed host-side with the same count recurrence the
    # spilled program runs) stays within the per-query traverser budget
    # — RMAT hubs explode a 2-hop walk past query.max-traversers
    deg = np.bincount(src, minlength=csr.num_vertices)
    candidates = rng.permutation(
        np.nonzero((deg >= 2) & (deg <= 32))[0]
    )
    seeds = []
    budget4 = 0.0
    for v in candidates:
        c = np.zeros(csr.num_vertices)
        c[int(v)] = 1.0
        totals = []
        for _ in range(4):
            c = np.bincount(
                dst, weights=c[src], minlength=csr.num_vertices
            )
            totals.append(c.sum())
        # per-seed AND whole-burst 4-hop budget: the step walk
        # materializes every traverser, and the burst must stay inside
        # query.max-traversers at the deepest cell
        if totals[2] >= 200 and totals[3] <= 120_000 and (
            budget4 + totals[3] <= 800_000
        ):
            seeds.append(ids[int(v)])
            budget4 += totals[3]
        if len(seeds) >= n_seeds:
            break
    planner = g.spillover_planner

    # the burst: the recurring multi-seed shape — re-running it is what
    # gives the digest table the repetitions the promotion policy needs
    def _burst_count(hops):
        t = g.traversal().V(*seeds)
        for _ in range(hops):
            t = t.out("knows")
        return t.count()

    def _burst_ids(hops):
        t = g.traversal().V(*seeds)
        for _ in range(hops):
            t = t.out("knows")
        return sorted(t.dedup().id_().to_list())

    def _spill_count():
        return registry.snapshot().get(
            "olap.spillover.spilled", {}
        ).get("count", 0)

    ts = time.strftime("%Y%m%d-%H%M%S")
    art_dir = os.path.join(_REPO_DIR, "bench_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art_path = os.path.join(art_dir, f"r9_spillover_ab_{ts}.jsonl")
    cells = []
    promotion_trace = []
    with open(art_path, "a") as art:
        for hops in (2, 3, 4):
            # A: the step-by-step walk (planner off). These runs also
            # feed the digest table the measured mean cost the promotion
            # policy prices the shape from.
            planner.enabled = False
            _burst_count(hops)  # warm row caches
            walk_walls = []
            for _ in range(3):
                w0 = time.perf_counter()
                walk_total = _burst_count(hops)
                walk_walls.append((time.perf_counter() - w0) * 1e3)
            walk_ids = _burst_ids(hops)
            # B: spilled. The first promoted run pays the one-time CSR
            # pack + compile (recorded as warmup), steady-state timed.
            planner.enabled = True
            before = _spill_count()
            p0 = time.perf_counter()
            _burst_count(hops)  # promotion run (count >= min-seen now)
            warm_ms = (time.perf_counter() - p0) * 1e3
            spilled_engaged = _spill_count() > before
            spill_walls = []
            for _ in range(3):
                w0 = time.perf_counter()
                spill_total = _burst_count(hops)
                spill_walls.append((time.perf_counter() - w0) * 1e3)
            _burst_ids(hops)  # brings the id-shape past min-seen
            spill_ids = _burst_ids(hops)
            promotion_trace = [
                {"digest": d, **s}
                for d, s in sorted(planner.promotion_snapshot().items())
            ]
            walk_ms = _stats.median(walk_walls)
            spill_ms = _stats.median(spill_walls)
            set_equal = (
                walk_total == spill_total and walk_ids == spill_ids
            )
            assert set_equal, (
                f"spillover A/B mismatch at {hops} hops: "
                f"walk {walk_total}/{len(walk_ids)} distinct vs "
                f"spilled {spill_total}/{len(spill_ids)} distinct"
            )
            cell = {
                "hops": hops,
                "seeds": len(seeds),
                "traversers": walk_total,
                "distinct_endpoints": len(walk_ids),
                "walk_ms": [round(w, 2) for w in walk_walls],
                "walk_median_ms": round(walk_ms, 2),
                "spill_warmup_ms": round(warm_ms, 2),
                "spill_ms": [round(w, 2) for w in spill_walls],
                "spill_median_ms": round(spill_ms, 2),
                "speedup": round(walk_ms / spill_ms, 2) if spill_ms else None,
                "spilled_engaged": spilled_engaged,
                "set_equal": set_equal,
            }
            cells.append(cell)
            art.write(json.dumps({
                "stage": "oltp_spillover", "scale": scale, **cell,
            }) + "\n")
            art.flush()
            _hb(
                f"oltp_spillover@{hops}hop: walk {walk_ms:.0f}ms vs "
                f"spilled {spill_ms:.1f}ms ({cell['speedup']}x, "
                f"{walk_total} traversers)", t0,
            )
    three = next(c for c in cells if c["hops"] == 3)
    line = {
        "stage": "oltp_spillover",
        "scale": scale,
        "vertices": csr.num_vertices,
        "edges": len(src),
        "build_s": round(build_s, 1),
        "cells": cells,
        "promotion_trace": promotion_trace,
        "spillover_counters": {
            name[len("olap.spillover."):]: m["count"]
            for name, m in registry.snapshot().items()
            if m["type"] == "counter"
            and name.startswith("olap.spillover.")
            and "." not in name[len("olap.spillover."):]
        },
        "artifact": os.path.relpath(art_path, _REPO_DIR),
        "accept_3x": bool(
            three["speedup"] and three["speedup"] >= 3.0
            and three["set_equal"] and three["spilled_engaged"]
        ),
    }
    g.close()
    _emit(line)
    _hb(
        f"oltp_spillover: 3-hop {three['speedup']}x "
        f"(>=3x: {line['accept_3x']})", t0,
    )


def _streaming_freshness_stage(t0):
    """Streaming freshness A/B (ISSUE 14 acceptance): sustained write
    bursts against a store-backed graph while a rolling PageRank keeps
    running over the snapshot. Per round: commit a bounded burst
    (<= 1% of edges), refresh the snapshot via the delta capture
    (zero store reads, olap/delta.materialize) AND via a full
    scan+repack (load_csr_snapshot), and assert the two are
    array-for-array identical — which makes every superstep over the
    refreshed arrays bitwise-identical to the repacked CSR by
    construction (additionally asserted by running PageRank on both).
    Round 1 also runs a FUSED cell: the overlay consumed superstep-side
    (base pack untouched), CC bitwise vs repack per the MIN contract.
    Reports refresh-vs-repack latency, write throughput, and the
    staleness window per round; acceptance: refresh >= 10x faster than
    the repack at <= 1% churn."""
    import statistics as _stats

    import numpy as np

    from janusgraph_tpu.core.bulk import bulk_add_edges, bulk_add_vertices
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.olap import delta as _delta
    from janusgraph_tpu.olap.csr import load_csr_snapshot
    from janusgraph_tpu.olap.programs import PageRankProgram
    from janusgraph_tpu.olap.programs.connected_components import (
        ConnectedComponentsProgram,
    )
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor
    from janusgraph_tpu.observability import registry

    scale = int(os.environ.get("BENCH_STREAM_SCALE", "20"))
    edge_cap = int(os.environ.get("BENCH_STREAM_EDGES", "2000000"))
    rounds = int(os.environ.get("BENCH_STREAM_ROUNDS", "4"))
    burst_frac = float(os.environ.get("BENCH_STREAM_BURST", "0.005"))
    pr_iters = int(os.environ.get("BENCH_STREAM_PR_ITERS", "5"))

    base_csr = _cached_rmat_csr(scale, 16, t0)
    n = base_csr.num_vertices
    src = np.repeat(
        np.arange(n), np.diff(base_csr.out_indptr)
    )[:edge_cap]
    dst = np.asarray(base_csr.out_dst[:edge_cap], dtype=np.int64)
    g = open_graph({
        "storage.backend": "inmemory",
        "computer.delta-capture-limit": 1 << 20,
    })
    b0 = time.perf_counter()
    vids = bulk_add_vertices(g, n)
    bulk_add_edges(g, "link", vids[src], vids[dst])
    build_s = time.perf_counter() - b0
    _hb(
        f"streaming_freshness: seeded s{scale} store graph "
        f"({n} v, {len(src)} e) in {build_s:.1f}s", t0,
    )

    p0 = time.perf_counter()
    csr, epoch = load_csr_snapshot(g)
    pack0_s = time.perf_counter() - p0
    _hb(f"streaming_freshness: initial pack {pack0_s:.2f}s", t0)

    rng = np.random.default_rng(14)
    burst = max(1, int(burst_frac * len(src)))
    ts = time.strftime("%Y%m%d-%H%M%S")
    art_dir = os.path.join(_REPO_DIR, "bench_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art_path = os.path.join(art_dir, f"r11_stream_{ts}.jsonl")
    cells = []
    with open(art_path, "a") as art:
        for rnd in range(rounds):
            # -- bounded write burst (bulk columnar adds; the capture
            # decodes each committed batch vectorized)
            w0 = time.perf_counter()
            bs = rng.integers(0, n, burst)
            bd = rng.integers(0, n, burst)
            bulk_add_edges(g, "link", vids[bs], vids[bd])
            write_s = time.perf_counter() - w0
            burst_epoch_t = time.perf_counter()

            # -- A: O(delta) refresh from the capture, zero store reads
            r0 = time.perf_counter()
            got = _delta.overlay_since(g, epoch)
            assert got is not None, "capture overflowed mid-bench"
            ov, upto = got
            view = _delta.OverlayView(csr, ov, max_lane_cells=1 << 22)
            refreshed = _delta.materialize(csr, ov, idm=g.idm)
            refresh_ms = (time.perf_counter() - r0) * 1e3
            staleness_ms = (time.perf_counter() - burst_epoch_t) * 1e3
            depth = ov.size
            registry.set_gauge("olap.delta.overlay_depth", float(depth))

            # -- B: the full scan + repack the delta path replaces
            k0 = time.perf_counter()
            repack, repack_epoch = load_csr_snapshot(g)
            repack_ms = (time.perf_counter() - k0) * 1e3

            # refreshed arrays must BE the repacked arrays — then every
            # superstep over them is bitwise-identical by construction
            arrays_identical = all(
                np.array_equal(getattr(refreshed, f), getattr(repack, f))
                for f in (
                    "vertex_ids", "out_indptr", "out_dst",
                    "in_indptr", "in_src",
                )
            )
            assert arrays_identical, "delta refresh diverged from repack"
            # rolling PageRank over the fresh snapshot, asserted bitwise
            # against the repacked CSR in-stage
            pr_f = TPUExecutor(refreshed, strategy="ell").run(
                PageRankProgram(max_iterations=pr_iters)
            )
            pr_r = TPUExecutor(repack, strategy="ell").run(
                PageRankProgram(max_iterations=pr_iters)
            )
            pr_bitwise = bool(
                np.array_equal(pr_f["rank"], pr_r["rank"])
            )
            assert pr_bitwise, "refreshed PageRank diverged from repack"

            fused_cell = None
            if rnd == 0:
                # fused cell: the overlay consumed superstep-side over
                # the UNTOUCHED base pack; MIN family bitwise vs repack
                f0 = time.perf_counter()
                cc_f = TPUExecutor(csr, strategy="ell", delta=view).run(
                    ConnectedComponentsProgram(max_iterations=20)
                )
                fused_wall_ms = (time.perf_counter() - f0) * 1e3
                cc_r = TPUExecutor(repack, strategy="ell").run(
                    ConnectedComponentsProgram(max_iterations=20),
                    frontier="off",
                )
                fused_cell = {
                    "cc_bitwise": bool(np.array_equal(
                        np.asarray(cc_f["component"]),
                        np.asarray(cc_r["component"]),
                    )),
                    "wall_ms": round(fused_wall_ms, 1),
                    "lane_cells": int(sum(
                        view.lanes(True)["_meta"][k]
                        for k in ("acap", "tcap", "lcap")
                    )),
                }
                assert fused_cell["cc_bitwise"], (
                    "fused CC diverged from repack"
                )

            csr, epoch = refreshed, upto
            cell = {
                "round": rnd,
                "burst_edges": int(burst),
                "writes_per_s": round(burst / max(write_s, 1e-9), 1),
                "overlay_depth": int(depth),
                "refresh_ms": round(refresh_ms, 2),
                "repack_ms": round(repack_ms, 2),
                "speedup": round(repack_ms / max(refresh_ms, 1e-9), 2),
                "staleness_window_ms": round(staleness_ms, 2),
                "arrays_identical": arrays_identical,
                "pagerank_bitwise": pr_bitwise,
                "fused": fused_cell,
            }
            cells.append(cell)
            art.write(json.dumps({
                "stage": "streaming_freshness", "scale": scale, **cell,
            }) + "\n")
            art.flush()
            _hb(
                f"streaming_freshness r{rnd}: refresh "
                f"{refresh_ms:.0f}ms vs repack {repack_ms:.0f}ms "
                f"({cell['speedup']}x), {depth} records", t0,
            )
    med_refresh = _stats.median(c["refresh_ms"] for c in cells)
    med_repack = _stats.median(c["repack_ms"] for c in cells)
    speedup = med_repack / max(med_refresh, 1e-9)
    line = {
        "stage": "streaming_freshness",
        "scale": scale,
        "vertices": n,
        "edges": len(src),
        "burst_fraction": burst_frac,
        "build_s": round(build_s, 1),
        "initial_pack_s": round(pack0_s, 2),
        "cells": cells,
        "refresh_median_ms": round(med_refresh, 2),
        "repack_median_ms": round(med_repack, 2),
        "refresh_speedup": round(speedup, 2),
        "writes_per_s": round(
            _stats.median(c["writes_per_s"] for c in cells), 1
        ),
        "staleness_window_ms": round(
            _stats.median(c["staleness_window_ms"] for c in cells), 2
        ),
        "delta_counters": {
            name[len("olap.delta."):]: m.get("count", m.get("value"))
            for name, m in registry.snapshot().items()
            if name.startswith("olap.delta.")
        },
        "artifact": os.path.relpath(art_path, _REPO_DIR),
        "accept_10x": bool(
            speedup >= 10.0
            and all(c["arrays_identical"] for c in cells)
            and all(c["pagerank_bitwise"] for c in cells)
        ),
    }
    g.close()
    _emit(line)
    _hb(
        f"streaming_freshness: refresh {speedup:.1f}x faster than "
        f"repack (>=10x: {line['accept_10x']})", t0,
    )


def _oltp_pipeline_stage(t0):
    """Pipelined-vs-synchronous wire framing A/B (ISSUE 11 acceptance):
    a closed-loop multiquery workload (per iteration: one existence-
    probe getSlice, one mutate, and every 8th iteration a 16-key
    multi-slice prefetch) against a remote KCVS server, swept over
    offered in-flight depth (worker threads) at a simulated storage-node
    service time. The synchronous baseline is the PR 1 framing
    (pipeline=False) at the default 4-connection pool; the pipelined
    path multiplexes every in-flight op over 2 sockets. Each level
    records achieved throughput, wire frames/op, coalesce ratio, and
    in-flight depth. Zero-latency cells ride along for transparency:
    in-process loopback on this host is GIL-bound, so the adaptive gate
    keeps the sync path there (~1.0x by design)."""
    import threading as _threading

    from janusgraph_tpu.observability import registry
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    lat_us = float(os.environ.get("BENCH_PIPE_LAT_US", "2000"))
    depths = [
        int(x) for x in os.environ.get(
            "BENCH_PIPE_DEPTHS", "1,8,16,32,64"
        ).split(",")
    ]
    iters = int(os.environ.get("BENCH_PIPE_ITERS", "40"))

    def _measure(pipeline, nthreads, lat_s, iters_n, with_multi=False):
        registry.reset()
        backing = InMemoryStoreManager()
        server = RemoteStoreServer(
            _LatencyManager(backing, lat_s) if lat_s else backing,
            pipeline_workers=64,
        ).start()
        mgr = RemoteStoreManager(*server.address, pipeline=pipeline)
        store = mgr.open_database("edgestore")
        seed_keys = [f"seed{i:03d}".encode() for i in range(64)]
        for k in seed_keys:
            store.mutate(k, [(b"c", b"v")], [], None)
        # warm-up outside the timed window: dials the sockets, settles
        # the adaptive gate's service-time EWMA, and (pipelined) brings
        # the mux out of its negotiation bootstrap — both paths equally
        if nthreads > 1:
            warm = [
                _threading.Thread(
                    target=lambda i=i: [
                        store.get_slice(
                            KeySliceQuery(
                                seed_keys[i % 64], SliceQuery(b"", None)
                            ), None,
                        ) for _ in range(6)
                    ],
                )
                for i in range(nthreads)
            ]
            for th in warm:
                th.start()
            for th in warm:
                th.join()
        errs = []
        ops_done = [0]

        def worker(i):
            n = 0
            try:
                for j in range(iters_n):
                    if with_multi:
                        # prefetch shape: one 16-key multiQuery batch —
                        # ALREADY amortized on the wire, so both framings
                        # pay ~one service time per batch (recorded for
                        # transparency; expect ~1x)
                        res = store.get_slice_multi(
                            seed_keys[:16], SliceQuery(b"", None), None
                        )
                        assert len(res) == 16
                        n += 16
                        continue
                    # per-op stream: the existence-probe getSlice and
                    # point mutate — the one-op-per-roundtrip traffic
                    # the pipelined framing exists to batch
                    k = f"w{i}-{j:03d}".encode()
                    store.mutate(k, [(b"c", b"v")], [], None)
                    got = store.get_slice(
                        KeySliceQuery(k, SliceQuery(b"", None)), None
                    )
                    assert got == [(b"c", b"v")]
                    n += 2
            except Exception as e:  # noqa: BLE001 - surfaced in the line
                errs.append(f"{type(e).__name__}: {e}")
            ops_done[0] += n

        threads = [
            _threading.Thread(target=worker, args=(i,))
            for i in range(nthreads)
        ]
        stop_sampler = _threading.Event()
        inflight_samples = []

        def _sampler():
            while not stop_sampler.is_set():
                mux = mgr._mux
                if mux is not None:
                    inflight_samples.append(mux.in_flight())
                stop_sampler.wait(0.01)

        sampler = _threading.Thread(target=_sampler, daemon=True)
        w0 = time.perf_counter()
        for th in threads:
            th.start()
        sampler.start()
        for th in threads:
            th.join()
        stop_sampler.set()
        sampler.join(timeout=1.0)
        wall = time.perf_counter() - w0
        if mgr._mux is not None:
            mgr._mux.flush_stats()
        snap = registry.snapshot()

        def _cnt(name):
            return snap.get(name, {}).get("count", 0)

        p_ops = _cnt("storage.remote.pipeline.ops")
        frames = _cnt("storage.remote.pipeline.wire_frames")
        mgr.close()
        server.stop()
        return {
            "ops_per_s": round(ops_done[0] / wall, 1),
            "wall_s": round(wall, 3),
            "ops": ops_done[0],
            "pipelined_ops": p_ops,
            "wire_frames": frames,
            "frames_per_op": round(frames / p_ops, 3) if p_ops else None,
            "coalesce_ratio": round(p_ops / frames, 3) if frames else None,
            "in_flight_peak": max(inflight_samples, default=0),
            "in_flight_mean": round(
                sum(inflight_samples) / len(inflight_samples), 1
            ) if inflight_samples else 0,
            "errors": errs[:3],
        }

    levels = []
    for depth in depths:
        sync = _measure(False, depth, lat_us / 1e6, iters)
        pipe = _measure(True, depth, lat_us / 1e6, iters)
        if depth == depths[-1]:
            # one repetition pass on the acceptance cell: medians, not
            # single lucky runs (1-core host, noisy neighbors)
            import statistics as _stats

            sync_reps = [sync["ops_per_s"]] + [
                _measure(False, depth, lat_us / 1e6, iters)["ops_per_s"]
                for _ in range(2)
            ]
            pipe_reps = [pipe["ops_per_s"]] + [
                _measure(True, depth, lat_us / 1e6, iters)["ops_per_s"]
                for _ in range(2)
            ]
            sync["ops_per_s"] = round(_stats.median(sync_reps), 1)
            pipe["ops_per_s"] = round(_stats.median(pipe_reps), 1)
            sync["reps"] = [round(v, 1) for v in sync_reps]
            pipe["reps"] = [round(v, 1) for v in pipe_reps]
        speedup = (
            pipe["ops_per_s"] / sync["ops_per_s"]
            if sync["ops_per_s"] else None
        )
        levels.append({
            "offered_depth": depth,
            "sync": sync,
            "pipelined": pipe,
            "speedup": round(speedup, 3) if speedup else None,
        })
        _hb(
            f"oltp_pipeline@depth={depth}: sync {sync['ops_per_s']:.0f} "
            f"vs pipelined {pipe['ops_per_s']:.0f} ops/s "
            f"({speedup:.2f}x, coalesce "
            f"{pipe['coalesce_ratio']})", t0,
        )
    # transparency cells: (a) loopback zero latency — the adaptive gate
    # keeps the sync path (ratio ~1.0 by design on a GIL-bound host);
    # (b) the prefetch/multiQuery batch shape — already amortized on the
    # wire, both framings pay ~one service time per 16-key batch
    z_sync = _measure(False, 16, 0.0, iters)
    z_pipe = _measure(True, 16, 0.0, iters)
    m_sync = _measure(False, 16, lat_us / 1e6, 12, with_multi=True)
    m_pipe = _measure(True, 16, lat_us / 1e6, 12, with_multi=True)
    best = max(levels, key=lambda r: r["speedup"] or 0)
    line = {
        "stage": "oltp_pipeline",
        "storage_latency_us": lat_us,
        "iters_per_thread": iters,
        "pipeline_defaults": {
            "connections": 2, "depth": 128, "max_batch": 64,
            "coalesce_us": 150.0, "sync_pool_size": 4,
        },
        "depth_sweep": levels,
        "zero_latency": {
            "sync": z_sync, "pipelined": z_pipe,
            "ratio": round(
                z_pipe["ops_per_s"] / z_sync["ops_per_s"], 3
            ) if z_sync["ops_per_s"] else None,
        },
        "prefetch_batch_cell": {
            "sync": m_sync, "pipelined": m_pipe,
            "ratio": round(
                m_pipe["ops_per_s"] / m_sync["ops_per_s"], 3
            ) if m_sync["ops_per_s"] else None,
            "note": "16-key multiQuery batches are already amortized "
                    "on the wire; pipelining targets the per-op stream",
        },
        "peak_speedup": best["speedup"],
        "peak_offered_depth": best["offered_depth"],
        "accept_3x": bool(best["speedup"] and best["speedup"] >= 3.0),
    }
    _emit(line)
    _hb(
        f"oltp_pipeline: peak {best['speedup']:.2f}x at depth "
        f"{best['offered_depth']} (>=3x: {line['accept_3x']})", t0,
    )


def _pallas_stage(jax, pr_iters, t0):
    import numpy as np

    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    csr = rmat_csr(16, 16)
    prog = PageRankProgram(max_iterations=pr_iters, tol=0.0)
    res = {}
    times = {}
    for strat in ("ell", "pallas"):
        ex = TPUExecutor(csr, strategy=strat)
        ex.run(prog)
        r0 = time.perf_counter()
        out = ex.run(prog, sync_every=pr_iters)
        jax.block_until_ready(out["rank"])
        times[strat] = time.perf_counter() - r0
        res[strat] = np.asarray(out["rank"])
        _hb(f"pallas stage: {strat} {times[strat]:.3f}s", t0)
    max_rel = float(
        np.max(np.abs(res["pallas"] - res["ell"]) / np.maximum(res["ell"], 1e-12))
    )
    _emit({
        "stage": "pallas",
        # 1% relative: the kernel's one-hot MXU partial sums accumulate in
        # tile order, the ELL path in bucket order — f32 reassociation noise
        # on s16's ~1e-5 rank values measured 0.28% max relative
        "ok": bool(max_rel < 1e-2),
        "scale": 16,
        "ell_wall_s": round(times["ell"], 3),
        "pallas_wall_s": round(times["pallas"], 3),
        "max_rel_diff_vs_ell": max_rel,
    })


def main() -> int:
    if "--worker" in sys.argv:
        worker()
        return 0
    return supervise()


if __name__ == "__main__":
    sys.exit(main())
