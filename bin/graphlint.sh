#!/usr/bin/env bash
# graphlint wrapper: trace-safety / lock-discipline / padding-invariant
# analysis for janusgraph_tpu. Exits nonzero on error findings.
#
# Usage:
#   bin/graphlint.sh                      # full package scan
#   bin/graphlint.sh --changed-only       # merge-base diff + working tree
#   bin/graphlint.sh --format json        # machine-readable report (v2 keys)
#   bin/graphlint.sh --check-imports      # + syntax/import sweep
#   bin/graphlint.sh --stats              # call-graph + per-rule counts
#   bin/graphlint.sh janusgraph_tpu/olap  # scoped scan
#
# CI mode (suppression ratchet — fails if any rule's suppression count
# grows past the checked-in budget):
#   bin/graphlint.sh --baseline .graphlint-baseline.json
# Re-bank the budget after removing suppressions:
#   bin/graphlint.sh --write-baseline .graphlint-baseline.json
# Inspect the budget table:
#   bin/graphlint.sh --baseline .graphlint-baseline.json --report-suppressions
#
# All flags pass through to `python -m janusgraph_tpu.analysis`
# (see --help / --list-rules). Suppress a finding in code with
#   # graphlint: disable=JGnnn -- <why>
# Mark an explicit context handoff to a worker thread with
#   # graphlint: handoff  (see docs/static_analysis.md, JG402)
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"

exec python -m janusgraph_tpu.analysis "$@"
