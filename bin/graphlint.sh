#!/usr/bin/env bash
# graphlint wrapper: trace-safety / lock-discipline / padding-invariant
# analysis for janusgraph_tpu. Exits nonzero on error findings.
#
# Usage:
#   bin/graphlint.sh                      # full package scan
#   bin/graphlint.sh --changed-only       # only git-changed .py files
#   bin/graphlint.sh --json               # machine-readable report
#   bin/graphlint.sh --check-imports      # + syntax/import sweep
#   bin/graphlint.sh janusgraph_tpu/olap  # scoped scan
#
# All flags pass through to `python -m janusgraph_tpu.analysis`
# (see --help / --list-rules). Suppress a finding in code with
#   # graphlint: disable=JGnnn -- <why>
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"

exec python -m janusgraph_tpu.analysis "$@"
