#!/usr/bin/env bash
# Launch the JanusGraph-TPU query server
# (reference analogue: janusgraph-dist bin/janusgraph-server.sh)
exec python -m janusgraph_tpu server "$@"
