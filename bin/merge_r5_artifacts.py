#!/usr/bin/env python
"""Consolidate the round-5 TPU evidence into one artifact.

Inputs (bench_artifacts/): r5_tpu_ladder.json (the supervisor capture
from the tunnel's first window — s16/s20 rungs + the seven s20 workload
stages), r5_tpu_ladder.log (the s22 rung whose JSON line was lost to the
tunnel wedge — parsed from the worker heartbeats), and, if the watcher
landed it, r5_tpu_remainder.jsonl (s22/s23 rungs + dataset/OLTP/pallas
stages). Output: r5_consolidated.json — every TPU stage de-duplicated
(newest wins per (stage, workload, scale)), with provenance per stage.
"""

import json
import os
import re
import sys

ART = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "bench_artifacts")


def _key(s):
    return (s.get("stage"), s.get("workload"), s.get("scale"),
            s.get("dataset"), s.get("backend"))


def main() -> int:
    stages = {}

    def add(stage, source):
        stage = dict(stage)
        stage["source"] = source
        stages[_key(stage)] = stage

    ladder = os.path.join(ART, "r5_tpu_ladder.json")
    if os.path.exists(ladder):
        with open(ladder) as f:
            data = json.load(f)
        for s in data.get("stages", []):
            add(s, "r5_tpu_ladder.json")

    # the s22 rung from the worker log (its JSON line was lost when the
    # dense-BFS compile wedged the claim; heartbeats carry the numbers)
    log = os.path.join(ART, "r5_tpu_ladder.log")
    if os.path.exists(log):
        text = open(log, errors="replace").read()
        m = re.search(
            r"s22: pagerank (\d+\.\d+)s \((\d+\.\d+e\+\d+) edges/s\)", text
        )
        fb = re.search(r"s22: bfs-4hop frontier (\d+\.\d+)s", text)
        if m and ("pagerank", None, 22, None, None) not in stages:
            add({
                "stage": "pagerank", "platform": "tpu", "scale": 22,
                "value": float(m.group(2)),
                "pagerank_wall_s": float(m.group(1)),
                "pr_iters": 20, "num_edges": 67108864,
                "note": "recovered from worker heartbeats (JSON line "
                        "lost to the s22 dense-BFS tunnel wedge)",
            }, "r5_tpu_ladder.log")
        if fb and ("bfs", None, 22, None, None) not in stages:
            add({
                "stage": "bfs", "platform": "tpu", "scale": 22,
                "bfs_4hop_wall_s": float(fb.group(1)),
                "note": "recovered from worker heartbeats",
            }, "r5_tpu_ladder.log")

    remainder = os.path.join(ART, "r5_tpu_remainder.jsonl")
    if os.path.exists(remainder):
        for line in open(remainder):
            line = line.strip()
            if not line:
                continue
            try:
                s = json.loads(line)
            except ValueError:
                continue
            if isinstance(s, dict) and "stage" in s:
                add(s, "r5_tpu_remainder.jsonl")

    tpu = [s for s in stages.values() if s.get("platform") == "tpu"
           or s.get("stage") in ("oltp",)]
    out = {
        "round": 5,
        "tpu_stage_count": sum(
            1 for s in stages.values() if s.get("platform") == "tpu"
        ),
        "stages": sorted(
            stages.values(),
            key=lambda s: (str(s.get("stage")), s.get("scale") or 0),
        ),
        "note": "consolidated round-5 hardware evidence; see "
                "BASELINE.md + docs/tpu_notes.md for the analysis",
    }
    dest = os.path.join(ART, "r5_consolidated.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {dest}: {out['tpu_stage_count']} TPU stages "
          f"({len(tpu)} rows incl. host-side OLTP)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
