#!/bin/bash
# TPU tunnel watcher, round-5 remainder: the first r5 ladder landed
# s16/s20 TPU stages + all s20 workloads (r5_tpu_ladder.json) before the
# s22 dense-BFS compile wedged the tunnel claim. This watcher retries the
# REMAINDER — s22/s23 pagerank+frontier rungs (dense capped by the new
# BENCH_DENSE_MAX_SCALE default), the dataset-fidelity rows, the OLTP
# stage, and the pallas stage — until they land or the deadline passes.
# Kill cleanly:  touch /tmp/tpu_watch2.stop   (checked between attempts)
set -u
REPO=/root/repo
OUT=$REPO/bench_artifacts
mkdir -p "$OUT"
rm -f /tmp/tpu_watch2.stop
DEADLINE=$(( $(date +%s) + ${TPU_WATCH_BUDGET_S:-21600} ))   # default 6h
ATTEMPT=0
echo $$ > /tmp/tpu_watch2.pid
while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -f /tmp/tpu_watch2.stop ]; do
  ATTEMPT=$((ATTEMPT + 1))
  LOG=$OUT/r5b_attempt${ATTEMPT}.log
  JSONL=$OUT/r5b_attempt${ATTEMPT}.jsonl
  echo "[tpu_watch2] attempt $ATTEMPT $(date -u +%H:%M:%S)" >> "$OUT/r5_watch.log"
  PYTHONPATH=/root/.axon_site:$REPO \
    BENCH_SCALES="22,23" BENCH_EXTRAS_SCALE=0 \
    BENCH_INIT_TIMEOUT_S=${TPU_WATCH_INIT_S:-900} \
    BENCH_WORKER_BUDGET_S=3600 BENCH_STAGE_TIMEOUT_S=900 \
    timeout 4200 python "$REPO/bench.py" --worker > "$JSONL" 2> "$LOG"
  rc=$?
  echo "[tpu_watch2] attempt $ATTEMPT exit=$rc" >> "$OUT/r5_watch.log"
  if grep -q '"platform": "tpu"' "$JSONL" 2>/dev/null; then
    cp "$JSONL" "$OUT/r5_tpu_remainder.jsonl"
    echo "[tpu_watch2] TPU REMAINDER LANDED -> r5_tpu_remainder.jsonl" >> "$OUT/r5_watch.log"
    break
  fi
  rm -f "$JSONL"
  sleep "${TPU_WATCH_SLEEP_S:-600}"
done
rm -f /tmp/tpu_watch2.pid
echo "[tpu_watch2] done $(date -u +%H:%M:%S)" >> "$OUT/r5_watch.log"
