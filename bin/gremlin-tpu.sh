#!/usr/bin/env bash
# Interactive console (reference analogue: janusgraph-dist bin/gremlin.sh)
exec python -m janusgraph_tpu console "$@"
