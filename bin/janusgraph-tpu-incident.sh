#!/usr/bin/env bash
# Merged cross-replica failover forensics: pulls a fleet frontend's
# GET /fleet/incident (every replica's flight ring, offset-corrected
# onto one clock) and prints the kill -> mark_dead -> re-pin -> warm-up
# narrative. Usage: janusgraph-tpu-incident.sh --url host:port [--window 60]
exec python -m janusgraph_tpu incident "$@"
