#!/bin/bash
# TPU tunnel watcher (round 5): retry the bench ladder until a TPU stage
# lands or the hard deadline passes. Artifacts land in bench_artifacts/.
# Kill cleanly:  touch /tmp/tpu_watch.stop   (checked between attempts)
# Never leaves a worker running past its per-attempt timeout.
set -u
REPO=/root/repo
OUT=$REPO/bench_artifacts
mkdir -p "$OUT"
rm -f /tmp/tpu_watch.stop   # a stale stop file must not kill a fresh launch
DEADLINE=$(( $(date +%s) + ${TPU_WATCH_BUDGET_S:-30600} ))   # default 8.5h
ATTEMPT=0
echo $$ > /tmp/tpu_watch.pid
while [ "$(date +%s)" -lt "$DEADLINE" ] && [ ! -f /tmp/tpu_watch.stop ]; do
  ATTEMPT=$((ATTEMPT + 1))
  LOG=$OUT/r5_watch_attempt${ATTEMPT}.log
  JSONL=$OUT/r5_watch_attempt${ATTEMPT}.jsonl
  echo "[tpu_watch] attempt $ATTEMPT $(date -u +%H:%M:%S)" >> "$OUT/r5_watch.log"
  PYTHONPATH=/root/.axon_site:$REPO \
    BENCH_INIT_TIMEOUT_S=${TPU_WATCH_INIT_S:-1500} \
    BENCH_WORKER_BUDGET_S=3600 \
    timeout 3900 python "$REPO/bench.py" --worker > "$JSONL" 2> "$LOG"
  rc=$?
  echo "[tpu_watch] attempt $ATTEMPT exit=$rc" >> "$OUT/r5_watch.log"
  if grep -q '"platform": "tpu"' "$JSONL" 2>/dev/null; then
    cp "$JSONL" "$OUT/r5_tpu_ladder.json"
    echo "[tpu_watch] TPU STAGES LANDED -> r5_tpu_ladder.json" >> "$OUT/r5_watch.log"
    break
  fi
  rm -f "$JSONL"  # keep logs, drop empty jsonl
  sleep "${TPU_WATCH_SLEEP_S:-600}"
done
rm -f /tmp/tpu_watch.pid
echo "[tpu_watch] done $(date -u +%H:%M:%S)" >> "$OUT/r5_watch.log"
