#!/usr/bin/env bash
# benchdiff wrapper: the bench regression sentinel as a CI gate.
# Compares two bench artifacts cell-by-cell (stage, scale, platform,
# host-fallback) and exits nonzero on any regressed headline metric.
#
# Usage:
#   bin/benchdiff.sh OLD.json NEW.json              # report only
#   bin/benchdiff.sh OLD.json NEW.json --fail-on-regress   # CI gate
#   bin/benchdiff.sh OLD.jsonl NEW.jsonl --threshold 15    # 15% noise band
#
# Accepts every artifact shape the bench has written: single stage
# dicts (SATURATE_r*.json), supervisor wrappers with embedded stage
# lines (BENCH_r*.json), and per-stage JSONL (bench_artifacts/*.jsonl).
# Exit codes: 0 ok / 1 regression (with --fail-on-regress) /
# 2 bad arguments / 3 no comparable cells.
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"

exec python -m janusgraph_tpu benchdiff "$@"
