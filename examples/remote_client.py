"""Remote access example: start a server, query it over HTTP and WebSocket
(reference analogue: janusgraph-examples remote graph app)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.driver import JanusGraphClient
from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer


def main() -> None:
    graph = open_graph({"storage.backend": "inmemory"})
    gods.load(graph)
    manager = JanusGraphManager()
    manager.put_graph("graph", graph)
    server = JanusGraphServer(manager=manager).start()
    try:
        client = JanusGraphClient(port=server.port)
        print("count over HTTP:", client.submit("g.V().count()"))
        ws = client.ws()
        print("names over WS:",
              ws.submit("g.V().has('name','jupiter').out('brother').values('name')"))
        ws.close()
    finally:
        server.stop()
        graph.close()


if __name__ == "__main__":
    main()
