"""Persistent local-backend example (reference analogue: the berkeleyje
example app): data survives process restarts via the WAL-backed store."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

from janusgraph_tpu.core.graph import open_graph


def main(directory: str) -> None:
    cfg = {"storage.backend": "local", "storage.directory": directory}
    g1 = open_graph(cfg)
    mgmt = g1.management()
    if g1.schema_cache.get_by_name("name") is None:
        mgmt.make_property_key("name", str)
    src = g1.traversal()
    v = src.add_v()
    v.property("name", "persisted!")
    src.commit()
    g1.close()

    g2 = open_graph(cfg)
    print("after reopen:", g2.traversal().V().values("name").to_list())
    g2.close()


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp())
