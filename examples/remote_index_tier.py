"""Networked deployment example: graph over a TCP storage backend PLUS a
TCP mixed-index provider — the cql+elasticsearch deployment shape
(reference analogue: janusgraph-dist config recipes wiring
storage.backend=cql with index.search.backend=elasticsearch;
janusgraph-es .../rest/RestElasticSearchClient.java:505).

Both services here run in-process for a self-contained demo; in a real
deployment each would live on its own host and the client config would
point at their addresses.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.indexing import LocalIndexProvider, RemoteIndexServer
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.remote import RemoteStoreManager, RemoteStoreServer


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        # "cluster": a storage server and an index server
        store_srv = RemoteStoreServer(InMemoryStoreManager()).start()
        idx_srv = RemoteIndexServer(
            LocalIndexProvider(directory=os.path.join(tmp, "idx"))
        ).start()
        print(f"storage server on {store_srv.address}, "
              f"index server on {idx_srv.address}")

        # client: a graph wired to both over TCP
        graph = open_graph(
            {
                "schema.default": "auto",
                "index.search.backend": "remote",
                "index.search.hostname": idx_srv.address[0],
                "index.search.port": idx_srv.address[1],
            },
            store_manager=RemoteStoreManager(*store_srv.address),
        )
        try:
            mgmt = graph.management()
            mgmt.make_property_key("bio", str)
            mgmt.make_property_key("age", int)
            mgmt.build_mixed_index("people", ["bio", "age"], backing="search")

            tx = graph.new_transaction()
            tx.add_vertex(name="hercules", bio="fought the nemean lion", age=30)
            tx.add_vertex(name="jupiter", bio="god of thunder and sky", age=5000)
            tx.commit()

            t = graph.traversal()
            print("text search 'thunder':",
                  [v.value("name") for v in
                   t.V().has("bio", P.text_contains("thunder")).to_list()])
            print("range age < 500:",
                  [v.value("name") for v in
                   t.V().has("age", P.lt(500)).to_list()])
        finally:
            graph.close()
            store_srv.stop()
            idx_srv.stop()


if __name__ == "__main__":
    main()
