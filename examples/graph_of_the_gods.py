"""Graph of the Gods end-to-end example (reference:
janusgraph-examples + GraphOfTheGodsFactory.java:41): load the canonical
demo graph, run OLTP traversals, then OLAP PageRank on the TPU executor."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# host devices by default (the ambient env may point JAX at a TPU that a
# demo should not claim); set JG_EXAMPLE_PLATFORM=tpu to run the real chip
jax.config.update("jax_platforms", os.environ.get("JG_EXAMPLE_PLATFORM", "cpu"))

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.olap.programs import PageRankProgram


def main() -> None:
    graph = open_graph({"storage.backend": "inmemory"})
    gods.load(graph)
    g = graph.traversal()

    print("Saturn's grandchild:",
          g.V().has("name", "saturn").in_("father").in_("father").values("name").to_list())
    print("Gods older than 3500:",
          g.V().has("age", P.gt(3500)).values("name").to_list())
    print("Battles of Hercules:",
          g.V().has("name", "hercules").out("battled").values("name").to_list())

    result = graph.compute().program(PageRankProgram(max_iterations=20)).submit()
    ranks = sorted(result.by_vertex("rank").items(), key=lambda kv: -kv[1])
    names = {v.id: v.value("name") for v in g.V().to_list()}
    print("PageRank top 3:", [(names[vid], round(r, 4)) for vid, r in ranks[:3]])
    graph.close()


if __name__ == "__main__":
    main()
