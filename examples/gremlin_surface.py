"""The round-5 Gremlin surface in one tour: mergeV/mergeE upserts, the
chained repeat modulators, math(), edge identity round-trips, and the
traversal-embedded OLAP computer steps — everything in BOTH spellings
(python DSL here; the camelCase forms run verbatim over the HTTP
endpoint, see remote_client.py).

Run:  python examples/gremlin_surface.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import AnonymousTraversal, T

__ = AnonymousTraversal()


def main():
    graph = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(graph)
    g = graph.traversal()

    # --- declarative upserts (TinkerPop 3.6 mergeV/mergeE) -------------
    minerva = (
        g.merge_v({T.label: "god", "name": "minerva"})
        .on_create({"age": 100})
        .on_match({"seen": True})
        .next()
    )
    print("mergeV created:", minerva.value("name"), minerva.value("age"))
    again = g.merge_v({T.label: "god", "name": "minerva"}).next()
    print("idempotent:", again.id == minerva.id)

    jupiter = g.V().has("name", "jupiter").next()
    e = (
        g.merge_e({Direction.OUT: jupiter, Direction.IN: minerva,
                   T.label: "sired"})
        .on_create({"order": 1})
        .next()
    )
    print("mergeE:", e.label, e.property_values())

    # --- edge identity round-trip --------------------------------------
    rid = g.V().has("name", "jupiter").out_e("brother").id_().next()
    print("edge id:", rid, "->", g.E(rid).next().label)

    # --- chained loop modulators (real Gremlin spelling) ---------------
    names = (
        g.V().has("name", "hercules")
        .repeat(__.out("father")).until(__.has("name", "saturn"))
        .values("name").to_list()
    )
    print("repeat().until():", names)

    # --- math() ---------------------------------------------------------
    ratios = (
        g.V().has("name", "jupiter").as_("a")
        .out("brother").math("a / _").by("age").to_list()
    )
    print("math('a / _'):", ratios)

    # --- traversal-embedded OLAP (runs on the configured executor) ------
    top = (
        g.V().page_rank()
        .order("pagerank", reverse=True).limit(3).values("name").to_list()
    )
    print("pageRank top-3:", top)
    comp = g.V().connected_component().group_count("component")
    print("connectedComponent sizes:", comp)
    path = g.V().has("name", "hercules").shortest_path(
        target=__.has("name", "saturn")
    ).next()
    print("shortestPath:", [v.value("name") for v in path])

    graph.close()


if __name__ == "__main__":
    main()
