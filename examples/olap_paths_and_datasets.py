"""OLAP path()/select() + dataset-shaped analytics (round 5 features).

Demonstrates:
  1. path-carrying OLAP traversals — device reach masks + host traverser
     enumeration (the TraversalVertexProgram path analogue; reference:
     FulgoraGraphComputer.java:155) — checked against the OLTP oracle;
  2. select() over as()-labeled steps;
  3. the dataset-fidelity generators behind BASELINE rows 2 and 4
     (LDBC-SF1-sized SNB shape, Twitter-2010-shaped power law) with
     frontier-compacted ConnectedComponents.

Run:  JAX_PLATFORMS=cpu python examples/olap_paths_and_datasets.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from janusgraph_tpu.core import gods  # noqa: E402
from janusgraph_tpu.core.graph import open_graph  # noqa: E402

# ---------------------------------------------------------------- 1. paths
g = open_graph({"storage.backend": "inmemory"})
gods.load(g)

result = g.compute(executor="cpu").traverse(
    ("out", ["battled"]), ("in", ["battled"]), ("out", ["father"]),
    paths=True,
).submit()
total = int(np.asarray(result.states["count"]).sum())
print(f"3-hop traverser count (device): {total}")
print("enumerated paths (host):")
name_of = {
    v.id: v.value("name") for v in g.new_transaction().vertices()
}
for p in result.paths():
    print("  " + " -> ".join(name_of[v] for v in p))

# OLTP oracle agrees
oltp = (
    g.traversal().V().out("battled").in_("battled").out("father")
    .path().to_list()
)
assert sorted(tuple(v.id for v in p) for p in oltp) == sorted(result.paths())
print("OLTP path() parity: ok")

# -------------------------------------------------------------- 2. select
sel = g.compute(executor="cpu").traverse(
    ("out", ["battled"], (), "monster"),
    paths=True, source_as="hero",
).submit()
print("select('hero', 'monster'):")
for row in sel.select("hero", "monster"):
    print(f"  {name_of[row['hero']]} battled {name_of[row['monster']]}")
g.close()

# ------------------------------------------------- 3. dataset-shaped OLAP
from janusgraph_tpu.olap.generators import ldbc_sf_csr, twitter_csr  # noqa: E402
from janusgraph_tpu.olap.programs import (  # noqa: E402
    ConnectedComponentsProgram,
    PeerPressureProgram,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor  # noqa: E402

ldbc = ldbc_sf_csr(1, scale_down=64)  # SF1 shape at 1/64 size for the demo
ex = TPUExecutor(ldbc)
cc = ex.run(ConnectedComponentsProgram(max_iterations=64))
print(
    f"LDBC-SF1-shaped ({ldbc.num_vertices:,} v / {ldbc.num_edges:,} e): "
    f"{len(np.unique(cc['component']))} components "
    f"via the {ex.last_run_info.get('path', 'dense')} path"
)

tw = twitter_csr(1 << 13, 30)
hubs = np.sort(np.diff(tw.in_indptr))[-3:]
pp = TPUExecutor(tw).run(PeerPressureProgram(rounds=5), sync_every=5)
print(
    f"Twitter-2010-shaped ({tw.num_vertices:,} v / {tw.num_edges:,} e, "
    f"top-3 hub in-degrees {hubs[::-1]} — celebrity skew): "
    f"{len(np.unique(pp['cluster']))} clusters"
)
print("done")
