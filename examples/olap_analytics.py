"""OLAP analytics tour: PageRank, frontier BFS with path reconstruction,
connected components, and a filtered traversal with group-count-by-label —
the TPU-native analogue of the reference's FulgoraGraphComputer workloads
(reference: janusgraph-examples + OLAPTest.java vertex programs)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# host devices by default (the ambient env may point JAX at a TPU that a
# demo should not claim); set JG_EXAMPLE_PLATFORM=tpu to run the real chip
jax.config.update("jax_platforms", os.environ.get("JG_EXAMPLE_PLATFORM", "cpu"))

import numpy as np

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.predicates import Cmp
from janusgraph_tpu.olap.csr import load_csr
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    ShortestPathProgram,
)
from janusgraph_tpu.olap.programs.olap_traversal import (
    build_olap_traversal,
    group_count_by_label,
)
from janusgraph_tpu.olap.programs.shortest_path import reconstruct_path
from janusgraph_tpu.olap.tpu_executor import TPUExecutor


def main() -> None:
    graph = open_graph({"storage.backend": "inmemory"})
    gods.load(graph)
    csr = load_csr(graph, property_keys=("name", "age"))
    names = csr.properties["name"]
    ex = TPUExecutor(csr, frontier="always")

    # PageRank (single compiled dispatch for the whole iteration)
    ranks = ex.run(PageRankProgram(max_iterations=20, tol=0.0))["rank"]
    top = np.argsort(np.asarray(ranks))[::-1][:3]
    print("top pagerank:", [(names[i], round(float(ranks[i]), 4)) for i in top])

    # frontier-compacted BFS with path reconstruction
    herc = int(np.nonzero(names == "hercules")[0][0])
    res = ex.run(ShortestPathProgram(seed_index=herc, track_paths=True))
    tart = int(np.nonzero(names == "tartarus")[0][0])
    path = reconstruct_path(res, tart)
    print("hercules -> tartarus:", [names[v] for v in path])

    # connected components (frontier min-label propagation)
    comp = ex.run(ConnectedComponentsProgram())["component"]
    n_comp = len(np.unique(np.asarray(comp)))
    print("connected components:", n_comp)

    # filtered OLAP traversal + group-count-by-label:
    # g.V().out().has('age', gt(100)).groupCount().by(label)
    prog = build_olap_traversal(
        graph, csr, [("out", None, [("age", Cmp.GREATER_THAN, 100)])]
    )
    counts = ex.run(prog)["count"]
    print("out().has(age>100) by label:",
          group_count_by_label(graph, csr, counts))

    graph.close()


if __name__ == "__main__":
    main()
