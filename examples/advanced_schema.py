"""Advanced schema features walkthrough (reference analogues:
ManagementSystem.setConsistency / setTTL / buildEdgeIndex):

  1. LOCK consistency — two graph instances over one backend race on the
     same property; the stale writer is rejected by the consistent-key
     locker's expected-value check.
  2. FORK consistency — updating a loaded edge forks a fresh relation id.
  3. Schema TTL — a session property whose cells expire.
  4. RelationTypeIndex — a vertex-centric index built AFTER the edge label
     exists, backfilled with reindex, queried as a sort-key range.

Run: python examples/advanced_schema.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# host devices by default (the ambient env may point JAX at a TPU that a
# demo should not claim); set JG_EXAMPLE_PLATFORM=tpu to run the real chip
jax.config.update("jax_platforms", os.environ.get("JG_EXAMPLE_PLATFORM", "cpu"))

from janusgraph_tpu.core.codecs import Consistency, Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def lock_consistency():
    print("== LOCK consistency (two instances, one backend) ==")
    shared = InMemoryStoreManager()
    g1 = open_graph(store_manager=shared)
    g1.management().make_property_key("serial", int)
    g1.management().set_consistency("serial", Consistency.LOCK)
    tx = g1.new_transaction()
    v = tx.add_vertex()
    v.property("serial", 1)
    tx.commit()

    g2 = open_graph(store_manager=shared)
    tx1, tx2 = g1.new_transaction(), g2.new_transaction()
    tx1.get_vertex(v.id).property("serial", 2)
    tx2.get_vertex(v.id).property("serial", 3)
    tx1.commit()
    try:
        tx2.commit()
        print("  UNEXPECTED: stale writer committed")
    except Exception as e:
        print(f"  stale writer rejected: {type(e).__name__}")
    final = g1.new_transaction().get_vertex(v.id).value("serial")
    print(f"  committed value: {final}")
    g1.close(), g2.close()


def fork_consistency():
    print("== FORK consistency (edge updates fork) ==")
    g = open_graph()
    m = g.management()
    m.make_property_key("since", int)
    m.make_edge_label("follows")
    m.set_consistency("follows", Consistency.FORK)
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e = tx.add_edge(a, "follows", b, since=1)
    tx.commit()
    tx2 = g.new_transaction()
    [loaded] = tx2.get_vertex(a.id).edges(Direction.OUT, "follows")
    updated = loaded.set_property("since", 2)
    print(f"  relation id {loaded.id} -> {updated.id} (forked)")
    tx2.commit()
    g.close()


def schema_ttl():
    print("== schema TTL ==")
    g = open_graph()
    m = g.management()
    m.make_property_key("session", str)
    m.set_ttl("session", 3600)
    print(f"  session ttl: {m.get_ttl('session')}s")
    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("session", "tok")
    tx.commit()
    print(
        "  readback:",
        g.new_transaction().get_vertex(v.id).value("session"),
    )
    g.close()


def relation_index():
    print("== RelationTypeIndex (post-hoc vertex-centric index) ==")
    g = open_graph()
    m = g.management()
    m.make_property_key("time", int)
    m.make_edge_label("battled")  # no sort key at creation
    tx = g.new_transaction()
    hercules = tx.add_vertex()
    for t in (1, 5, 9, 12, 20):
        tx.add_edge(hercules, "battled", tx.add_vertex(), time=t)
    tx.commit()

    m.build_edge_index("battled", "battlesByTime", ["time"])
    n = m.reindex_relation_index("battlesByTime")
    print(f"  backfilled {n} edges")
    tx2 = g.new_transaction()
    hits = tx2.get_edges(
        tx2.get_vertex(hercules.id),
        Direction.OUT,
        ("battled",),
        sort_range=(5, 15),
    )
    print(f"  battles in [5, 15): {sorted(e.value('time') for e in hits)}")
    g.close()


if __name__ == "__main__":
    lock_consistency()
    fork_consistency()
    schema_ttl()
    relation_index()
