"""Distributed deployment example (reference analogue: a Cassandra cluster +
several JanusGraph instances + Spark workers for OLAP input):

  1. one storage-server process hosting an N-node sharded composite,
  2. a graph instance connected over the remote KCVS protocol (OLTP),
  3. N loader processes doing partition-parallel CSR extraction,
  4. OLAP PageRank on the merged snapshot, written back over the wire.

Run: python examples/distributed_cluster.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# host devices by default (the ambient env may point JAX at a TPU that a
# demo should not claim); set JG_EXAMPLE_PLATFORM=tpu to run the real chip
jax.config.update("jax_platforms", os.environ.get("JG_EXAMPLE_PLATFORM", "cpu"))

import numpy as np

from janusgraph_tpu.core.bulk import bulk_add_edges, bulk_add_vertices
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.distributed_load import distributed_load_csr
from janusgraph_tpu.olap.programs import PageRankProgram
from janusgraph_tpu.olap.tpu_executor import TPUExecutor, write_back
from janusgraph_tpu.storage.sharded_store import ShardedStoreManager
from janusgraph_tpu.storage.remote import RemoteStoreServer


def main() -> None:
    # 1. storage tier: 3 hash-partitioned nodes behind one TCP endpoint
    server = RemoteStoreServer(ShardedStoreManager(num_nodes=3)).start()
    host, port = server.address
    cfg = {
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    }
    print(f"storage cluster at {host}:{port} (3 sharded nodes)")

    # 2. a graph instance over the wire: bulk-ingest a small power-law graph
    g = open_graph(cfg)
    rng = np.random.default_rng(7)
    n, m = 5000, 40000
    vids = bulk_add_vertices(g, n, label="page")
    bulk_add_edges(
        g, "links", vids[rng.integers(0, n, m)], vids[rng.integers(0, n, m)]
    )
    print(f"ingested {n} vertices / {m} edges over the remote protocol")

    # 3. partition-parallel extraction with 4 REAL worker processes
    csr = distributed_load_csr(cfg, num_workers=4)
    print(f"distributed load: {csr.num_vertices}v {csr.num_edges}e")

    # 4. OLAP + write-back through the same wire
    res = TPUExecutor(csr).run(PageRankProgram(max_iterations=20))
    write_back(g, csr, {"rank": res["rank"]})
    top = max(
        g.traversal().V().to_list(), key=lambda v: v.value("rank") or 0.0
    )
    print(f"highest-rank vertex {top.id}: {top.value('rank'):.2e}")

    g.close()
    server.stop()


if __name__ == "__main__":
    main()
