"""Persistent local mixed-index provider — the embedded-Lucene analogue.

Plays the role janusgraph-lucene plays for the reference (reference:
janusgraph-lucene/.../LuceneIndex.java — embedded disk-backed provider
implementing IndexProvider.java:36), built on this framework's own
log-structured ordered-KV engine (storage/localstore.py: WAL + snapshot +
compaction) instead of an external library. The ordered-KV composite-key
encoding (storage/kvstore.py encode_key: order-preserving, prefix-free)
turns every index structure into a contiguous key range:

  M <store> <field>                  -> key metadata (type/mapping)
  D <store> <docid> <field>          -> the doc's stored values (framed)
  T <store> <field> <term> <docid>   -> posting (value = u32 refcount)

Terms are namespaced by kind byte so one field can carry several index
shapes (TEXTSTRING):
  t<token>                 tokenized text      (textContains*)
  s<utf-8 value>           exact string        (eq / textPrefix / ...)
  o<order-preserving enc>  orderable scalars   (Cmp ranges via KV range scan)

Because encode_key is order-preserving, numeric/date RANGE queries are ONE
contiguous KV scan over the `o` region — the disk analogue of Lucene's
point/range trees. Geoshape values live only in the doc store and are
exact-tested (same policy as the in-memory provider). Postings carry a
refcount so LIST/SET cardinality and duplicate tokens survive partial
removals. Durability, crash recovery, and compaction are inherited from the
underlying engine's WAL.
"""

from __future__ import annotations

import json
import re
import struct
import threading
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Set, Tuple

from janusgraph_tpu.core.predicates import (
    Contain,
    Cmp,
    Geo,
    Geoshape,
    Text,
    fuzzy_distance,
    levenshtein,
    tokenize,
)
from janusgraph_tpu.exceptions import BackendError
from janusgraph_tpu.indexing.provider import (
    And,
    IndexEntry,
    IndexFeatures,
    IndexProvider,
    IndexQuery,
    KeyInformation,
    Mapping,
    Not,
    Or,
    PredicateCondition,
    RawQuery,
    register_index_provider,
)
from janusgraph_tpu.storage.kvstore import decode_composite, encode_key

_TEXT_PREDICATES = {
    Text.CONTAINS, Text.CONTAINS_PREFIX, Text.CONTAINS_REGEX,
    Text.CONTAINS_FUZZY, Text.CONTAINS_PHRASE,
}
# Contain.NOT_IN excluded like NOT_EQUAL (matches docs lacking the field)
_STRING_PREDICATES = {Cmp.EQUAL, Contain.IN, Text.PREFIX, Text.REGEX, Text.FUZZY}
_ORDER_PREDICATES = {
    Cmp.LESS_THAN, Cmp.LESS_THAN_EQUAL,
    Cmp.GREATER_THAN, Cmp.GREATER_THAN_EQUAL,
}


def _next_prefix(key: bytes) -> bytes:
    """Smallest byte string greater than every extension of `key`."""
    b = bytearray(key)
    for i in range(len(b) - 1, -1, -1):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return key + b"\xff"  # all-0xff: unbounded in practice


# leading-segment decode shares the composite codec with the KV adapter —
# decode_composite has exactly the (segment, rest-after-terminator) contract
_decode_segment = decode_composite


class LocalIndexProvider(IndexProvider):
    """Disk-backed mixed-index provider over the local ordered-KV engine
    (shorthand "localindex")."""

    name = "localindex"

    def __init__(self, directory: str = "", fsync: bool = False, **_kwargs):
        from janusgraph_tpu.storage.localstore import LocalKVStoreManager

        if not directory:
            raise BackendError("localindex requires index.search.directory")
        self._mgr = LocalKVStoreManager(directory, fsync=fsync)
        self._kv = self._mgr.open_database("index")
        self._tx = self._mgr.begin_transaction()
        self._lock = threading.RLock()
        # serializer for framed doc values (self-describing, Geoshape-aware)
        from janusgraph_tpu.core.attributes import Serializer

        self._ser = Serializer()
        self._infos: Dict[Tuple[str, str], KeyInformation] = {}
        self._check_format()
        self._load_meta()

    #: bump on any change to the posting/doc byte layouts; directories
    #: written by another version are refused LOUDLY instead of being
    #: decoded as garbage (no silent migration)
    FORMAT_VERSION = 2
    _VKEY = b"V"

    def _check_format(self) -> None:
        stored = self._kv.get(self._VKEY, self._tx)
        if stored is None:
            has_data = any(True for _ in self._kv.scan(b"D", b"N", self._tx))
            if has_data:
                raise BackendError(
                    "localindex directory predates format versioning — "
                    "rebuild the index (REINDEX) into a fresh directory"
                )
            self._kv.insert(
                self._VKEY, struct.pack(">I", self.FORMAT_VERSION), self._tx
            )
            self._tx.commit()
            return
        (ver,) = struct.unpack(">I", stored)
        if ver != self.FORMAT_VERSION:
            raise BackendError(
                f"localindex format v{ver} != supported v{self.FORMAT_VERSION}"
                " — rebuild the index (REINDEX) into a fresh directory"
            )

    # -------------------------------------------------------------- layout
    @staticmethod
    def _mkey(store: str, field: str) -> bytes:
        return b"M" + encode_key(store.encode()) + encode_key(field.encode())

    @staticmethod
    def _dkey(store: str, docid: str, field: str = None) -> bytes:
        k = b"D" + encode_key(store.encode()) + encode_key(docid.encode())
        return k if field is None else k + encode_key(field.encode())

    @staticmethod
    def _tprefix(store: str, field: str) -> bytes:
        return b"T" + encode_key(store.encode()) + encode_key(field.encode())

    def _pkey(self, store: str, field: str, term: bytes, docid: str) -> bytes:
        return (
            self._tprefix(store, field)
            + encode_key(term)
            + encode_key(docid.encode())
        )

    # ---------------------------------------------------------- value terms
    def _terms_for(self, info: KeyInformation, value) -> List[bytes]:
        """The posting terms one stored value contributes."""
        m = info.mapping
        if isinstance(value, str):
            if m == Mapping.DEFAULT:
                m = Mapping.TEXT
            out: List[bytes] = []
            if m in (Mapping.TEXT, Mapping.TEXTSTRING):
                out.extend(b"t" + t.encode() for t in tokenize(value))
            if m in (Mapping.STRING, Mapping.TEXTSTRING):
                out.append(b"s" + value.encode())
            return out
        if isinstance(value, Geoshape):
            return []  # exact-tested over the doc store
        try:
            # encode in the FIELD's registered value space (int values on a
            # float field must land in float-ordered bytes, matching the
            # query-side _coerce — parity with the in-memory provider)
            return [b"o" + self._ser.write_ordered(self._coerce(info, value))]
        except Exception:
            return []

    def _info(self, store: str, field: str, key_infos=None) -> KeyInformation:
        info = self._infos.get((store, field))
        if info is None:
            info = (key_infos or {}).get(store, {}).get(
                field, KeyInformation(object)
            )
        return info

    def _load_meta(self) -> None:
        for k, v in self._kv.scan(b"M", b"N", self._tx):
            store_b, rest = _decode_segment(k[1:])
            field_b, _ = _decode_segment(rest)
            meta = json.loads(v.decode())
            self._infos[(store_b.decode(), field_b.decode())] = KeyInformation(
                {"str": str, "float": float, "int": int,
                 "Geoshape": Geoshape}.get(meta["type"], object),
                Mapping(meta["mapping"]),
                meta.get("cardinality", "SINGLE"),
            )

    # ------------------------------------------------------------------ SPI
    def features(self) -> IndexFeatures:
        return IndexFeatures(
            supports_cardinality=("SINGLE", "LIST", "SET"), supports_geo=True
        )

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        with self._lock:
            existing = self._infos.get((store, key))
            if existing is not None and existing.mapping != info.mapping:
                raise BackendError(
                    f"field {key} already registered with mapping "
                    f"{existing.mapping}"
                )
            if existing is None:
                self._infos[(store, key)] = info
                meta = {
                    "type": getattr(info.data_type, "__name__", "object"),
                    "mapping": info.mapping.value,
                    "cardinality": info.cardinality,
                }
                self._kv.insert(
                    self._mkey(store, key), json.dumps(meta).encode(), self._tx
                )

    # doc value (en/de)coding: [count u32] then framed values
    def _encode_values(self, values: List[object]) -> bytes:
        parts = [struct.pack(">I", len(values))]
        for v in values:
            framed = self._ser.write_object(v)
            parts.append(struct.pack(">I", len(framed)) + framed)
        return b"".join(parts)

    def _decode_values(self, data: bytes) -> List[object]:
        (n,) = struct.unpack(">I", data[:4])
        off = 4
        out = []
        for _ in range(n):
            (ln,) = struct.unpack(">I", data[off : off + 4])
            off += 4
            v, _ = self._ser.read_object(data[off : off + ln])
            off += ln
            out.append(v)
        return out

    def _doc_values(self, store: str, docid: str) -> Dict[str, List[object]]:
        prefix = self._dkey(store, docid)
        out: Dict[str, List[object]] = {}
        for k, v in self._kv.scan(prefix, _next_prefix(prefix), self._tx):
            field_b, _ = _decode_segment(k[len(prefix) :])
            out[field_b.decode()] = self._decode_values(v)
        return out

    def _posting_adjust(
        self, store: str, field: str, term: bytes, docid: str, delta: int
    ) -> None:
        key = self._pkey(store, field, term, docid)
        cur = self._kv.get(key, self._tx)
        count = (struct.unpack(">I", cur)[0] if cur else 0) + delta
        if count > 0:
            self._kv.insert(key, struct.pack(">I", count), self._tx)
        elif cur is not None:
            self._kv.delete(key, self._tx)

    def _remove_values(
        self, store: str, docid: str, field: str, values: List[object], key_infos
    ):
        """Remove a BATCH of values from one doc field: one read-modify-write
        of the doc entry, mirroring _add_values (per-value re-encoding is
        O(n^2) for LIST-cardinality docs)."""
        info = self._info(store, field, key_infos)
        vals = self._doc_values(store, docid).get(field, [])
        try:
            # hashable fast path: multiset subtraction in one pass
            from collections import Counter

            want = Counter(values)
            kept: List[object] = []
            removed: List[object] = []
            for v in vals:
                if want.get(v, 0) > 0:
                    want[v] -= 1
                    removed.append(v)
                else:
                    kept.append(v)
            vals = kept
        except TypeError:  # unhashable values: linear removal
            removed = []
            for value in values:
                try:
                    vals.remove(value)
                except ValueError:
                    continue
                removed.append(value)
        if not removed:
            return
        dkey = self._dkey(store, docid, field)
        if vals:
            self._kv.insert(dkey, self._encode_values(vals), self._tx)
        else:
            self._kv.delete(dkey, self._tx)
        for value in removed:
            for term in self._terms_for(info, value):
                self._posting_adjust(store, field, term, docid, -1)

    def _add_values(
        self, store: str, docid: str, field: str, values: List[object], key_infos
    ):
        """Append a BATCH of values to one doc field: one read-modify-write
        of the doc entry regardless of how many values the mutation carries
        (per-value re-encoding would be O(n^2) for LIST-cardinality docs)."""
        info = self._info(store, field, key_infos)
        vals = self._doc_values(store, docid).get(field, [])
        vals.extend(values)
        self._kv.insert(
            self._dkey(store, docid, field), self._encode_values(vals), self._tx
        )
        for value in values:
            for term in self._terms_for(info, value):
                self._posting_adjust(store, field, term, docid, +1)

    def _delete_doc(self, store: str, docid: str, key_infos) -> None:
        for field, vals in self._doc_values(store, docid).items():
            info = self._info(store, field, key_infos)
            for v in vals:
                for term in self._terms_for(info, v):
                    self._posting_adjust(store, field, term, docid, -1)
            self._kv.delete(self._dkey(store, docid, field), self._tx)

    @staticmethod
    def _group_by_field(entries) -> Dict[str, List[object]]:
        grouped: Dict[str, List[object]] = {}
        for e in entries:
            grouped.setdefault(e.field, []).append(e.value)
        return grouped

    def mutate(self, mutations, key_infos) -> None:
        with self._lock:
            for store, per_doc in mutations.items():
                for docid, m in per_doc.items():
                    if m.is_deleted:
                        self._delete_doc(store, docid, key_infos)
                        if not m.additions:
                            continue
                    for field, values in self._group_by_field(m.deletions).items():
                        self._remove_values(store, docid, field, values, key_infos)
                    for field, values in self._group_by_field(m.additions).items():
                        self._add_values(store, docid, field, values, key_infos)
            self._tx.commit()

    def restore(self, documents, key_infos) -> None:
        with self._lock:
            for store, per_doc in documents.items():
                for docid, entries in per_doc.items():
                    self._delete_doc(store, docid, key_infos)
                    for field, values in self._group_by_field(entries).items():
                        self._add_values(store, docid, field, values, key_infos)
            self._tx.commit()

    # ---------------------------------------------------------------- query
    def _scan_term_region(
        self, store: str, field: str, lo: bytes, hi: Optional[bytes]
    ) -> Iterator[Tuple[bytes, str]]:
        """Yield (term, docid) for postings in [prefix+lo, prefix+hi)."""
        prefix = self._tprefix(store, field)
        start = prefix + lo
        end = _next_prefix(prefix) if hi is None else prefix + hi
        for k, _v in self._kv.scan(start, end, self._tx):
            term, rest = _decode_segment(k[len(prefix) :])
            docid_b, _ = _decode_segment(rest)
            yield term, docid_b.decode()

    def _term_docs(self, store: str, field: str, term: bytes) -> Set[str]:
        ek = encode_key(term)
        return {
            d for _t, d in self._scan_term_region(
                store, field, ek, _next_prefix(ek)
            )
        }

    def _all_docids(self, store: str) -> Set[str]:
        prefix = b"D" + encode_key(store.encode())
        out: Set[str] = set()
        for k, _v in self._kv.scan(prefix, _next_prefix(prefix), self._tx):
            docid_b, _ = _decode_segment(k[len(prefix) :])
            out.add(docid_b.decode())
        return out

    def _docs_with_field(self, store: str, field: str):
        """(docid, values) pairs for docs carrying the field — ONE contiguous
        scan of the store's doc region (the exact-test fallback path), not a
        per-doc range scan."""
        prefix = b"D" + encode_key(store.encode())
        want = field.encode()
        for k, v in self._kv.scan(prefix, _next_prefix(prefix), self._tx):
            docid_b, rest = _decode_segment(k[len(prefix) :])
            field_b, _ = _decode_segment(rest)
            if field_b == want:
                yield docid_b.decode(), self._decode_values(v)

    def _coerce(self, info: KeyInformation, cond):
        """Encode query conditions in the FIELD's value space: postings were
        written with write_ordered(field-typed value), so an int condition on
        a float field must be encoded as a float (the int and double ordered
        encodings are not byte-comparable). Lossy directions are handled at
        the call sites (EQUAL: no match; ranges: floor/ceil rewrite)."""
        t = info.data_type
        if t is float and isinstance(cond, int) and not isinstance(cond, bool):
            return float(cond)
        if t is int and isinstance(cond, float) and cond.is_integer():
            return int(cond)
        return cond

    def _field_query(self, store: str, field: str, predicate, cond) -> Set[str]:
        info = self._info(store, field)
        if predicate is Contain.IN:
            out: Set[str] = set()
            for v in cond:
                out |= self._field_query(store, field, Cmp.EQUAL, v)
            return out
        if predicate is Cmp.EQUAL:
            if isinstance(cond, Geoshape):
                return {
                    d for d, vals in self._docs_with_field(store, field)
                    if any(v == cond for v in vals)
                }
            if isinstance(cond, str):
                return self._term_docs(store, field, b"s" + cond.encode())
            if (
                info.data_type is int
                and isinstance(cond, float)
                and not cond.is_integer()
            ):
                return set()  # a non-integral value never equals an int field
            try:
                term = b"o" + self._ser.write_ordered(self._coerce(info, cond))
            except Exception:
                term = None
            if term is not None:
                return self._term_docs(store, field, term)
        if predicate is Cmp.NOT_EQUAL:
            return {
                d for d, vals in self._docs_with_field(store, field)
                if any(v != cond for v in vals)
            }
        if predicate in _ORDER_PREDICATES:
            if (
                info.data_type is int
                and isinstance(cond, float)
                and not cond.is_integer()
            ):
                # exact rewrite into int space: x > 1.5 == x >= 2, etc.
                import math

                if predicate in (Cmp.GREATER_THAN, Cmp.GREATER_THAN_EQUAL):
                    predicate, cond = Cmp.GREATER_THAN_EQUAL, math.ceil(cond)
                else:
                    predicate, cond = Cmp.LESS_THAN_EQUAL, math.floor(cond)
            enc = self._ser.write_ordered(self._coerce(info, cond))
            bound = encode_key(b"o" + enc)
            region_lo, region_hi = b"o", b"p"  # the whole `o` term namespace
            if predicate is Cmp.GREATER_THAN_EQUAL:
                lo, hi = bound, encode_key(region_hi)
            elif predicate is Cmp.GREATER_THAN:
                lo, hi = _next_prefix(bound), encode_key(region_hi)
            elif predicate is Cmp.LESS_THAN:
                lo, hi = encode_key(region_lo)[:1], bound
            else:  # LESS_THAN_EQUAL
                lo, hi = encode_key(region_lo)[:1], _next_prefix(bound)
            return {d for _t, d in self._scan_term_region(store, field, lo, hi)}
        if predicate is Text.CONTAINS:
            want = tokenize(str(cond))
            if not want:
                return set()
            out: Optional[Set[str]] = None
            for t in want:
                s = self._term_docs(store, field, b"t" + t.encode())
                out = s if out is None else out & s
                if not out:
                    return set()
            return out
        if predicate is Text.CONTAINS_PREFIX:
            p = str(cond).lower().encode()
            # tokens contain no NULs, so raw prefix == encoded prefix
            return {
                d for _t, d in self._scan_term_region(
                    store, field, b"t" + p, _next_prefix(b"t" + p)
                )
            }
        if predicate in (Text.CONTAINS_REGEX, Text.CONTAINS_FUZZY):
            out: Set[str] = set()
            if predicate is Text.CONTAINS_REGEX:
                rx = re.compile(str(cond))
                match = lambda tok: rx.fullmatch(tok) is not None
            else:
                t = str(cond).lower()
                cap = fuzzy_distance(t)
                match = lambda tok: levenshtein(tok, t, cap) <= cap
            for term, d in self._scan_term_region(
                store, field, b"t", b"u"
            ):
                if match(term[1:].decode()):
                    out.add(d)
            return out
        if predicate in (
            Text.CONTAINS_PHRASE, Text.PREFIX, Text.REGEX, Text.FUZZY,
        ):
            return {
                d for d, vals in self._docs_with_field(store, field)
                if any(
                    isinstance(v, str) and predicate.evaluate(v, cond)
                    for v in vals
                )
            }
        if predicate in (Geo.INTERSECT, Geo.DISJOINT, Geo.WITHIN, Geo.CONTAINS):
            return {
                d for d, vals in self._docs_with_field(store, field)
                if any(
                    isinstance(v, Geoshape) and predicate.evaluate(v, cond)
                    for v in vals
                )
            }
        return {
            d for d, vals in self._docs_with_field(store, field)
            if any(predicate.evaluate(v, cond) for v in vals)
        }

    def _evaluate(self, store: str, cond) -> Set[str]:
        if isinstance(cond, PredicateCondition):
            return self._field_query(store, cond.key, cond.predicate, cond.value)
        if isinstance(cond, And):
            out: Optional[Set[str]] = None
            for c in cond.children:
                r = self._evaluate(store, c)
                out = r if out is None else out & r
                if not out:
                    return set()
            return out if out is not None else self._all_docids(store)
        if isinstance(cond, Or):
            out: Set[str] = set()
            for c in cond.children:
                out |= self._evaluate(store, c)
            return out
        if isinstance(cond, Not):
            return self._all_docids(store) - self._evaluate(store, cond.child)
        raise BackendError(f"unsupported condition {cond!r}")

    def query(self, store: str, q: IndexQuery) -> List[str]:
        with self._lock:
            hits = self._evaluate(store, q.condition)
            if q.orders:
                def key_for(docid, o):
                    vals = self._doc_values(store, docid).get(o.key)
                    v = vals[0] if vals else None
                    return (v is None, v)

                try:
                    result = sorted(hits)
                    for o in reversed(q.orders):
                        result = sorted(
                            result,
                            key=lambda d, _o=o: key_for(d, _o),
                            reverse=o.desc,
                        )
                except TypeError:
                    result = sorted(hits)
            else:
                result = sorted(hits)
            if q.offset:
                result = result[q.offset :]
            if q.limit is not None:
                result = result[: q.limit]
            return result

    _RAW_TERM = re.compile(r"(?:v\.)?\"?([\w.]+)\"?:(\S+)")

    def raw_query(self, store: str, q: RawQuery) -> List[Tuple[str, float]]:
        with self._lock:
            scores: Dict[str, float] = defaultdict(float)
            terms = self._RAW_TERM.findall(q.query)
            if not terms:
                raise BackendError(f"unparseable raw query {q.query!r}")
            for fieldname, term in terms:
                hits = self._field_query(store, fieldname, Text.CONTAINS, term)
                if not hits:
                    hits = self._field_query(store, fieldname, Cmp.EQUAL, term)
                for d in hits:
                    scores[d] += 1.0
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
            if q.offset:
                ranked = ranked[q.offset :]
            if q.limit is not None:
                ranked = ranked[: q.limit]
            return ranked

    def totals(self, store: str, q: RawQuery) -> int:
        return len(self.raw_query(store, RawQuery(q.query, limit=None, offset=0)))

    def supports(self, info: KeyInformation, predicate) -> bool:
        m = info.mapping
        if info.data_type is str:
            eff = Mapping.TEXT if m in (Mapping.DEFAULT, Mapping.TEXT) else m
            if predicate in _TEXT_PREDICATES:
                return eff in (Mapping.TEXT, Mapping.TEXTSTRING)
            if predicate in _STRING_PREDICATES:
                return eff in (Mapping.STRING, Mapping.TEXTSTRING)
            return False
        if info.data_type is Geoshape:
            return predicate in (
                Geo.INTERSECT, Geo.DISJOINT, Geo.WITHIN, Geo.CONTAINS,
                Cmp.EQUAL, Contain.IN,
            )
        return predicate in _STRING_PREDICATES | _ORDER_PREDICATES

    def exists(self) -> bool:
        return bool(self._infos) or any(
            True for _ in self._kv.scan(b"D", b"E", self._tx)
        )

    def compact(self) -> None:
        """Snapshot + WAL truncation (inherited engine maintenance)."""
        with self._lock:
            self._mgr.compact()

    def close(self) -> None:
        with self._lock:
            self._mgr.close()

    def clear_storage(self) -> None:
        with self._lock:
            self._mgr.clear_storage()
            self._infos = {}


register_index_provider("localindex", LocalIndexProvider)
