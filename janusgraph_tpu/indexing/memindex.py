"""Host in-memory mixed-index provider: full-text, range, and geo queries.

The embedded provider playing the role the Lucene module plays for the
reference (reference: janusgraph-lucene/.../LuceneIndex.java — embedded
index used wherever an external Elasticsearch isn't warranted; SPI contract
IndexProvider.java:36, behavior contract
janusgraph-backend-testutils/.../IndexProviderTest.java:1290).

Structures per (store, field):
  - inverted index  token -> {docid}           (TEXT mapping; textContains*)
  - exact index     value -> {docid}           (STRING mapping, Cmp.EQUAL)
  - every document's stored values             (filter fallback, orders)
Numeric/date range queries binary-search a sorted (value, docid) list that
is rebuilt lazily after writes. Geo queries bbox-prefilter then exact-test.
Queries under lock; snapshot semantics are per-call.
"""

from __future__ import annotations

import bisect
import re
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from janusgraph_tpu.core.predicates import (
    Contain,
    Cmp,
    Geo,
    Geoshape,
    Text,
    fuzzy_distance,
    levenshtein,
    tokenize,
)
from janusgraph_tpu.exceptions import BackendError
from janusgraph_tpu.indexing.provider import (
    And,
    IndexEntry,
    IndexFeatures,
    IndexMutation,
    IndexProvider,
    IndexQuery,
    KeyInformation,
    Mapping,
    Not,
    Or,
    PredicateCondition,
    RawQuery,
    register_index_provider,
)

_TEXT_PREDICATES = {
    Text.CONTAINS,
    Text.CONTAINS_PREFIX,
    Text.CONTAINS_REGEX,
    Text.CONTAINS_FUZZY,
    Text.CONTAINS_PHRASE,
}
# NOT_EQUAL is deliberately NOT index-pushable: the provider only knows
# documents that HAVE the field, while neq over the graph also matches
# vertices lacking the property — pushdown would silently drop those
# (the in-memory filter path keeps the full-scan semantics)
# Contain.NOT_IN is excluded for the same reason as NOT_EQUAL: `without`
# over the graph matches vertices LACKING the property, which no provider
# document represents. Contain.IN is a union of equality lookups.
_STRING_PREDICATES = {
    Cmp.EQUAL,
    Contain.IN,
    Text.PREFIX,
    Text.REGEX,
    Text.FUZZY,
}
_ORDER_PREDICATES = {
    Cmp.LESS_THAN,
    Cmp.LESS_THAN_EQUAL,
    Cmp.GREATER_THAN,
    Cmp.GREATER_THAN_EQUAL,
}


class _FieldIndex:
    def __init__(self, info: KeyInformation):
        self.info = info
        self.inverted: Dict[str, Set[str]] = defaultdict(set)
        self.exact: Dict[object, Set[str]] = defaultdict(set)
        self.values: Dict[str, List[object]] = defaultdict(list)
        self._sorted: Optional[List[Tuple[object, str]]] = None

    # ------------------------------------------------------------- mutation
    def _effective_mapping(self) -> Mapping:
        m = self.info.mapping
        if m == Mapping.DEFAULT:
            return Mapping.TEXT if self.info.data_type is str else Mapping.STRING
        return m

    def add(self, docid: str, value) -> None:
        self.values[docid].append(value)
        m = self._effective_mapping()
        if isinstance(value, str):
            if m in (Mapping.TEXT, Mapping.TEXTSTRING):
                for tok in tokenize(value):
                    self.inverted[tok].add(docid)
            if m in (Mapping.STRING, Mapping.TEXTSTRING):
                self.exact[value].add(docid)
        elif isinstance(value, Geoshape):
            pass  # geo: exact-test over stored values
        else:
            self.exact[value].add(docid)
        self._sorted = None

    def remove(self, docid: str, value) -> None:
        vals = self.values.get(docid)
        if vals is None:
            return
        try:
            vals.remove(value)
        except ValueError:
            return
        remaining = vals
        if not vals:
            del self.values[docid]
        # postings stay while ANY remaining value of the doc still justifies
        # them (LIST/SET cardinality, duplicate values)
        if isinstance(value, str):
            live_tokens = {
                t
                for v in remaining
                if isinstance(v, str)
                for t in tokenize(v)
            }
            for tok in tokenize(value):
                if tok in live_tokens:
                    continue
                s = self.inverted.get(tok)
                if s is not None:
                    s.discard(docid)
                    if not s:
                        del self.inverted[tok]
        if not isinstance(value, Geoshape) and value not in remaining:
            s = self.exact.get(value)
            if s is not None:
                s.discard(docid)
                if not s:
                    del self.exact[value]
        self._sorted = None

    def remove_doc(self, docid: str) -> None:
        for value in list(self.values.get(docid, ())):
            self.remove(docid, value)

    # --------------------------------------------------------------- search
    def sorted_values(self) -> List[Tuple[object, str]]:
        if self._sorted is None:
            pairs = [
                (v, docid)
                for docid, vals in self.values.items()
                for v in vals
                if not isinstance(v, Geoshape)
            ]
            # incomparable mixed types on one field are a schema bug; let the
            # TypeError surface rather than silently emptying range queries
            pairs.sort(key=lambda p: p[0])
            self._sorted = pairs
        return self._sorted

    def range_query(self, predicate, cond) -> Set[str]:
        pairs = self.sorted_values()
        keys = [p[0] for p in pairs]
        if predicate is Cmp.LESS_THAN:
            hi = bisect.bisect_left(keys, cond)
            sel = pairs[:hi]
        elif predicate is Cmp.LESS_THAN_EQUAL:
            hi = bisect.bisect_right(keys, cond)
            sel = pairs[:hi]
        elif predicate is Cmp.GREATER_THAN:
            lo = bisect.bisect_right(keys, cond)
            sel = pairs[lo:]
        else:
            lo = bisect.bisect_left(keys, cond)
            sel = pairs[lo:]
        return {d for _, d in sel}

    def query(self, predicate, cond) -> Set[str]:
        if predicate is Contain.IN:
            out: Set[str] = set()
            for v in cond:
                out |= self.query(Cmp.EQUAL, v)
            return out
        if predicate is Cmp.EQUAL:
            if isinstance(cond, Geoshape):
                return {
                    d
                    for d, vals in self.values.items()
                    if any(v == cond for v in vals)
                }
            return set(self.exact.get(cond, ()))
        if predicate is Cmp.NOT_EQUAL:
            return {
                d
                for d, vals in self.values.items()
                if any(v != cond for v in vals)
            }
        if predicate in _ORDER_PREDICATES:
            return self.range_query(predicate, cond)
        if predicate is Text.CONTAINS:
            want = tokenize(str(cond))
            if not want:
                return set()
            out: Optional[Set[str]] = None
            for t in want:
                s = self.inverted.get(t, set())
                out = set(s) if out is None else out & s
                if not out:
                    return set()
            return out
        if predicate is Text.CONTAINS_PREFIX:
            p = str(cond).lower()
            out: Set[str] = set()
            for tok, docs in self.inverted.items():
                if tok.startswith(p):
                    out |= docs
            return out
        if predicate is Text.CONTAINS_REGEX:
            rx = re.compile(str(cond))
            out = set()
            for tok, docs in self.inverted.items():
                if rx.fullmatch(tok):
                    out |= docs
            return out
        if predicate is Text.CONTAINS_FUZZY:
            t = str(cond).lower()
            cap = fuzzy_distance(t)
            out = set()
            for tok, docs in self.inverted.items():
                if levenshtein(tok, t, cap) <= cap:
                    out |= docs
            return out
        if predicate is Text.CONTAINS_PHRASE:
            return {
                d
                for d, vals in self.values.items()
                if any(
                    isinstance(v, str) and Text.CONTAINS_PHRASE.evaluate(v, cond)
                    for v in vals
                )
            }
        if predicate in (Text.PREFIX, Text.REGEX, Text.FUZZY):
            return {
                d
                for d, vals in self.values.items()
                if any(
                    isinstance(v, str) and predicate.evaluate(v, cond) for v in vals
                )
            }
        if predicate in (Geo.INTERSECT, Geo.DISJOINT, Geo.WITHIN, Geo.CONTAINS):
            return {
                d
                for d, vals in self.values.items()
                if any(
                    isinstance(v, Geoshape) and predicate.evaluate(v, cond)
                    for v in vals
                )
            }
        # unknown predicate: exact filter over stored values
        return {
            d
            for d, vals in self.values.items()
            if any(predicate.evaluate(v, cond) for v in vals)
        }


class _Store:
    def __init__(self):
        self.fields: Dict[str, _FieldIndex] = {}
        self.docs: Set[str] = set()


class InMemoryIndexProvider(IndexProvider):
    """The embedded mixed-index backend (registered as shorthand
    "memindex"; reference analogue: janusgraph-lucene embedded provider)."""

    name = "memindex"

    def __init__(self, **_kwargs):
        self._stores: Dict[str, _Store] = {}
        self._lock = threading.RLock()
        self._closed = False

    # ------------------------------------------------------------------ SPI
    def features(self) -> IndexFeatures:
        return IndexFeatures(
            supports_cardinality=("SINGLE", "LIST", "SET"), supports_geo=True
        )

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        with self._lock:
            s = self._stores.setdefault(store, _Store())
            existing = s.fields.get(key)
            if existing is not None and existing.info.mapping != info.mapping:
                raise BackendError(
                    f"field {key} already registered with mapping "
                    f"{existing.info.mapping}"
                )
            if existing is None:
                s.fields[key] = _FieldIndex(info)

    def _field(self, store: str, key: str, key_infos) -> _FieldIndex:
        s = self._stores.setdefault(store, _Store())
        f = s.fields.get(key)
        if f is None:
            info = (key_infos or {}).get(store, {}).get(
                key, KeyInformation(object)
            )
            f = s.fields[key] = _FieldIndex(info)
        return f

    def mutate(self, mutations, key_infos) -> None:
        with self._lock:
            for store, per_doc in mutations.items():
                s = self._stores.setdefault(store, _Store())
                for docid, m in per_doc.items():
                    if m.is_deleted:
                        for f in s.fields.values():
                            f.remove_doc(docid)
                        s.docs.discard(docid)
                        if not m.additions:
                            continue
                    for e in m.deletions:
                        self._field(store, e.field, key_infos).remove(
                            docid, e.value
                        )
                    for e in m.additions:
                        self._field(store, e.field, key_infos).add(docid, e.value)
                        s.docs.add(docid)

    def restore(self, documents, key_infos) -> None:
        with self._lock:
            for store, per_doc in documents.items():
                s = self._stores.setdefault(store, _Store())
                for docid, entries in per_doc.items():
                    for f in s.fields.values():
                        f.remove_doc(docid)
                    s.docs.discard(docid)
                    for e in entries:
                        self._field(store, e.field, key_infos).add(docid, e.value)
                        s.docs.add(docid)

    # ---------------------------------------------------------------- query
    def _evaluate(self, s: _Store, cond, key_infos=None) -> Set[str]:
        if isinstance(cond, PredicateCondition):
            f = s.fields.get(cond.key)
            if f is None:
                return set()
            return f.query(cond.predicate, cond.value)
        if isinstance(cond, And):
            out: Optional[Set[str]] = None
            for c in cond.children:
                r = self._evaluate(s, c)
                out = r if out is None else out & r
                if not out:
                    return set()
            return out if out is not None else set(s.docs)
        if isinstance(cond, Or):
            out: Set[str] = set()
            for c in cond.children:
                out |= self._evaluate(s, c)
            return out
        if isinstance(cond, Not):
            return set(s.docs) - self._evaluate(s, cond.child)
        raise BackendError(f"unsupported condition {cond!r}")

    def query(self, store: str, q: IndexQuery) -> List[str]:
        with self._lock:
            s = self._stores.get(store)
            if s is None:
                return []
            hits = self._evaluate(s, q.condition)
            if q.orders:

                def key_for(docid, o: Order):
                    f = s.fields.get(o.key)
                    vals = f.values.get(docid) if f else None
                    v = vals[0] if vals else None
                    return (v is None, v)

                # stable multi-key mixed-direction sort: apply one stable
                # sort per key from the LAST key to the FIRST, so earlier
                # keys dominate
                try:
                    result = sorted(hits)
                    for o in reversed(q.orders):
                        result = sorted(
                            result,
                            key=lambda d, _o=o: key_for(d, _o),
                            reverse=o.desc,
                        )
                except TypeError:
                    result = sorted(hits)
            else:
                result = sorted(hits)
            if q.offset:
                result = result[q.offset :]
            if q.limit is not None:
                result = result[: q.limit]
            return result

    _RAW_TERM = re.compile(r"(?:v\.)?\"?([\w.]+)\"?:(\S+)")

    def raw_query(self, store: str, q: RawQuery) -> List[Tuple[str, float]]:
        """Minimal `field:term [field:term ...]` syntax, OR across terms,
        score = number of matching terms (reference: RawQuery — provider
        query-string search with scores)."""
        with self._lock:
            s = self._stores.get(store)
            if s is None:
                return []
            scores: Dict[str, float] = defaultdict(float)
            terms = self._RAW_TERM.findall(q.query)
            if not terms:
                raise BackendError(f"unparseable raw query {q.query!r}")
            for fieldname, term in terms:
                f = s.fields.get(fieldname)
                if f is None:
                    continue
                hits = f.query(Text.CONTAINS, term)
                if not hits:
                    hits = f.query(Cmp.EQUAL, term)
                for d in hits:
                    scores[d] += 1.0
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
            if q.offset:
                ranked = ranked[q.offset :]
            if q.limit is not None:
                ranked = ranked[: q.limit]
            return ranked

    def totals(self, store: str, q: RawQuery) -> int:
        full = RawQuery(q.query, limit=None, offset=0)
        return len(self.raw_query(store, full))

    def supports(self, info: KeyInformation, predicate) -> bool:
        m = info.mapping
        if info.data_type is str:
            eff = (
                Mapping.TEXT
                if m in (Mapping.DEFAULT, Mapping.TEXT)
                else m
            )
            if predicate in _TEXT_PREDICATES:
                return eff in (Mapping.TEXT, Mapping.TEXTSTRING)
            if predicate in _STRING_PREDICATES:
                return eff in (Mapping.STRING, Mapping.TEXTSTRING)
            return False
        if info.data_type is Geoshape:
            return predicate in (
                Geo.INTERSECT,
                Geo.DISJOINT,
                Geo.WITHIN,
                Geo.CONTAINS,
                Cmp.EQUAL,
                Contain.IN,
            )
        return predicate in _STRING_PREDICATES | _ORDER_PREDICATES

    def exists(self) -> bool:
        return bool(self._stores)

    def close(self) -> None:
        self._closed = True

    def clear_storage(self) -> None:
        with self._lock:
            self._stores = {}


register_index_provider("memindex", InMemoryIndexProvider)
