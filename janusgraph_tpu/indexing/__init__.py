"""Mixed (full-text / range / geo) index provider subsystem.

reference: diskstorage/indexing/ — IndexProvider.java:36 SPI,
IndexTransaction.java buffered mutations, IndexQuery.java condition trees;
providers janusgraph-es/janusgraph-lucene/janusgraph-solr.
"""

from janusgraph_tpu.indexing.provider import (
    And,
    IndexEntry,
    IndexFeatures,
    IndexMutation,
    IndexProvider,
    IndexQuery,
    IndexTransaction,
    KeyInformation,
    Mapping,
    Not,
    Or,
    Order,
    PredicateCondition,
    RawQuery,
    register_index_provider,
    open_index_provider,
)
from janusgraph_tpu.indexing.memindex import InMemoryIndexProvider
from janusgraph_tpu.indexing.localindex import LocalIndexProvider
from janusgraph_tpu.indexing.remote import (
    RemoteIndexProvider,
    RemoteIndexServer,
)

__all__ = [
    "And",
    "IndexEntry",
    "IndexFeatures",
    "IndexMutation",
    "IndexProvider",
    "IndexQuery",
    "IndexTransaction",
    "InMemoryIndexProvider",
    "LocalIndexProvider",
    "RemoteIndexProvider",
    "RemoteIndexServer",
    "KeyInformation",
    "Mapping",
    "Not",
    "Or",
    "Order",
    "PredicateCondition",
    "RawQuery",
    "register_index_provider",
    "open_index_provider",
]
