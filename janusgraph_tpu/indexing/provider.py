"""Index provider SPI: the contract every mixed-index backend implements.

Capability parity with the reference's indexing SPI (reference:
diskstorage/indexing/IndexProvider.java:36 — register/mutate/query/
raw_query/totals/restore/exists/close/clearStorage + supports();
IndexMutation.java — per-document add/delete entry lists with isNew/
isDeleted; IndexTransaction.java:1 — transaction-scoped mutation buffer
flushed at commit; IndexQuery condition tree And/Or/Not/PredicateCondition
with orders and limits; RawQuery for provider-syntax string queries).

Design divergence from the reference: conditions are tiny frozen dataclasses
evaluated by each provider directly (no TinkerPop Condition hierarchy), and
document values are plain Python objects — the serializer boundary lives in
the graph layer, not here.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from janusgraph_tpu.core.predicates import Predicate
from janusgraph_tpu.exceptions import ConfigurationError


class Mapping(Enum):
    """How a string key is indexed (reference:
    core/schema/Mapping.java — DEFAULT/TEXT/STRING/TEXTSTRING)."""

    DEFAULT = "DEFAULT"
    TEXT = "TEXT"
    STRING = "STRING"
    TEXTSTRING = "TEXTSTRING"


@dataclass(frozen=True)
class KeyInformation:
    """Per-field index metadata (reference:
    diskstorage/indexing/KeyInformation.java — data type + parameters)."""

    data_type: type
    mapping: Mapping = Mapping.DEFAULT
    cardinality: str = "SINGLE"


@dataclass(frozen=True)
class PredicateCondition:
    key: str
    predicate: Predicate
    value: object


@dataclass(frozen=True)
class And:
    children: Tuple[object, ...]


@dataclass(frozen=True)
class Or:
    children: Tuple[object, ...]


@dataclass(frozen=True)
class Not:
    child: object


@dataclass(frozen=True)
class Order:
    key: str
    desc: bool = False


@dataclass(frozen=True)
class IndexQuery:
    """reference: diskstorage/indexing/IndexQuery.java."""

    condition: object
    orders: Tuple[Order, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class RawQuery:
    """Provider-syntax string query (reference:
    diskstorage/indexing/RawQuery.java)."""

    query: str
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class IndexEntry:
    field: str
    value: object


class IndexMutation:
    """Per-document pending change set (reference:
    diskstorage/indexing/IndexMutation.java)."""

    def __init__(self, is_new: bool = False, is_deleted: bool = False):
        self.additions: List[IndexEntry] = []
        self.deletions: List[IndexEntry] = []
        self.is_new = is_new
        self.is_deleted = is_deleted

    def add(self, field: str, value) -> None:
        self.additions.append(IndexEntry(field, value))

    def delete(self, field: str, value) -> None:
        self.deletions.append(IndexEntry(field, value))

    def merge(self, other: "IndexMutation") -> None:
        self.additions.extend(other.additions)
        self.deletions.extend(other.deletions)
        self.is_new = self.is_new or other.is_new
        self.is_deleted = self.is_deleted or other.is_deleted


@dataclass(frozen=True)
class IndexFeatures:
    """Capability flags (reference:
    diskstorage/indexing/IndexFeatures.java)."""

    supports_document_ttl: bool = False
    supports_cardinality: Tuple[str, ...] = ("SINGLE",)
    supports_custom_analyzer: bool = False
    supports_geo: bool = True
    supports_not_query_normal_form: bool = True


class IndexProvider:
    """The mixed-index backend SPI (reference: IndexProvider.java:36)."""

    name = "abstract"

    def features(self) -> IndexFeatures:
        return IndexFeatures()

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        """Declare a field before writing documents that use it
        (reference: IndexProvider.register)."""
        raise NotImplementedError

    def mutate(
        self,
        mutations: Dict[str, Dict[str, IndexMutation]],
        key_infos: Dict[str, Dict[str, KeyInformation]],
    ) -> None:
        """Apply {store -> {docid -> mutation}} (reference:
        IndexProvider.mutate)."""
        raise NotImplementedError

    def restore(
        self,
        documents: Dict[str, Dict[str, List[IndexEntry]]],
        key_infos: Dict[str, Dict[str, KeyInformation]],
    ) -> None:
        """Overwrite documents from authoritative primary-storage state
        (reference: IndexProvider.restore — used by recovery + reindex)."""
        raise NotImplementedError

    def query(self, store: str, q: IndexQuery) -> List[str]:
        raise NotImplementedError

    #: index.search.scroll-page-size (set by open_index_provider)
    scroll_page_size = 1000

    def query_stream(
        self, store: str, q: IndexQuery, page_size: Optional[int] = None
    ):
        """Stream hits in pages — the scroll-API analogue in PURPOSE
        (reference: janusgraph-es .../ElasticSearchScroll.java:80 pages
        large result sets instead of materializing them), not in isolation
        level: this is offset-window paging, with each page reading the
        provider's CURRENT committed state. Under concurrent mutation a
        shifting window can skip or repeat a document — run sweeps that
        need exactly-once visitation (reindex/restore) against a quiesced
        index, or use a single bounded query(). The remote provider issues
        one bounded wire call per page."""
        if page_size is None:
            page_size = self.scroll_page_size
        offset = q.offset
        remaining = q.limit
        while True:
            page = page_size if remaining is None else min(page_size, remaining)
            if page <= 0:
                return
            hits = self.query(
                store, IndexQuery(q.condition, q.orders, page, offset)
            )
            yield from hits
            if len(hits) < page:
                return
            offset += len(hits)
            if remaining is not None:
                remaining -= len(hits)

    def raw_query(self, store: str, q: RawQuery) -> List[Tuple[str, float]]:
        raise NotImplementedError

    def totals(self, store: str, q: RawQuery) -> int:
        raise NotImplementedError

    def supports(self, info: KeyInformation, predicate: Predicate) -> bool:
        raise NotImplementedError

    def exists(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def clear_storage(self) -> None:
        raise NotImplementedError


class IndexTransaction:
    """Buffers document mutations for one graph transaction and flushes them
    in a single provider.mutate call at commit (reference:
    diskstorage/indexing/IndexTransaction.java — register/add/delete then
    flushInternal)."""

    def __init__(self, provider: IndexProvider, key_informations):
        self.provider = provider
        self._key_infos = key_informations  # {store: {field: KeyInformation}}
        self._mutations: Dict[str, Dict[str, IndexMutation]] = {}

    def _mutation(self, store: str, docid: str) -> IndexMutation:
        return self._mutations.setdefault(store, {}).setdefault(
            docid, IndexMutation()
        )

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        self._key_infos.setdefault(store, {})[key] = info
        self.provider.register(store, key, info)

    def add(self, store: str, docid: str, field: str, value, is_new=False) -> None:
        m = self._mutation(store, docid)
        m.is_new = m.is_new or is_new
        m.add(field, value)

    def delete(
        self, store: str, docid: str, field: str, value, delete_all=False
    ) -> None:
        m = self._mutation(store, docid)
        m.is_deleted = m.is_deleted or delete_all
        if field is not None:
            m.delete(field, value)

    def has_mutations(self) -> bool:
        return bool(self._mutations)

    def commit(self) -> None:
        if self._mutations:
            self.provider.mutate(self._mutations, self._key_infos)
            self._mutations = {}

    def rollback(self) -> None:
        self._mutations = {}

    # queries pass straight through (reads see committed index state only,
    # matching the reference's mixed-index visibility semantics)
    def query(self, store: str, q: IndexQuery) -> List[str]:
        return self.provider.query(store, q)

    def raw_query(self, store: str, q: RawQuery):
        return self.provider.raw_query(store, q)


_PROVIDERS: Dict[str, Callable[..., IndexProvider]] = {}
_PROVIDERS_LOCK = threading.Lock()


def register_index_provider(name: str, factory) -> None:
    """Shorthand registry (reference: StandardIndexProvider.java — the
    es/lucene/solr shorthand map)."""
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = factory


def open_index_provider(
    name: str, scroll_page_size: Optional[int] = None, **kwargs
) -> IndexProvider:
    with _PROVIDERS_LOCK:
        factory = _PROVIDERS.get(name)
    if factory is None:
        raise ConfigurationError(f"unknown index backend {name!r}")
    provider = factory(**kwargs)
    if scroll_page_size:
        provider.scroll_page_size = scroll_page_size
    return provider
