"""Networked mixed-index provider: TCP server/client for the IndexProvider SPI.

The reference's flagship index tier is a REMOTE service spoken to over a
wire protocol with a connection pool and retries (reference:
janusgraph-es .../diskstorage/es/ElasticSearchIndex.java:1355 and
.../es/rest/RestElasticSearchClient.java:505 — REST calls against an
external Elasticsearch). The TPU-native framework keeps the same split:
any in-process provider (localindex — the Lucene analogue — or memindex)
can be served over TCP by `RemoteIndexServer`, and `RemoteIndexProvider`
is a full IndexProvider whose calls cross the wire.

Protocol: same length-prefixed `[len:4][op:1][body]` -> `[len:4][status:1]
[body]` framing, pooled connections, and temporary/permanent status split
as the remote KCVS adapter (storage/remote.py), with the retry guard
(storage/backend_op.py) around every call. Attribute values ride the core
serializer's self-describing `[type_id:2][payload]` framing
(core/attributes.py), so every registered datatype — Geoshape included —
works over the wire without an index-specific codec.
"""

from __future__ import annotations

import struct
import threading
import socketserver
from typing import Dict, List, Optional, Tuple

from janusgraph_tpu.core.attributes import Serializer
from janusgraph_tpu.core.predicates import predicate_by_name
from janusgraph_tpu.exceptions import (
    PermanentBackendError,
    TemporaryBackendError,
)
from janusgraph_tpu.indexing.provider import (
    And,
    IndexEntry,
    IndexFeatures,
    IndexMutation,
    IndexProvider,
    IndexQuery,
    KeyInformation,
    Mapping,
    Not,
    Or,
    Order,
    PredicateCondition,
    RawQuery,
    register_index_provider,
)
from janusgraph_tpu.storage import backend_op
from janusgraph_tpu.storage.remote import (
    _DEADLINE_FLAG,
    _PIPELINE_FLAG,
    _FLAG_MASK,
    _LEDGER_FLAG,
    _TRACE_FLAG,
    _Conn,
    _deadline_guard,
    _pb,
    _ps,
    _raise_status,
    _Reader,
    _recv_exact,
    encode_deadline_prefix,
    encode_trace_prefix,
    split_deadline_prefix,
    split_trace_prefix,
)

_STATUS_OK = 0
_STATUS_TEMP = 1
_STATUS_PERM = 2

_OP_REGISTER = 1
_OP_MUTATE = 2
_OP_RESTORE = 3
_OP_QUERY = 4
_OP_RAW_QUERY = 5
_OP_TOTALS = 6
_OP_SUPPORTS = 7
_OP_EXISTS = 8
_OP_CLEAR = 9
_OP_FEATURES = 10
#: batch carrier for pipelined framing (storage/pipeline.iter_batch)
_OP_BATCH = 11

_OP_NAMES = {
    _OP_REGISTER: "register",
    _OP_MUTATE: "mutate",
    _OP_RESTORE: "restore",
    _OP_QUERY: "query",
    _OP_RAW_QUERY: "rawQuery",
    _OP_TOTALS: "totals",
    _OP_SUPPORTS: "supports",
    _OP_EXISTS: "exists",
    _OP_CLEAR: "clear",
    _OP_FEATURES: "features",
    _OP_BATCH: "pipelineBatch",
}

#: index ops that may ride pipelined frames: idempotent request/response
#: ops only — mutate/restore keep the sync dial-only-retry discipline
#: (their at-least-once hazards predate pipelining), and features is the
#: negotiation itself
_PIPELINEABLE_OPS = frozenset(
    (_OP_REGISTER, _OP_QUERY, _OP_RAW_QUERY, _OP_TOTALS, _OP_SUPPORTS,
     _OP_EXISTS)
)

#: one registry for the wire; user enums are not expected in index fields.
#: allow_pickle=False: a network peer must never be able to ship a pickle
#: payload into this process (see PickledObjectSerializer)
_SER = Serializer(allow_pickle=False)


# ------------------------------------------------------------------ encoding
def _pv(out: List[bytes], value) -> None:
    """Length-prefixed self-describing value frame."""
    _pb(out, _SER.write_object(value))


def _rv(r: _Reader):
    value, _ = _SER.read_object(r.bytes_())
    return value


def _encode_keyinfo(out: List[bytes], info: KeyInformation) -> None:
    out.append(struct.pack(">H", _SER.data_type_id(info.data_type)))
    _ps(out, info.mapping.value)
    _ps(out, info.cardinality)


def _decode_keyinfo(r: _Reader) -> KeyInformation:
    (tid,) = struct.unpack_from(">H", r.data, r.off)
    r.off += 2
    return KeyInformation(
        data_type=_SER.type_for_id(tid),
        mapping=Mapping(r.str_()),
        cardinality=r.str_(),
    )


def _encode_condition(out: List[bytes], cond) -> None:
    if isinstance(cond, PredicateCondition):
        out.append(b"\x00")
        _ps(out, cond.key)
        _ps(out, cond.predicate.name)
        _pv(out, cond.value)
    elif isinstance(cond, (And, Or)):
        out.append(b"\x01" if isinstance(cond, And) else b"\x02")
        out.append(struct.pack(">I", len(cond.children)))
        for c in cond.children:
            _encode_condition(out, c)
    elif isinstance(cond, Not):
        out.append(b"\x03")
        _encode_condition(out, cond.child)
    else:
        raise PermanentBackendError(
            f"unencodable condition {type(cond).__name__}"
        )


def _decode_condition(r: _Reader):
    tag = r.u8()
    if tag == 0:
        key = r.str_()
        pname = r.str_()
        pred = predicate_by_name(pname)
        if pred is None:
            raise PermanentBackendError(f"unknown predicate {pname!r}")
        return PredicateCondition(key, pred, _rv(r))
    if tag in (1, 2):
        n = r.u32()
        children = tuple(_decode_condition(r) for _ in range(n))
        return And(children) if tag == 1 else Or(children)
    if tag == 3:
        return Not(_decode_condition(r))
    raise PermanentBackendError(f"unknown condition tag {tag}")


def _encode_key_infos(out: List[bytes], key_infos) -> None:
    out.append(struct.pack(">I", len(key_infos)))
    for store, fields in key_infos.items():
        _ps(out, store)
        out.append(struct.pack(">I", len(fields)))
        for fname, info in fields.items():
            _ps(out, fname)
            _encode_keyinfo(out, info)


def _decode_key_infos(r: _Reader) -> Dict[str, Dict[str, KeyInformation]]:
    # explicit loops: the wire layout depends on strict read order, which
    # comprehension key/value evaluation order would leave implicit
    infos: Dict[str, Dict[str, KeyInformation]] = {}
    for _ in range(r.u32()):
        store = r.str_()
        fields: Dict[str, KeyInformation] = {}
        for _ in range(r.u32()):
            fname = r.str_()
            fields[fname] = _decode_keyinfo(r)
        infos[store] = fields
    return infos


def _encode_entries(out: List[bytes], entries: List[IndexEntry]) -> None:
    out.append(struct.pack(">I", len(entries)))
    for e in entries:
        _ps(out, e.field)
        _pv(out, e.value)


def _decode_entries(r: _Reader) -> List[IndexEntry]:
    entries = []
    for _ in range(r.u32()):
        field = r.str_()
        entries.append(IndexEntry(field, _rv(r)))
    return entries


def _encode_raw(out: List[bytes], q: RawQuery) -> None:
    _ps(out, q.query)
    out.append(struct.pack(">iI", -1 if q.limit is None else q.limit,
                           q.offset))


def _decode_raw(r: _Reader) -> RawQuery:
    query = r.str_()
    limit, offset = struct.unpack_from(">iI", r.data, r.off)
    r.off += 8
    return RawQuery(query, None if limit < 0 else limit, offset)


# -------------------------------------------------------------------- server
class _IndexHandler(socketserver.BaseRequestHandler):
    #: per flagged request: measured costs, prepended to the OK reply
    _led = None
    _op_t0 = 0

    def handle(self):
        import time as _time

        provider = self.server.provider  # type: ignore[attr-defined]
        sock = self.request
        pipe = None
        try:
            while True:
                try:
                    head = _recv_exact(sock, 5)
                except ConnectionError:
                    return
                (body_len,) = struct.unpack(">I", head[:4])
                raw = head[4]
                op = raw & ~_FLAG_MASK
                body = _recv_exact(sock, body_len) if body_len else b""
                if raw & _PIPELINE_FLAG:
                    if not getattr(self.server, "pipeline", True):
                        # pre-pipeline server: the 0x10 bit stays in the
                        # op byte -> unknown op (byte-identical old
                        # behavior; compliant clients never send this)
                        op = raw & ~(
                            _TRACE_FLAG | _LEDGER_FLAG | _DEADLINE_FLAG
                        )
                    else:
                        from janusgraph_tpu.storage.pipeline import (
                            ServerPipeline,
                            _InlineReply,
                            iter_batch,
                        )

                        if pipe is None:
                            pipe = ServerPipeline(sock, workers=getattr(
                                self.server, "pipeline_workers", 4
                            ))
                        t_arr = _time.monotonic()
                        if op != _OP_BATCH and pipe.serve_inline_ok():
                            self._serve_pipelined(
                                provider, _InlineReply(pipe), raw, body,
                                t_arr,
                            )
                            pipe.note_duration(
                                _time.monotonic() - t_arr
                            )
                            continue
                        subs = (
                            list(iter_batch(body))
                            if op == _OP_BATCH else [(raw, body)]
                        )
                        for sub_raw, sub_body in subs:
                            pipe.submit_op(
                                self._serve_pipelined, provider,
                                sub_raw, sub_body, t_arr,
                            )
                        continue
                ctx = None
                if raw & _TRACE_FLAG:
                    ctx, body = split_trace_prefix(body)
                budget_ms = None
                if raw & _DEADLINE_FLAG:
                    budget_ms, body = split_deadline_prefix(body)
                self._led = {} if raw & _LEDGER_FLAG else None
                self._op_t0 = _time.perf_counter_ns()
                try:
                    # inherit the caller's remaining budget (an op that
                    # arrives already-expired is refused permanently)
                    with _deadline_guard(budget_ms):
                        if ctx is not None:
                            from janusgraph_tpu.observability import tracer

                            # the index node's op joins the caller's trace
                            with tracer.child_span(
                                ctx, f"index.remote.{_OP_NAMES.get(op, op)}"
                            ) as sp:
                                self._dispatch(provider, sock, op, body)
                                if self._led:
                                    # index node owns these measurements
                                    # (the client merges the echo
                                    # un-annotated)
                                    sp.annotate(**{
                                        f"ledger.{k}": v
                                        for k, v in self._led.items()
                                        if k != "wall_ns"
                                    })
                        else:
                            self._dispatch(provider, sock, op, body)
                # graphlint: disable=JG204 -- protocol boundary: the error is serialized to the client as a temporary status frame, and the CLIENT retries
                except (TemporaryBackendError, ConnectionError) as e:
                    self._reply(sock, _STATUS_TEMP, str(e).encode())
                except Exception as e:  # noqa: BLE001 - protocol boundary
                    self._reply(
                        sock, _STATUS_PERM,
                        f"{type(e).__name__}: {e}".encode(),
                    )
                finally:
                    self._led = None
        except (ConnectionResetError, BrokenPipeError):
            return
        finally:
            if pipe is not None:
                pipe.close()

    def _serve_pipelined(self, provider, out, raw, body, t_arrival) -> None:
        """One pipelined index sub-op: per-op trace span, deadline
        guard, and ledger echo, replied by request id. Runs on a pool
        thread — all state local, never on the handler instance."""
        import time as _time

        op = raw & ~_FLAG_MASK
        (req_id,) = struct.unpack_from(">I", body, 0)
        body = body[4:]
        ctx = None
        if raw & _TRACE_FLAG:
            ctx, body = split_trace_prefix(body)
        budget_ms = None
        if raw & _DEADLINE_FLAG:
            budget_ms, body = split_deadline_prefix(body)
            if budget_ms is not None:
                # dispatch-queue dwell counts against the op's budget
                budget_ms -= (_time.monotonic() - t_arrival) * 1000.0
        led = {} if raw & _LEDGER_FLAG else None
        t0 = _time.perf_counter_ns()
        try:
            with _deadline_guard(budget_ms):
                if ctx is not None:
                    from janusgraph_tpu.observability import tracer

                    with tracer.child_span(
                        ctx, f"index.remote.{_OP_NAMES.get(op, op)}",
                        pipelined=True,
                    ) as sp:
                        payload = self._execute(provider, op, body, led)
                        if led:
                            sp.annotate(**{
                                f"ledger.{k}": v
                                for k, v in led.items()
                                if k != "wall_ns"
                            })
                else:
                    payload = self._execute(provider, op, body, led)
            if led is not None:
                from janusgraph_tpu.observability.profiler import (
                    encode_ledger_block,
                )

                led["wall_ns"] = _time.perf_counter_ns() - t0
                payload = encode_ledger_block(led) + payload
            out.reply(req_id, _STATUS_OK, payload)
        # graphlint: disable=JG204 -- protocol boundary: the error is serialized to the client as a temporary status frame addressed to this op's request id, and the CLIENT retries
        except (TemporaryBackendError, ConnectionError) as e:
            out.reply(req_id, _STATUS_TEMP, str(e).encode())
        except Exception as e:  # noqa: BLE001 - protocol boundary
            out.reply(
                req_id, _STATUS_PERM, f"{type(e).__name__}: {e}".encode()
            )

    def _reply(self, sock, status: int, body: bytes) -> None:
        if self._led is not None and status == _STATUS_OK:
            import time as _time

            from janusgraph_tpu.observability.profiler import (
                encode_ledger_block,
            )

            self._led["wall_ns"] = _time.perf_counter_ns() - self._op_t0
            body = encode_ledger_block(self._led) + body
        sock.sendall(struct.pack(">IB", len(body), status) + body)

    def _dispatch(self, provider, sock, op: int, body: bytes) -> None:
        if op == _OP_FEATURES:
            self._reply(
                sock, _STATUS_OK, self._features_payload(provider)
            )
            return
        self._reply(
            sock, _STATUS_OK,
            self._execute(provider, op, body, self._led),
        )

    def _features_payload(self, provider) -> bytes:
        f = provider.features()
        out = [
            bytes([int(f.supports_document_ttl),
                   int(f.supports_custom_analyzer),
                   int(f.supports_geo),
                   int(f.supports_not_query_normal_form)]),
            struct.pack(">I", len(f.supports_cardinality)),
        ]
        for c in f.supports_cardinality:
            _ps(out, c)
        # trailing protocol-capability bytes, positional: [trace] then
        # [ledger] then [deadline] then [pipeline]. Old clients stop
        # reading after the cardinalities (or after however many
        # capability bytes they know), so extra bytes are invisible to
        # them; old servers simply end the payload earlier and new
        # clients negotiate the capability OFF. Every earlier byte is
        # always written when a later one is, so positions stay
        # unambiguous.
        trace_on = getattr(self.server, "trace_propagation", True)
        ledger_on = getattr(self.server, "ledger_echo", True)
        deadline_on = getattr(self.server, "deadline_propagation", True)
        pipeline_on = getattr(self.server, "pipeline", True)
        if trace_on or ledger_on or deadline_on or pipeline_on:
            out.append(b"\x01" if trace_on else b"\x00")
        if ledger_on or deadline_on or pipeline_on:
            out.append(b"\x01" if ledger_on else b"\x00")
        if deadline_on or pipeline_on:
            out.append(b"\x01" if deadline_on else b"\x00")
        if pipeline_on:
            out.append(b"\x01")
        return b"".join(out)

    def _execute(self, provider, op: int, body: bytes, led) -> bytes:
        """One index op -> OK payload bytes (shared by the sync
        dispatch and the pipelined per-sub-op path)."""
        r = _Reader(body)
        if op == _OP_REGISTER:
            store, key = r.str_(), r.str_()
            provider.register(store, key, _decode_keyinfo(r))
            return b""
        if op == _OP_MUTATE:
            muts: Dict[str, Dict[str, IndexMutation]] = {}
            for _ in range(r.u32()):
                store = r.str_()
                per_doc = muts.setdefault(store, {})
                for _ in range(r.u32()):
                    docid = r.str_()
                    flags = r.u8()
                    m = IndexMutation(
                        is_new=bool(flags & 1), is_deleted=bool(flags & 2)
                    )
                    m.additions.extend(_decode_entries(r))
                    m.deletions.extend(_decode_entries(r))
                    per_doc[docid] = m
            if led is not None:
                led["cells_written"] = sum(
                    len(m.additions) + len(m.deletions)
                    for per_doc in muts.values()
                    for m in per_doc.values()
                )
            provider.mutate(muts, _decode_key_infos(r))
            return b""
        if op == _OP_RESTORE:
            docs: Dict[str, Dict[str, List[IndexEntry]]] = {}
            for _ in range(r.u32()):
                store = r.str_()
                per_doc = docs.setdefault(store, {})
                for _ in range(r.u32()):
                    docid = r.str_()
                    per_doc[docid] = _decode_entries(r)
            provider.restore(docs, _decode_key_infos(r))
            return b""
        if op == _OP_QUERY:
            store = r.str_()
            cond = _decode_condition(r)
            orders = tuple(
                Order(r.str_(), bool(r.u8())) for _ in range(r.u32())
            )
            limit, offset = struct.unpack_from(">iI", r.data, r.off)
            r.off += 8
            q = IndexQuery(
                cond, orders, None if limit < 0 else limit, offset
            )
            hits = provider.query(store, q)
            if led is not None:
                led["index_hits"] = len(hits)
            out: List[bytes] = [struct.pack(">I", len(hits))]
            for h in hits:
                _ps(out, h)
            return b"".join(out)
        if op == _OP_RAW_QUERY:
            store = r.str_()
            hits = provider.raw_query(store, _decode_raw(r))
            if led is not None:
                led["index_hits"] = len(hits)
            out = [struct.pack(">I", len(hits))]
            for docid, score in hits:
                _ps(out, docid)
                out.append(struct.pack(">d", float(score)))
            return b"".join(out)
        if op == _OP_TOTALS:
            store = r.str_()
            n = provider.totals(store, _decode_raw(r))
            return struct.pack(">Q", n)
        if op == _OP_SUPPORTS:
            info = _decode_keyinfo(r)
            pred = predicate_by_name(r.str_())
            ok = pred is not None and provider.supports(info, pred)
            return b"\x01" if ok else b"\x00"
        if op == _OP_EXISTS:
            return b"\x01" if provider.exists() else b"\x00"
        if op == _OP_CLEAR:
            provider.clear_storage()
            return b""
        if op in (_OP_FEATURES, _OP_BATCH):
            raise PermanentBackendError(
                f"op {_OP_NAMES.get(op, op)} is not pipelineable"
            )
        raise PermanentBackendError(f"unknown index op {op}")


class RemoteIndexServer:
    """Serve any IndexProvider over TCP (threaded; port 0 = ephemeral).
    ``trace_propagation=False`` = the pre-trace features payload,
    ``ledger_echo=False`` the pre-ledger one, ``deadline_propagation=
    False`` the pre-deadline one ("old-featured" index servers for
    compatibility tests)."""

    def __init__(self, provider: IndexProvider, host: str = "127.0.0.1",
                 port: int = 0, trace_propagation: bool = True,
                 ledger_echo: bool = True,
                 deadline_propagation: bool = True,
                 pipeline: bool = True, pipeline_workers: int = 4):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _IndexHandler)
        self._srv.provider = provider  # type: ignore[attr-defined]
        self._srv.trace_propagation = trace_propagation  # type: ignore[attr-defined]
        self._srv.ledger_echo = ledger_echo  # type: ignore[attr-defined]
        self._srv.deadline_propagation = deadline_propagation  # type: ignore[attr-defined]
        self._srv.pipeline = pipeline  # type: ignore[attr-defined]
        self._srv.pipeline_workers = pipeline_workers  # type: ignore[attr-defined]
        self.provider = provider
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.server_address  # type: ignore[return-value]

    def start(self) -> "RemoteIndexServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="index-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# -------------------------------------------------------------------- client
class RemoteIndexProvider(IndexProvider):
    """Client-side IndexProvider speaking the remote index protocol —
    the janusgraph-es analogue (RestElasticSearchClient.java:505: pooled
    REST client with request retries)."""

    name = "remote"

    def __init__(self, hostname: str = "127.0.0.1", port: int = 0,
                 pool_size: int = 4, retry_time_s: float = 10.0,
                 directory: str = None,
                 breaker_enabled: bool = False,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_ms: float = 1000.0,
                 breaker_half_open_probes: int = 1,
                 trace_propagation: bool = True,
                 resource_ledger: bool = True,
                 deadline_propagation: bool = True,
                 pipeline: bool = True,
                 pipeline_connections: int = 2,
                 pipeline_depth: int = 128,
                 pipeline_max_batch: int = 64,
                 pipeline_coalesce_us: float = 150.0,
                 **_ignored):
        # `directory` accepted-and-ignored: open_index_provider passes the
        # local providers' kwargs through one call site (core/graph.py)
        if not hostname or int(port) <= 0:
            from janusgraph_tpu.exceptions import ConfigurationError

            raise ConfigurationError(
                "index backend 'remote' requires index.search.hostname and "
                f"a positive index.search.port (got {hostname!r}:{port!r})"
            )
        self.host, self.port = hostname, int(port)
        self.retry_time_s = retry_time_s
        #: metrics.trace-propagation, gated on the server's negotiated
        #: capability byte (None = features not yet fetched)
        self.trace_propagation = trace_propagation
        self._remote_trace: Optional[bool] = None
        #: metrics.resource-ledger, gated on the second capability byte
        self.resource_ledger = resource_ledger
        self._remote_ledger: Optional[bool] = None
        #: server.deadline.propagation, gated on the third capability byte
        self.deadline_propagation = deadline_propagation
        self._remote_deadline: Optional[bool] = None
        #: index.search.pipeline, gated on the fourth capability byte:
        #: idempotent index ops (query/rawQuery/totals/supports/exists/
        #: register) ride pipelined frames once engaged; mutate/restore
        #: keep the sync dial-only-retry discipline
        self.pipeline = pipeline
        self.pipeline_connections = pipeline_connections
        self.pipeline_depth = pipeline_depth
        self.pipeline_max_batch = pipeline_max_batch
        self.pipeline_coalesce_us = pipeline_coalesce_us
        self._remote_pipeline: Optional[bool] = None
        self._mux = None
        self._mux_lock = threading.Lock()
        self._calls_active = 0
        self._op_ewma_s = 0.0
        #: the provider accounts index hits itself (echo or local
        #: fallback), so graph.mixed_index_query must not count them again
        self.ledger_self_accounting = True
        self._pool = [_Conn(self.host, self.port) for _ in range(pool_size)]
        # whether this thread's last _call carried a ledger echo (drives
        # the old-server fallback accounting in query/raw_query)
        self._tls = threading.local()
        self._pool_lock = threading.Lock()
        self._pool_idx = 0
        self._features: Optional[IndexFeatures] = None
        self._supports_memo: Dict[Tuple, bool] = {}
        # same storage.breaker.* machinery as the remote KCVS client: a
        # down index tier fails fast instead of serializing every commit
        # behind a full retry budget
        self.breaker = None
        if breaker_enabled:
            from janusgraph_tpu.storage.circuit import CircuitBreaker

            self.breaker = CircuitBreaker(
                "index.remote",
                failure_threshold=breaker_failure_threshold,
                reset_timeout_s=breaker_reset_ms / 1000.0,
                half_open_probes=breaker_half_open_probes,
            )

    def _frame_parts(self, op: int):
        """Same negotiation as RemoteStoreManager._frame_parts: returns
        (flags, trace_prefix, want_ledger, expires_at); the deadline
        prefix is encoded at send time from expires_at."""
        if op == _OP_FEATURES:
            return 0, b"", False, None
        import time as _time

        from janusgraph_tpu.core.deadline import remaining_ms
        from janusgraph_tpu.observability import tracer
        from janusgraph_tpu.observability.profiler import current_ledger

        ctx = tracer.current_context() if self.trace_propagation else None
        led = current_ledger() if self.resource_ledger else None
        budget = remaining_ms() if self.deadline_propagation else None
        if ctx is None and led is None and budget is None:
            return 0, b"", False, None
        if (self._remote_trace is None or self._remote_ledger is None
                or self._remote_deadline is None):
            try:
                self.features()
            # graphlint: disable=JG204 -- negotiation is best-effort: the frame just goes unflagged, and the op itself will surface the failure through its own retry guard
            except (TemporaryBackendError, PermanentBackendError):
                return 0, b"", False, None
        flags = 0
        prefix = b""
        expires_at = None
        if budget is not None and self._remote_deadline:
            flags |= _DEADLINE_FLAG
            expires_at = _time.monotonic() + budget / 1000.0
        if ctx is not None and self._remote_trace:
            flags |= _TRACE_FLAG
            prefix = encode_trace_prefix(ctx)
        if led is not None and self._remote_ledger:
            flags |= _LEDGER_FLAG
        return flags, prefix, bool(flags & _LEDGER_FLAG), expires_at

    def _frame(self, op: int, body: bytes):
        """Synchronous-framing view: (op|flags, body with prefixes,
        want_ledger) — trace prefix outside the deadline prefix."""
        import time as _time

        flags, prefix, want_ledger, expires_at = self._frame_parts(op)
        if flags & _DEADLINE_FLAG:
            prefix = prefix + encode_deadline_prefix(
                max(0.0, (expires_at - _time.monotonic()) * 1000.0)
            )
        return op | flags, prefix + body, want_ledger

    def _should_pipeline(self) -> bool:
        """Same adaptive gate as the remote KCVS client: engage when
        latency-dominated concurrency outgrows the pool, or while ops
        are already in flight on the mux."""
        if not self.pipeline:
            return False
        if self._mux is not None and self._mux.busy():
            return True
        from janusgraph_tpu.storage.remote import RemoteStoreManager

        return (
            self._calls_active > len(self._pool)
            and self._op_ewma_s
            > RemoteStoreManager._PIPELINE_LATENCY_GATE_S
        )

    def _mux_for(self, op: int):
        """The pipeline mux when this op may ride pipelined framing
        (negotiated + enabled + idempotent op); None = sync path."""
        if not self.pipeline or op not in _PIPELINEABLE_OPS:
            return None
        if self._remote_pipeline is None:
            try:
                self.features()
            # graphlint: disable=JG204 -- negotiation is best-effort: the op falls back to the sync path, whose own retry guard surfaces the failure
            except (TemporaryBackendError, PermanentBackendError):
                return None
        if not self._remote_pipeline:
            return None
        if self._mux is None:
            from janusgraph_tpu.storage.pipeline import PipelineMux

            with self._mux_lock:
                if self._mux is None:
                    from janusgraph_tpu.observability.profiler import (
                        split_ledger_block,
                    )

                    self._mux = PipelineMux(
                        self.host, self.port,
                        connections=self.pipeline_connections,
                        depth=self.pipeline_depth,
                        max_batch=self.pipeline_max_batch,
                        coalesce_us=self.pipeline_coalesce_us,
                        metric_prefix="index.remote",
                        batch_op=_OP_BATCH,
                        split_ledger=split_ledger_block,
                    )
        return self._mux

    def _call(self, op: int, body: bytes, idempotent: bool = True) -> bytes:
        """One wire call under the retry guard. Non-idempotent ops (mutate/
        restore: LIST-cardinality additions are not replay-safe) retry only
        the DIAL — once the request may have reached the server, a dropped
        connection surfaces as a permanent 'outcome unknown' error instead
        of an at-least-once resend duplicating index entries."""
        self._calls_active += 1
        try:
            return self._call_inner(op, body, idempotent)
        finally:
            self._calls_active -= 1

    def _call_inner(
        self, op: int, body: bytes, idempotent: bool = True
    ) -> bytes:
        mux = (
            self._mux_for(op)
            if (idempotent and self._should_pipeline()) else None
        )
        if mux is not None:
            from janusgraph_tpu.storage.pipeline import WireOp

            flags, prefix, want_ledger, expires_at = self._frame_parts(op)
            item = WireOp(
                op, flags, prefix, body, want_ledger=want_ledger,
                expires_at=expires_at,
            )
            timeout = 30.0 + self.retry_time_s

            def pattempt():
                # one submit+wait = one network attempt: a failed op
                # fails only itself; siblings in flight complete
                return mux.submit(item).result(timeout)

            pguarded = pattempt
            if self.breaker is not None:
                pguarded = lambda: self.breaker.call(pattempt)  # noqa: E731
            payload, fields = backend_op.execute(
                pguarded, max_time_s=self.retry_time_s
            )
            if want_ledger:
                from janusgraph_tpu.observability.profiler import (
                    merge_echo,
                )

                merge_echo(fields, layer="index.remote")
            self._tls.echoed = fields is not None
            return payload
        op, body, want_ledger = self._frame(op, body)

        def attempt() -> bytes:
            with self._pool_lock:
                conn = self._pool[self._pool_idx % len(self._pool)]
                self._pool_idx += 1
            with conn.lock:
                if conn.sock is None:
                    try:
                        # graphlint: disable=JG403 -- conn.lock exists to serialize the wire protocol on THIS connection; blocking while holding it is its whole job, and other pool connections proceed
                        conn._connect()
                    except OSError as e:
                        raise TemporaryBackendError(
                            f"connect failed: {e}"
                        ) from e
                try:
                    import time as _time

                    t0 = _time.monotonic()
                    # graphlint: disable=JG403 -- per-connection lock serializes request/response framing on one socket by design; contention moves to another pool slot, not behind this one
                    status, payload, _sock = conn.request(op, body)
                    # adaptive-gate latency signal (lock wait excluded)
                    self._op_ewma_s = (
                        0.9 * self._op_ewma_s
                        + 0.1 * (_time.monotonic() - t0)
                    )
                except TemporaryBackendError:
                    if idempotent:
                        raise
                    raise PermanentBackendError(
                        "index mutation outcome unknown: connection lost "
                        "mid-request (not replayed; verify index state or "
                        "reindex)"
                    ) from None
            if status == _STATUS_TEMP and not idempotent:
                # a clean temporary-failure reply still means the provider
                # may have PARTIALLY applied the mutation before failing —
                # replaying would duplicate the applied entries
                raise PermanentBackendError(
                    "index mutation failed server-side with a temporary "
                    f"error (not replayed; outcome may be partial): "
                    f"{payload.decode('utf-8', 'replace')}"
                )
            if status != _STATUS_OK:
                _raise_status(status, payload)
            return payload

        guarded = attempt
        if self.breaker is not None:
            guarded = lambda: self.breaker.call(attempt)  # noqa: E731
        payload = backend_op.execute(guarded, max_time_s=self.retry_time_s)
        if want_ledger:
            from janusgraph_tpu.observability.profiler import (
                merge_echo,
                split_ledger_block,
            )

            fields, payload = split_ledger_block(payload)
            # index node measured + span-annotated; merge un-annotated
            merge_echo(fields, layer="index.remote")
            self._tls.echoed = fields is not None
        else:
            self._tls.echoed = False
        return payload

    def features(self) -> IndexFeatures:
        if self._features is None:
            r = _Reader(self._call(_OP_FEATURES, b""))
            flags = [r.u8() for _ in range(4)]
            cards = tuple(r.str_() for _ in range(r.u32()))
            # trailing capability bytes, positional: [trace][ledger]; an
            # old server's payload ends earlier and the capability stays
            # off in whichever dimension is absent
            self._remote_trace = r.off < len(r.data) and r.u8() == 1
            self._remote_ledger = r.off < len(r.data) and r.u8() == 1
            self._remote_deadline = r.off < len(r.data) and r.u8() == 1
            self._remote_pipeline = r.off < len(r.data) and r.u8() == 1
            self._features = IndexFeatures(
                supports_document_ttl=bool(flags[0]),
                supports_cardinality=cards,
                supports_custom_analyzer=bool(flags[1]),
                supports_geo=bool(flags[2]),
                supports_not_query_normal_form=bool(flags[3]),
            )
        return self._features

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        out: List[bytes] = []
        _ps(out, store)
        _ps(out, key)
        _encode_keyinfo(out, info)
        self._call(_OP_REGISTER, b"".join(out))

    def mutate(self, mutations, key_infos) -> None:
        out: List[bytes] = [struct.pack(">I", len(mutations))]
        for store, per_doc in mutations.items():
            _ps(out, store)
            out.append(struct.pack(">I", len(per_doc)))
            for docid, m in per_doc.items():
                _ps(out, docid)
                out.append(bytes([int(m.is_new) | (int(m.is_deleted) << 1)]))
                _encode_entries(out, m.additions)
                _encode_entries(out, m.deletions)
        _encode_key_infos(out, key_infos)
        self._call(_OP_MUTATE, b"".join(out), idempotent=False)

    def restore(self, documents, key_infos) -> None:
        out: List[bytes] = [struct.pack(">I", len(documents))]
        for store, per_doc in documents.items():
            _ps(out, store)
            out.append(struct.pack(">I", len(per_doc)))
            for docid, entries in per_doc.items():
                _ps(out, docid)
                _encode_entries(out, entries)
        _encode_key_infos(out, key_infos)
        self._call(_OP_RESTORE, b"".join(out), idempotent=False)

    def query(self, store: str, q: IndexQuery) -> List[str]:
        out: List[bytes] = []
        _ps(out, store)
        _encode_condition(out, q.condition)
        out.append(struct.pack(">I", len(q.orders)))
        for o in q.orders:
            _ps(out, o.key)
            out.append(bytes([int(o.desc)]))
        out.append(struct.pack(">iI", -1 if q.limit is None else q.limit,
                               q.offset))
        r = _Reader(self._call(_OP_QUERY, b"".join(out)))
        hits = [r.str_() for _ in range(r.u32())]
        self._count_hits(hits)
        return hits

    def _count_hits(self, hits) -> None:
        """Fallback accounting against an old (pre-ledger) index server:
        no echo came back, so the decoded hit count is the PRIMARY accrual
        (annotates the client-side span). A ledger-disabled client stays
        entirely ledger-oblivious."""
        if getattr(self._tls, "echoed", False) or not self.resource_ledger:
            return
        from janusgraph_tpu.observability.profiler import (
            accrue,
            current_ledger,
        )

        if current_ledger() is not None:
            accrue(index_hits=len(hits))

    def raw_query(self, store: str, q: RawQuery) -> List[Tuple[str, float]]:
        out: List[bytes] = []
        _ps(out, store)
        _encode_raw(out, q)
        r = _Reader(self._call(_OP_RAW_QUERY, b"".join(out)))
        n = r.u32()
        hits = []
        for _ in range(n):
            docid = r.str_()
            (score,) = struct.unpack_from(">d", r.data, r.off)
            r.off += 8
            hits.append((docid, score))
        self._count_hits(hits)
        return hits

    def totals(self, store: str, q: RawQuery) -> int:
        out: List[bytes] = []
        _ps(out, store)
        _encode_raw(out, q)
        return struct.unpack(">Q", self._call(_OP_TOTALS, b"".join(out)))[0]

    def supports(self, info: KeyInformation, predicate) -> bool:
        memo_key = (
            info.data_type, info.mapping, info.cardinality, predicate.name
        )
        hit = self._supports_memo.get(memo_key)
        if hit is None:
            out: List[bytes] = []
            _encode_keyinfo(out, info)
            _ps(out, predicate.name)
            hit = self._call(_OP_SUPPORTS, b"".join(out)) == b"\x01"
            self._supports_memo[memo_key] = hit
        return hit

    def exists(self) -> bool:
        return self._call(_OP_EXISTS, b"") == b"\x01"

    def clear_storage(self) -> None:
        self._call(_OP_CLEAR, b"")

    def close(self) -> None:
        if self._mux is not None:
            self._mux.close()
            self._mux = None
        for conn in self._pool:
            with conn.lock:
                if conn.sock is not None:
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
                    conn.sock = None


register_index_provider("remote", RemoteIndexProvider)
