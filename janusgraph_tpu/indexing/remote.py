"""Networked mixed-index provider: TCP server/client for the IndexProvider SPI.

The reference's flagship index tier is a REMOTE service spoken to over a
wire protocol with a connection pool and retries (reference:
janusgraph-es .../diskstorage/es/ElasticSearchIndex.java:1355 and
.../es/rest/RestElasticSearchClient.java:505 — REST calls against an
external Elasticsearch). The TPU-native framework keeps the same split:
any in-process provider (localindex — the Lucene analogue — or memindex)
can be served over TCP by `RemoteIndexServer`, and `RemoteIndexProvider`
is a full IndexProvider whose calls cross the wire.

Protocol: same length-prefixed `[len:4][op:1][body]` -> `[len:4][status:1]
[body]` framing, pooled connections, and temporary/permanent status split
as the remote KCVS adapter (storage/remote.py), with the retry guard
(storage/backend_op.py) around every call. Attribute values ride the core
serializer's self-describing `[type_id:2][payload]` framing
(core/attributes.py), so every registered datatype — Geoshape included —
works over the wire without an index-specific codec.
"""

from __future__ import annotations

import struct
import threading
import socketserver
from typing import Dict, List, Optional, Tuple

from janusgraph_tpu.core.attributes import Serializer
from janusgraph_tpu.core.predicates import predicate_by_name
from janusgraph_tpu.exceptions import (
    PermanentBackendError,
    TemporaryBackendError,
)
from janusgraph_tpu.indexing.provider import (
    And,
    IndexEntry,
    IndexFeatures,
    IndexMutation,
    IndexProvider,
    IndexQuery,
    KeyInformation,
    Mapping,
    Not,
    Or,
    Order,
    PredicateCondition,
    RawQuery,
    register_index_provider,
)
from janusgraph_tpu.storage import backend_op
from janusgraph_tpu.storage.remote import (
    _DEADLINE_FLAG,
    _FLAG_MASK,
    _LEDGER_FLAG,
    _TRACE_FLAG,
    _Conn,
    _deadline_guard,
    _pb,
    _ps,
    _raise_status,
    _Reader,
    _recv_exact,
    encode_deadline_prefix,
    encode_trace_prefix,
    split_deadline_prefix,
    split_trace_prefix,
)

_STATUS_OK = 0
_STATUS_TEMP = 1
_STATUS_PERM = 2

_OP_REGISTER = 1
_OP_MUTATE = 2
_OP_RESTORE = 3
_OP_QUERY = 4
_OP_RAW_QUERY = 5
_OP_TOTALS = 6
_OP_SUPPORTS = 7
_OP_EXISTS = 8
_OP_CLEAR = 9
_OP_FEATURES = 10

_OP_NAMES = {
    _OP_REGISTER: "register",
    _OP_MUTATE: "mutate",
    _OP_RESTORE: "restore",
    _OP_QUERY: "query",
    _OP_RAW_QUERY: "rawQuery",
    _OP_TOTALS: "totals",
    _OP_SUPPORTS: "supports",
    _OP_EXISTS: "exists",
    _OP_CLEAR: "clear",
    _OP_FEATURES: "features",
}

#: one registry for the wire; user enums are not expected in index fields.
#: allow_pickle=False: a network peer must never be able to ship a pickle
#: payload into this process (see PickledObjectSerializer)
_SER = Serializer(allow_pickle=False)


# ------------------------------------------------------------------ encoding
def _pv(out: List[bytes], value) -> None:
    """Length-prefixed self-describing value frame."""
    _pb(out, _SER.write_object(value))


def _rv(r: _Reader):
    value, _ = _SER.read_object(r.bytes_())
    return value


def _encode_keyinfo(out: List[bytes], info: KeyInformation) -> None:
    out.append(struct.pack(">H", _SER.data_type_id(info.data_type)))
    _ps(out, info.mapping.value)
    _ps(out, info.cardinality)


def _decode_keyinfo(r: _Reader) -> KeyInformation:
    (tid,) = struct.unpack_from(">H", r.data, r.off)
    r.off += 2
    return KeyInformation(
        data_type=_SER.type_for_id(tid),
        mapping=Mapping(r.str_()),
        cardinality=r.str_(),
    )


def _encode_condition(out: List[bytes], cond) -> None:
    if isinstance(cond, PredicateCondition):
        out.append(b"\x00")
        _ps(out, cond.key)
        _ps(out, cond.predicate.name)
        _pv(out, cond.value)
    elif isinstance(cond, (And, Or)):
        out.append(b"\x01" if isinstance(cond, And) else b"\x02")
        out.append(struct.pack(">I", len(cond.children)))
        for c in cond.children:
            _encode_condition(out, c)
    elif isinstance(cond, Not):
        out.append(b"\x03")
        _encode_condition(out, cond.child)
    else:
        raise PermanentBackendError(
            f"unencodable condition {type(cond).__name__}"
        )


def _decode_condition(r: _Reader):
    tag = r.u8()
    if tag == 0:
        key = r.str_()
        pname = r.str_()
        pred = predicate_by_name(pname)
        if pred is None:
            raise PermanentBackendError(f"unknown predicate {pname!r}")
        return PredicateCondition(key, pred, _rv(r))
    if tag in (1, 2):
        n = r.u32()
        children = tuple(_decode_condition(r) for _ in range(n))
        return And(children) if tag == 1 else Or(children)
    if tag == 3:
        return Not(_decode_condition(r))
    raise PermanentBackendError(f"unknown condition tag {tag}")


def _encode_key_infos(out: List[bytes], key_infos) -> None:
    out.append(struct.pack(">I", len(key_infos)))
    for store, fields in key_infos.items():
        _ps(out, store)
        out.append(struct.pack(">I", len(fields)))
        for fname, info in fields.items():
            _ps(out, fname)
            _encode_keyinfo(out, info)


def _decode_key_infos(r: _Reader) -> Dict[str, Dict[str, KeyInformation]]:
    # explicit loops: the wire layout depends on strict read order, which
    # comprehension key/value evaluation order would leave implicit
    infos: Dict[str, Dict[str, KeyInformation]] = {}
    for _ in range(r.u32()):
        store = r.str_()
        fields: Dict[str, KeyInformation] = {}
        for _ in range(r.u32()):
            fname = r.str_()
            fields[fname] = _decode_keyinfo(r)
        infos[store] = fields
    return infos


def _encode_entries(out: List[bytes], entries: List[IndexEntry]) -> None:
    out.append(struct.pack(">I", len(entries)))
    for e in entries:
        _ps(out, e.field)
        _pv(out, e.value)


def _decode_entries(r: _Reader) -> List[IndexEntry]:
    entries = []
    for _ in range(r.u32()):
        field = r.str_()
        entries.append(IndexEntry(field, _rv(r)))
    return entries


def _encode_raw(out: List[bytes], q: RawQuery) -> None:
    _ps(out, q.query)
    out.append(struct.pack(">iI", -1 if q.limit is None else q.limit,
                           q.offset))


def _decode_raw(r: _Reader) -> RawQuery:
    query = r.str_()
    limit, offset = struct.unpack_from(">iI", r.data, r.off)
    r.off += 8
    return RawQuery(query, None if limit < 0 else limit, offset)


# -------------------------------------------------------------------- server
class _IndexHandler(socketserver.BaseRequestHandler):
    #: per flagged request: measured costs, prepended to the OK reply
    _led = None
    _op_t0 = 0

    def handle(self):
        import time as _time

        provider = self.server.provider  # type: ignore[attr-defined]
        sock = self.request
        try:
            while True:
                try:
                    head = _recv_exact(sock, 5)
                except ConnectionError:
                    return
                (body_len,) = struct.unpack(">I", head[:4])
                raw = head[4]
                op = raw & ~_FLAG_MASK
                body = _recv_exact(sock, body_len) if body_len else b""
                ctx = None
                if raw & _TRACE_FLAG:
                    ctx, body = split_trace_prefix(body)
                budget_ms = None
                if raw & _DEADLINE_FLAG:
                    budget_ms, body = split_deadline_prefix(body)
                self._led = {} if raw & _LEDGER_FLAG else None
                self._op_t0 = _time.perf_counter_ns()
                try:
                    # inherit the caller's remaining budget (an op that
                    # arrives already-expired is refused permanently)
                    with _deadline_guard(budget_ms):
                        if ctx is not None:
                            from janusgraph_tpu.observability import tracer

                            # the index node's op joins the caller's trace
                            with tracer.child_span(
                                ctx, f"index.remote.{_OP_NAMES.get(op, op)}"
                            ) as sp:
                                self._dispatch(provider, sock, op, body)
                                if self._led:
                                    # index node owns these measurements
                                    # (the client merges the echo
                                    # un-annotated)
                                    sp.annotate(**{
                                        f"ledger.{k}": v
                                        for k, v in self._led.items()
                                        if k != "wall_ns"
                                    })
                        else:
                            self._dispatch(provider, sock, op, body)
                # graphlint: disable=JG204 -- protocol boundary: the error is serialized to the client as a temporary status frame, and the CLIENT retries
                except (TemporaryBackendError, ConnectionError) as e:
                    self._reply(sock, _STATUS_TEMP, str(e).encode())
                except Exception as e:  # noqa: BLE001 - protocol boundary
                    self._reply(
                        sock, _STATUS_PERM,
                        f"{type(e).__name__}: {e}".encode(),
                    )
                finally:
                    self._led = None
        except (ConnectionResetError, BrokenPipeError):
            return

    def _reply(self, sock, status: int, body: bytes) -> None:
        if self._led is not None and status == _STATUS_OK:
            import time as _time

            from janusgraph_tpu.observability.profiler import (
                encode_ledger_block,
            )

            self._led["wall_ns"] = _time.perf_counter_ns() - self._op_t0
            body = encode_ledger_block(self._led) + body
        sock.sendall(struct.pack(">IB", len(body), status) + body)

    def _dispatch(self, provider, sock, op: int, body: bytes) -> None:
        r = _Reader(body)
        if op == _OP_REGISTER:
            store, key = r.str_(), r.str_()
            provider.register(store, key, _decode_keyinfo(r))
            self._reply(sock, _STATUS_OK, b"")
            return
        if op == _OP_MUTATE:
            muts: Dict[str, Dict[str, IndexMutation]] = {}
            for _ in range(r.u32()):
                store = r.str_()
                per_doc = muts.setdefault(store, {})
                for _ in range(r.u32()):
                    docid = r.str_()
                    flags = r.u8()
                    m = IndexMutation(
                        is_new=bool(flags & 1), is_deleted=bool(flags & 2)
                    )
                    m.additions.extend(_decode_entries(r))
                    m.deletions.extend(_decode_entries(r))
                    per_doc[docid] = m
            if self._led is not None:
                self._led["cells_written"] = sum(
                    len(m.additions) + len(m.deletions)
                    for per_doc in muts.values()
                    for m in per_doc.values()
                )
            provider.mutate(muts, _decode_key_infos(r))
            self._reply(sock, _STATUS_OK, b"")
            return
        if op == _OP_RESTORE:
            docs: Dict[str, Dict[str, List[IndexEntry]]] = {}
            for _ in range(r.u32()):
                store = r.str_()
                per_doc = docs.setdefault(store, {})
                for _ in range(r.u32()):
                    docid = r.str_()
                    per_doc[docid] = _decode_entries(r)
            provider.restore(docs, _decode_key_infos(r))
            self._reply(sock, _STATUS_OK, b"")
            return
        if op == _OP_QUERY:
            store = r.str_()
            cond = _decode_condition(r)
            orders = tuple(
                Order(r.str_(), bool(r.u8())) for _ in range(r.u32())
            )
            limit, offset = struct.unpack_from(">iI", r.data, r.off)
            r.off += 8
            q = IndexQuery(
                cond, orders, None if limit < 0 else limit, offset
            )
            hits = provider.query(store, q)
            if self._led is not None:
                self._led["index_hits"] = len(hits)
            out: List[bytes] = [struct.pack(">I", len(hits))]
            for h in hits:
                _ps(out, h)
            self._reply(sock, _STATUS_OK, b"".join(out))
            return
        if op == _OP_RAW_QUERY:
            store = r.str_()
            hits = provider.raw_query(store, _decode_raw(r))
            if self._led is not None:
                self._led["index_hits"] = len(hits)
            out = [struct.pack(">I", len(hits))]
            for docid, score in hits:
                _ps(out, docid)
                out.append(struct.pack(">d", float(score)))
            self._reply(sock, _STATUS_OK, b"".join(out))
            return
        if op == _OP_TOTALS:
            store = r.str_()
            n = provider.totals(store, _decode_raw(r))
            self._reply(sock, _STATUS_OK, struct.pack(">Q", n))
            return
        if op == _OP_SUPPORTS:
            info = _decode_keyinfo(r)
            pred = predicate_by_name(r.str_())
            ok = pred is not None and provider.supports(info, pred)
            self._reply(sock, _STATUS_OK, b"\x01" if ok else b"\x00")
            return
        if op == _OP_EXISTS:
            self._reply(
                sock, _STATUS_OK, b"\x01" if provider.exists() else b"\x00"
            )
            return
        if op == _OP_CLEAR:
            provider.clear_storage()
            self._reply(sock, _STATUS_OK, b"")
            return
        if op == _OP_FEATURES:
            f = provider.features()
            out = [
                bytes([int(f.supports_document_ttl),
                       int(f.supports_custom_analyzer),
                       int(f.supports_geo),
                       int(f.supports_not_query_normal_form)]),
                struct.pack(">I", len(f.supports_cardinality)),
            ]
            for c in f.supports_cardinality:
                _ps(out, c)
            # trailing protocol-capability bytes, positional: [trace]
            # then [ledger] then [deadline]. Old clients stop reading
            # after the cardinalities (or after however many capability
            # bytes they know), so extra bytes are invisible to them; old
            # servers simply end the payload earlier and new clients
            # negotiate the capability OFF. Every earlier byte is always
            # written when a later one is, so positions stay unambiguous.
            trace_on = getattr(self.server, "trace_propagation", True)
            ledger_on = getattr(self.server, "ledger_echo", True)
            deadline_on = getattr(self.server, "deadline_propagation", True)
            if trace_on or ledger_on or deadline_on:
                out.append(b"\x01" if trace_on else b"\x00")
            if ledger_on or deadline_on:
                out.append(b"\x01" if ledger_on else b"\x00")
            if deadline_on:
                out.append(b"\x01")
            self._reply(sock, _STATUS_OK, b"".join(out))
            return
        raise PermanentBackendError(f"unknown index op {op}")


class RemoteIndexServer:
    """Serve any IndexProvider over TCP (threaded; port 0 = ephemeral).
    ``trace_propagation=False`` = the pre-trace features payload,
    ``ledger_echo=False`` the pre-ledger one, ``deadline_propagation=
    False`` the pre-deadline one ("old-featured" index servers for
    compatibility tests)."""

    def __init__(self, provider: IndexProvider, host: str = "127.0.0.1",
                 port: int = 0, trace_propagation: bool = True,
                 ledger_echo: bool = True,
                 deadline_propagation: bool = True):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _IndexHandler)
        self._srv.provider = provider  # type: ignore[attr-defined]
        self._srv.trace_propagation = trace_propagation  # type: ignore[attr-defined]
        self._srv.ledger_echo = ledger_echo  # type: ignore[attr-defined]
        self._srv.deadline_propagation = deadline_propagation  # type: ignore[attr-defined]
        self.provider = provider
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.server_address  # type: ignore[return-value]

    def start(self) -> "RemoteIndexServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="index-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# -------------------------------------------------------------------- client
class RemoteIndexProvider(IndexProvider):
    """Client-side IndexProvider speaking the remote index protocol —
    the janusgraph-es analogue (RestElasticSearchClient.java:505: pooled
    REST client with request retries)."""

    name = "remote"

    def __init__(self, hostname: str = "127.0.0.1", port: int = 0,
                 pool_size: int = 4, retry_time_s: float = 10.0,
                 directory: str = None,
                 breaker_enabled: bool = False,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_ms: float = 1000.0,
                 breaker_half_open_probes: int = 1,
                 trace_propagation: bool = True,
                 resource_ledger: bool = True,
                 deadline_propagation: bool = True,
                 **_ignored):
        # `directory` accepted-and-ignored: open_index_provider passes the
        # local providers' kwargs through one call site (core/graph.py)
        if not hostname or int(port) <= 0:
            from janusgraph_tpu.exceptions import ConfigurationError

            raise ConfigurationError(
                "index backend 'remote' requires index.search.hostname and "
                f"a positive index.search.port (got {hostname!r}:{port!r})"
            )
        self.host, self.port = hostname, int(port)
        self.retry_time_s = retry_time_s
        #: metrics.trace-propagation, gated on the server's negotiated
        #: capability byte (None = features not yet fetched)
        self.trace_propagation = trace_propagation
        self._remote_trace: Optional[bool] = None
        #: metrics.resource-ledger, gated on the second capability byte
        self.resource_ledger = resource_ledger
        self._remote_ledger: Optional[bool] = None
        #: server.deadline.propagation, gated on the third capability byte
        self.deadline_propagation = deadline_propagation
        self._remote_deadline: Optional[bool] = None
        #: the provider accounts index hits itself (echo or local
        #: fallback), so graph.mixed_index_query must not count them again
        self.ledger_self_accounting = True
        self._pool = [_Conn(self.host, self.port) for _ in range(pool_size)]
        # whether this thread's last _call carried a ledger echo (drives
        # the old-server fallback accounting in query/raw_query)
        self._tls = threading.local()
        self._pool_lock = threading.Lock()
        self._pool_idx = 0
        self._features: Optional[IndexFeatures] = None
        self._supports_memo: Dict[Tuple, bool] = {}
        # same storage.breaker.* machinery as the remote KCVS client: a
        # down index tier fails fast instead of serializing every commit
        # behind a full retry budget
        self.breaker = None
        if breaker_enabled:
            from janusgraph_tpu.storage.circuit import CircuitBreaker

            self.breaker = CircuitBreaker(
                "index.remote",
                failure_threshold=breaker_failure_threshold,
                reset_timeout_s=breaker_reset_ms / 1000.0,
                half_open_probes=breaker_half_open_probes,
            )

    def _frame(self, op: int, body: bytes):
        """Same negotiation as RemoteStoreManager._frame: attach the
        ambient trace context / ledger flag only once the server's
        features payload proved it understands flagged frames. Returns
        (op, body, want_ledger)."""
        if op == _OP_FEATURES:
            return op, body, False
        from janusgraph_tpu.core.deadline import remaining_ms
        from janusgraph_tpu.observability import tracer
        from janusgraph_tpu.observability.profiler import current_ledger

        ctx = tracer.current_context() if self.trace_propagation else None
        led = current_ledger() if self.resource_ledger else None
        budget = remaining_ms() if self.deadline_propagation else None
        if ctx is None and led is None and budget is None:
            return op, body, False
        if (self._remote_trace is None or self._remote_ledger is None
                or self._remote_deadline is None):
            try:
                self.features()
            # graphlint: disable=JG204 -- negotiation is best-effort: the frame just goes unflagged, and the op itself will surface the failure through its own retry guard
            except (TemporaryBackendError, PermanentBackendError):
                return op, body, False
        want_ledger = bool(led is not None and self._remote_ledger)
        if budget is not None and self._remote_deadline:
            # deadline prefix inside the trace prefix (server strips
            # trace first, then deadline)
            op |= _DEADLINE_FLAG
            body = encode_deadline_prefix(budget) + body
        if ctx is not None and self._remote_trace:
            op |= _TRACE_FLAG
            body = encode_trace_prefix(ctx) + body
        if want_ledger:
            op |= _LEDGER_FLAG
        return op, body, want_ledger

    def _call(self, op: int, body: bytes, idempotent: bool = True) -> bytes:
        """One wire call under the retry guard. Non-idempotent ops (mutate/
        restore: LIST-cardinality additions are not replay-safe) retry only
        the DIAL — once the request may have reached the server, a dropped
        connection surfaces as a permanent 'outcome unknown' error instead
        of an at-least-once resend duplicating index entries."""
        op, body, want_ledger = self._frame(op, body)

        def attempt() -> bytes:
            with self._pool_lock:
                conn = self._pool[self._pool_idx % len(self._pool)]
                self._pool_idx += 1
            with conn.lock:
                if conn.sock is None:
                    try:
                        conn._connect()
                    except OSError as e:
                        raise TemporaryBackendError(
                            f"connect failed: {e}"
                        ) from e
                try:
                    status, payload, _sock = conn.request(op, body)
                except TemporaryBackendError:
                    if idempotent:
                        raise
                    raise PermanentBackendError(
                        "index mutation outcome unknown: connection lost "
                        "mid-request (not replayed; verify index state or "
                        "reindex)"
                    ) from None
            if status == _STATUS_TEMP and not idempotent:
                # a clean temporary-failure reply still means the provider
                # may have PARTIALLY applied the mutation before failing —
                # replaying would duplicate the applied entries
                raise PermanentBackendError(
                    "index mutation failed server-side with a temporary "
                    f"error (not replayed; outcome may be partial): "
                    f"{payload.decode('utf-8', 'replace')}"
                )
            if status != _STATUS_OK:
                _raise_status(status, payload)
            return payload

        guarded = attempt
        if self.breaker is not None:
            guarded = lambda: self.breaker.call(attempt)  # noqa: E731
        payload = backend_op.execute(guarded, max_time_s=self.retry_time_s)
        if want_ledger:
            from janusgraph_tpu.observability.profiler import (
                merge_echo,
                split_ledger_block,
            )

            fields, payload = split_ledger_block(payload)
            # index node measured + span-annotated; merge un-annotated
            merge_echo(fields, layer="index.remote")
            self._tls.echoed = fields is not None
        else:
            self._tls.echoed = False
        return payload

    def features(self) -> IndexFeatures:
        if self._features is None:
            r = _Reader(self._call(_OP_FEATURES, b""))
            flags = [r.u8() for _ in range(4)]
            cards = tuple(r.str_() for _ in range(r.u32()))
            # trailing capability bytes, positional: [trace][ledger]; an
            # old server's payload ends earlier and the capability stays
            # off in whichever dimension is absent
            self._remote_trace = r.off < len(r.data) and r.u8() == 1
            self._remote_ledger = r.off < len(r.data) and r.u8() == 1
            self._remote_deadline = r.off < len(r.data) and r.u8() == 1
            self._features = IndexFeatures(
                supports_document_ttl=bool(flags[0]),
                supports_cardinality=cards,
                supports_custom_analyzer=bool(flags[1]),
                supports_geo=bool(flags[2]),
                supports_not_query_normal_form=bool(flags[3]),
            )
        return self._features

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        out: List[bytes] = []
        _ps(out, store)
        _ps(out, key)
        _encode_keyinfo(out, info)
        self._call(_OP_REGISTER, b"".join(out))

    def mutate(self, mutations, key_infos) -> None:
        out: List[bytes] = [struct.pack(">I", len(mutations))]
        for store, per_doc in mutations.items():
            _ps(out, store)
            out.append(struct.pack(">I", len(per_doc)))
            for docid, m in per_doc.items():
                _ps(out, docid)
                out.append(bytes([int(m.is_new) | (int(m.is_deleted) << 1)]))
                _encode_entries(out, m.additions)
                _encode_entries(out, m.deletions)
        _encode_key_infos(out, key_infos)
        self._call(_OP_MUTATE, b"".join(out), idempotent=False)

    def restore(self, documents, key_infos) -> None:
        out: List[bytes] = [struct.pack(">I", len(documents))]
        for store, per_doc in documents.items():
            _ps(out, store)
            out.append(struct.pack(">I", len(per_doc)))
            for docid, entries in per_doc.items():
                _ps(out, docid)
                _encode_entries(out, entries)
        _encode_key_infos(out, key_infos)
        self._call(_OP_RESTORE, b"".join(out), idempotent=False)

    def query(self, store: str, q: IndexQuery) -> List[str]:
        out: List[bytes] = []
        _ps(out, store)
        _encode_condition(out, q.condition)
        out.append(struct.pack(">I", len(q.orders)))
        for o in q.orders:
            _ps(out, o.key)
            out.append(bytes([int(o.desc)]))
        out.append(struct.pack(">iI", -1 if q.limit is None else q.limit,
                               q.offset))
        r = _Reader(self._call(_OP_QUERY, b"".join(out)))
        hits = [r.str_() for _ in range(r.u32())]
        self._count_hits(hits)
        return hits

    def _count_hits(self, hits) -> None:
        """Fallback accounting against an old (pre-ledger) index server:
        no echo came back, so the decoded hit count is the PRIMARY accrual
        (annotates the client-side span). A ledger-disabled client stays
        entirely ledger-oblivious."""
        if getattr(self._tls, "echoed", False) or not self.resource_ledger:
            return
        from janusgraph_tpu.observability.profiler import (
            accrue,
            current_ledger,
        )

        if current_ledger() is not None:
            accrue(index_hits=len(hits))

    def raw_query(self, store: str, q: RawQuery) -> List[Tuple[str, float]]:
        out: List[bytes] = []
        _ps(out, store)
        _encode_raw(out, q)
        r = _Reader(self._call(_OP_RAW_QUERY, b"".join(out)))
        n = r.u32()
        hits = []
        for _ in range(n):
            docid = r.str_()
            (score,) = struct.unpack_from(">d", r.data, r.off)
            r.off += 8
            hits.append((docid, score))
        self._count_hits(hits)
        return hits

    def totals(self, store: str, q: RawQuery) -> int:
        out: List[bytes] = []
        _ps(out, store)
        _encode_raw(out, q)
        return struct.unpack(">Q", self._call(_OP_TOTALS, b"".join(out)))[0]

    def supports(self, info: KeyInformation, predicate) -> bool:
        memo_key = (
            info.data_type, info.mapping, info.cardinality, predicate.name
        )
        hit = self._supports_memo.get(memo_key)
        if hit is None:
            out: List[bytes] = []
            _encode_keyinfo(out, info)
            _ps(out, predicate.name)
            hit = self._call(_OP_SUPPORTS, b"".join(out)) == b"\x01"
            self._supports_memo[memo_key] = hit
        return hit

    def exists(self) -> bool:
        return self._call(_OP_EXISTS, b"") == b"\x01"

    def clear_storage(self) -> None:
        self._call(_OP_CLEAR, b"")

    def close(self) -> None:
        for conn in self._pool:
            with conn.lock:
                if conn.sock is not None:
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
                    conn.sock = None


register_index_provider("remote", RemoteIndexProvider)
