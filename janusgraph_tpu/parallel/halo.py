"""Propagation-blocked halo exchange plan: the source-partitioned view.

The dst-partitioned ShardedCSR (parallel/sharded.py) ships boundary SOURCE
values: every superstep each shard gathers the values its peers need and
swaps (S, B) buckets, then aggregates ALL of its in-edges locally — the
"eager" exchange. Propagation blocking (PAPERS.md arXiv:2011.08451,
arXiv:2108.11521) flips the plan to the SOURCE partition: each shard owns
its out-edges, bins remote-bound messages by DESTINATION shard inside the
superstep kernel, combiner-merges them locally (one merged value per
distinct remote destination), and exchanges the merged bins in ONE batched
all_to_all. The receiver only scatter-combines S*Hc merged values instead
of aggregating its remote edges — exchange volume drops from the distinct-
source boundary width B to the distinct-destination halo width Hc, and the
per-superstep message-table concatenation disappears.

This module is the HOST-side plan builder plus the numpy replay oracle:

  * :class:`BlockedPlan` — per-shard source-partitioned edge blocks
    (``blk_src_loc``/``blk_seg``/``blk_valid``/``blk_weight``), the
    bins-only segment map the frontier engine merges through
    (``blk_bin_seg``), and the receive map (``recv_dst``). Bin capacities
    are pow2-tiered (``halo_cap``) so one compiled executable serves every
    graph whose halo fits the tier.
  * distributed CSR loading — ``pair_dst_lists`` / ``build_local`` /
    ``assemble_recv`` let each host build ONLY its own shards' blocks from
    the storage partitions it loaded (olap/distributed_load.py ships the
    same source-keyed partition ranges), exchanging just the compact
    per-(q→s) distinct-destination lists as metadata instead of
    materializing the full graph everywhere.
  * :func:`replay_superstep` — the numpy twin of the device kernel, same
    arithmetic in the same order (np.add.at/minimum.at are bitwise-equal
    to XLA CPU segment reductions) — the CPU-oracle side of the blocked
    path's bitwise-identity contract, and the per-shard measured-wall
    probe (:func:`measure_shard_walls`).

Bitwise contract: MIN/MAX combiners are exactly order-insensitive, so
blocked results are bitwise-identical to the eager paths (BFS/SSSP/CC).
SUM programs associate differently (per-source-shard partials, then a
cross-shard fold) — there the contract is bitwise identity against
:func:`replay_superstep` (the plan's own numpy oracle), the same precedent
as HybridPack's numpy replay, with eager-vs-blocked agreeing to float
tolerance.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.kernels import _next_pow2, fp_fence
from janusgraph_tpu.olap.vertex_program import Combiner, apply_edge_transform


def edges_from_sharded(sc) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The canonical dst-sorted edge multiset of a ShardedCSR (global src,
    global dst, weight) — the blocked plan builds from the SAME edges the
    eager plan packed, so the two plans aggregate the identical multiset."""
    S, Np, Em = sc.num_shards, sc.shard_size, sc.edges_per_shard
    offsets = sc._offsets
    dst_parts: List[np.ndarray] = []
    w_parts: List[np.ndarray] = []
    for s in range(S):
        k = int(offsets[s + 1] - offsets[s])
        base = s * Em
        dst_parts.append(
            s * Np + sc.in_dst_loc[base : base + k].astype(np.int64)
        )
        w_parts.append(sc.in_weight[base : base + k])
    dst = (
        np.concatenate(dst_parts) if dst_parts
        else np.empty(0, np.int64)
    )
    w = np.concatenate(w_parts) if w_parts else np.empty(0, np.float32)
    return sc._src_sorted.astype(np.int64), dst, w.astype(np.float32)


def pair_dst_lists(
    src: np.ndarray,
    dst: np.ndarray,
    num_shards: int,
    shard_size: int,
    owner_range: Optional[Tuple[int, int]] = None,
) -> Dict[Tuple[int, int], np.ndarray]:
    """{(q, s): sorted distinct global dst ids} for every cross-shard pair
    with at least one edge. ``owner_range`` restricts to owners q in
    [lo, hi) — the distributed-loading case where this host only scanned
    the storage partitions backing those source shards."""
    owner = src // shard_size
    dshard = dst // shard_size
    lo, hi = owner_range if owner_range is not None else (0, num_shards)
    lists: Dict[Tuple[int, int], np.ndarray] = {}
    for q in range(lo, hi):
        mq = owner == q
        if not mq.any():
            continue
        for s in range(num_shards):
            if s == q:
                continue
            mm = mq & (dshard == s)
            if not mm.any():
                continue
            lists[(q, s)] = np.unique(dst[mm])
    return lists


def halo_tier(
    lists: Dict[Tuple[int, int], np.ndarray], floor: int = 1
) -> int:
    """Pow2-tiered bin capacity: the smallest power of two covering the
    widest per-pair distinct-destination list. One tier serves the whole
    mesh (all_to_all needs uniform splits), and pow2 tiers mean a halo
    that grows within its tier recompiles nothing (JG301 contract)."""
    widest = max((len(u) for u in lists.values()), default=0)
    return _next_pow2(max(int(floor), widest, 1))


def pair_widths(
    src: np.ndarray, dst: np.ndarray, num_shards: int, shard_size: int
) -> Dict[str, int]:
    """Cheap comparative exchange stats for the autotuner: the eager
    boundary width B (max distinct cross-shard SOURCES any pair ships) vs
    the blocked halo width (max distinct cross-shard DESTINATIONS any
    pair merges into)."""
    owner = src // shard_size
    dshard = dst // shard_size
    cross = owner != dshard
    b_src = 0
    b_dst = 0
    if cross.any():
        pair = owner[cross] * num_shards + dshard[cross]
        n = int(max(src.max(initial=0), dst.max(initial=0))) + 1
        b_src = int(np.bincount(
            np.unique(pair * n + src[cross]) // n
        ).max())
        b_dst = int(np.bincount(
            np.unique(pair * n + dst[cross]) // n
        ).max())
    return {
        "boundary_width": max(1, b_src),
        "halo_width": max(1, b_dst),
        "halo_cap": _next_pow2(max(1, b_dst)),
        "cross_edges": int(cross.sum()),
    }


class BlockedPlan:
    """Host-side propagation-blocked exchange plan, ready for device
    placement (every array's leading dim is divisible by S).

    Arrays (Eq = max out-edges any shard owns, Hc = halo_cap, the pow2
    bin tier; T = Np + S*Hc segments per shard plus one trailing dead
    slot):

      blk_src_loc (S*Eq,)       int32  edge source, LOCAL to its owner
      blk_seg     (S*Eq,)       int32  full segment map: local dst
                                        [0, Np), outgoing bin slot
                                        [Np, Np+S*Hc), dead (padding)
      blk_bin_seg (S*Eq,)       int32  bins-only map for the frontier
                                        engine: [0, S*Hc) or dead S*Hc
                                        (local edges excluded — they stay
                                        for compacted expansion)
      blk_valid   (S*Eq,)       f32
      blk_weight  (S*Eq,)       f32
      recv_dst    (S*(S*Hc),)   int32  received bin slot -> local dst,
                                        pad -> Np (dead)
    """

    def __init__(
        self,
        num_shards: int,
        shard_size: int,
        halo_cap: int,
        edges_per_owner: int,
        owner_lo: int = 0,
        owner_hi: Optional[int] = None,
    ):
        S = num_shards
        self.num_shards = S
        self.shard_size = shard_size
        self.halo_cap = halo_cap
        self.edges_per_owner = edges_per_owner
        self.owner_lo = owner_lo
        self.owner_hi = S if owner_hi is None else owner_hi
        rows = self.owner_hi - self.owner_lo
        Eq, Hc, Np = edges_per_owner, halo_cap, shard_size
        self.blk_src_loc = np.zeros(rows * Eq, dtype=np.int32)
        # padded slots land in the trailing dead segment so a padded edge
        # can never leak into a bin or a local vertex
        self.blk_seg = np.full(rows * Eq, Np + S * Hc, dtype=np.int32)
        self.blk_bin_seg = np.full(rows * Eq, S * Hc, dtype=np.int32)
        self.blk_valid = np.zeros(rows * Eq, dtype=np.float32)
        self.blk_weight = np.ones(rows * Eq, dtype=np.float32)
        self.recv_dst = np.full(rows * (S * Hc), Np, dtype=np.int32)
        #: per-owner real (unpadded) edge counts, local/remote split — the
        #: per-shard cost inputs for the skew report and measured walls
        self.edges_by_owner = [0] * rows
        self.local_edges_by_owner = [0] * rows
        self.bins_used_by_owner = [0] * rows

    # ------------------------------------------------------------- builders
    @classmethod
    def build(
        cls,
        src: np.ndarray,
        dst: np.ndarray,
        w: np.ndarray,
        num_shards: int,
        shard_size: int,
        halo_cap: Optional[int] = None,
        edges_per_owner: Optional[int] = None,
    ) -> "BlockedPlan":
        """Single-process build over the full edge multiset."""
        lists = pair_dst_lists(src, dst, num_shards, shard_size)
        if halo_cap is None:
            halo_cap = halo_tier(lists)
        owner = src // shard_size
        counts = np.bincount(owner, minlength=num_shards)
        if edges_per_owner is None:
            edges_per_owner = max(1, int(counts.max()) if len(counts) else 1)
        plan = cls(num_shards, shard_size, halo_cap, edges_per_owner)
        plan.fill_owners(src, dst, w, lists, (0, num_shards))
        plan.fill_recv(lists, (0, num_shards))
        plan.pair_lists = lists
        return plan

    @classmethod
    def build_local(
        cls,
        src: np.ndarray,
        dst: np.ndarray,
        w: np.ndarray,
        num_shards: int,
        shard_size: int,
        shard_range: Tuple[int, int],
        halo_cap: int,
        edges_per_owner: int,
        all_pair_lists: Dict[Tuple[int, int], np.ndarray],
    ) -> "BlockedPlan":
        """Distributed build: this host holds ONLY the edges whose source
        shard falls in ``shard_range`` (the storage partitions it
        scanned), plus the exchanged metadata — the global pow2 bin tier,
        the global per-owner edge ceiling, and every pair's compact
        distinct-destination list (``all_pair_lists``, the halo index:
        at most S*S*Hc vertex ids, NOT the O(E) edge set)."""
        plan = cls(
            num_shards, shard_size, halo_cap, edges_per_owner,
            owner_lo=shard_range[0], owner_hi=shard_range[1],
        )
        plan.fill_owners(src, dst, w, all_pair_lists, shard_range)
        plan.fill_recv(all_pair_lists, shard_range)
        plan.pair_lists = all_pair_lists
        return plan

    def fill_owners(self, src, dst, w, lists, owner_range) -> None:
        S, Np, Eq, Hc = (
            self.num_shards, self.shard_size, self.edges_per_owner,
            self.halo_cap,
        )
        owner = src // Np
        dshard = dst // Np
        lo = owner_range[0]
        for q in range(*owner_range):
            m = np.nonzero(owner == q)[0]  # keeps dst-sorted order
            k = len(m)
            row = q - lo
            base = row * Eq
            self.edges_by_owner[row] = k
            if not k:
                continue
            qsrc, qdst, qds = src[m], dst[m], dshard[m]
            self.blk_src_loc[base : base + k] = (qsrc - q * Np).astype(
                np.int32
            )
            self.blk_valid[base : base + k] = 1.0
            self.blk_weight[base : base + k] = w[m]
            seg = np.empty(k, dtype=np.int64)
            bin_seg = np.full(k, S * Hc, dtype=np.int64)
            local = qds == q
            seg[local] = qdst[local] - q * Np
            self.local_edges_by_owner[row] = int(local.sum())
            used = 0
            for s in range(S):
                if s == q:
                    continue
                mm = qds == s
                if not mm.any():
                    continue
                u = lists[(q, s)]
                j = np.searchsorted(u, qdst[mm])
                seg[mm] = Np + s * Hc + j
                bin_seg[mm] = s * Hc + j
                used += len(u)
            self.bins_used_by_owner[row] = used
            self.blk_seg[base : base + k] = seg.astype(np.int32)
            self.blk_bin_seg[base : base + k] = bin_seg.astype(np.int32)

    def fill_recv(self, lists, shard_range) -> None:
        S, Np, Hc = self.num_shards, self.shard_size, self.halo_cap
        lo = shard_range[0]
        for s in range(*shard_range):
            base = (s - lo) * (S * Hc)
            for q in range(S):
                u = lists.get((q, s))
                if u is None:
                    continue
                self.recv_dst[base + q * Hc : base + q * Hc + len(u)] = (
                    u - s * Np
                ).astype(np.int32)

    # ------------------------------------------------------------- reporting
    def comm_stats(self) -> Dict[str, object]:
        S, Hc = self.num_shards, self.halo_cap
        used = sum(self.bins_used_by_owner)
        return {
            "halo_cap": Hc,
            "blocked_elems": S * Hc,
            "bin_fill": round(used / max(1, (self.owner_hi - self.owner_lo) * S * Hc), 4),
            "edges_per_owner": list(self.edges_by_owner),
        }


# ---------------------------------------------------------------------------
# packed (ELL/tree) aggregation for the blocked exchange

_BLOCKED_ELL_MAX_CAP = 1 << 14


def build_ell(plan: BlockedPlan, has_weight: bool) -> None:
    """Attach the packed aggregation structures to a (full) BlockedPlan:

    Sender side — a uniform degree-bucketed ELL over the fused segment
    space [local destinations ++ outgoing bins]: gather + fixed
    adjacent-pair tree reduction (olap/kernels.tree_reduce) instead of a
    scatter-add, indexing the shard's OWN Np-row outgoing block (plus one
    identity pad row) — no message-table concat, cache-resident. Bucket
    row counts are padded uniform across shards (SPMD); oversized
    segments row-split through kernels.split_rows exactly like the eager
    pack.

      ell_buckets    [(idx (S*N_r, c)[, w, valid][, rowseg])...]
      ell_meta       [None | n_slots] per bucket (split fold width)
      ell_unpermute  (S*(Np+S*Hc),) int32 — position of each segment in
                     the stacked bucket output (+1 appended identity row
                     for empty segments)
      ell_out_len    stacked rows per shard (dead slot = this index)

    Receiver side — a width-R (pow2) combine row per local vertex over
    [received bins (S*Hc) ++ local partials (Np) ++ identity pad]: the
    local partial first, then contributing peers in ascending shard
    order, reduced through the same tree.

      recv_idx       (S*Np, R) int32
      recv_width     R
    """
    from janusgraph_tpu.olap.kernels import split_rows

    S, Np, Eq, Hc = (
        plan.num_shards, plan.shard_size, plan.edges_per_owner,
        plan.halo_cap,
    )
    assert plan.owner_lo == 0 and plan.owner_hi == S
    T = Np + S * Hc

    deg = np.zeros((S, T), dtype=np.int64)
    orders = []
    starts_all = []
    for q in range(S):
        base = q * Eq
        k = plan.edges_by_owner[q]
        seg = plan.blk_seg[base : base + k].astype(np.int64)
        order = np.argsort(seg, kind="stable")
        d = np.bincount(seg, minlength=T)[:T]
        deg[q] = d
        ip = np.zeros(T + 1, dtype=np.int64)
        np.cumsum(d, out=ip[1:])
        orders.append(order)
        starts_all.append(ip)

    caps = np.maximum(
        1, 1 << np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
    )
    caps = np.minimum(caps, _BLOCKED_ELL_MAX_CAP)
    # empty segments join no bucket; their unpermute slot reads the
    # appended identity row
    caps[deg == 0] = 0

    cap_set = sorted(
        c for c in set(int(x) for x in np.unique(caps)) if c > 0
    )
    buckets: List[Tuple] = []
    meta: List[Optional[int]] = []
    unpermute: Optional[np.ndarray] = None
    out_off = 0
    rows_total = 0
    for c in cap_set:
        members_per_shard = [np.nonzero(caps[q] == c)[0] for q in range(S)]
        split = c == _BLOCKED_ELL_MAX_CAP and any(
            len(m) and int(deg[q][m].max()) > c
            for q, m in enumerate(members_per_shard)
        )
        shard_rows = []
        for q in range(S):
            m = members_per_shard[q]
            st = starts_all[q][m]
            if split:
                shard_rows.append(split_rows(m, deg[q][m], st, c))
            else:
                shard_rows.append(
                    (st, deg[q][m], np.arange(len(m), dtype=np.int64))
                )
        N_rows = max(len(r[0]) for r in shard_rows)
        N_slots = max(len(m) for m in members_per_shard)
        if N_rows == 0:
            continue
        idx = np.full((S * N_rows, c), Np, dtype=np.int32)  # sentinel pad row
        if has_weight:
            wmat = np.zeros((S * N_rows, c), dtype=np.float32)
            valid = np.zeros((S * N_rows, c), dtype=np.float32)
        else:
            wmat = valid = None
        rowseg = np.full(S * N_rows, N_slots, dtype=np.int32)
        for q in range(S):
            members = members_per_shard[q]
            starts_r, degs_r, rseg = shard_rows[q]
            rows = len(starts_r)
            if rows == 0:
                continue
            base = q * Eq
            order = orders[q]
            total = int(degs_r.sum())
            if total:
                row_ids = np.repeat(np.arange(rows), degs_r)
                col_ids = np.arange(total) - np.repeat(
                    np.cumsum(degs_r) - degs_r, degs_r
                )
                epos = order[np.repeat(starts_r, degs_r) + col_ids]
                bidx = idx[q * N_rows : q * N_rows + rows]
                bidx[row_ids, col_ids] = plan.blk_src_loc[base + epos]
                if valid is not None:
                    valid[q * N_rows : q * N_rows + rows][
                        row_ids, col_ids
                    ] = 1.0
                if wmat is not None:
                    wmat[q * N_rows : q * N_rows + rows][
                        row_ids, col_ids
                    ] = plan.blk_weight[base + epos]
            rowseg[q * N_rows : q * N_rows + rows] = rseg.astype(np.int32)
            if unpermute is None:
                unpermute = np.zeros(S * T, dtype=np.int64)
            unpermute[q * T + members] = out_off + np.arange(len(members))
        if split:
            buckets.append((idx, wmat, valid, rowseg))
            meta.append(N_slots)
            out_off += N_slots
        else:
            buckets.append((idx, wmat, valid))
            meta.append(None)
            out_off += N_rows
        rows_total += N_rows
    if unpermute is None:
        unpermute = np.zeros(S * T, dtype=np.int64)
    # empty segments -> the appended identity row
    for q in range(S):
        empty = np.nonzero(deg[q] == 0)[0]
        unpermute[q * T + empty] = out_off
    plan.ell_buckets = buckets
    plan.ell_meta = meta
    plan.ell_unpermute = unpermute.astype(np.int32)
    plan.ell_out_len = out_off

    # receiver combine rows: local partial first, then ascending peers
    pairs_by_dst: Dict[int, List[int]] = {}
    width = 1
    for (q, s), u in plan.pair_lists.items():
        for j, v in enumerate(u):
            pairs_by_dst.setdefault(int(v), []).append((q, j))
    for v, lst in pairs_by_dst.items():
        width = max(width, 1 + len(lst))
    R = _next_pow2(width)
    sentinel = S * Hc + Np
    recv_idx = np.full((S * Np, R), sentinel, dtype=np.int32)
    recv_idx[:, 0] = S * Hc + (np.arange(S * Np) % Np)  # own local partial
    for v, lst in pairs_by_dst.items():
        s = v // Np
        row = recv_idx[v]
        for i, (q, j) in enumerate(sorted(lst)):
            row[1 + i] = q * Hc + j
    plan.recv_idx = recv_idx
    plan.recv_width = R


# ---------------------------------------------------------------------------
# numpy replay oracle + measured-wall probe


def _seg_reduce_np(op: str, data, seg, n: int):
    tail = data.shape[1:]
    if op == Combiner.SUM:
        acc = np.zeros((n,) + tail, dtype=data.dtype)
        np.add.at(acc, seg, data)
    elif op == Combiner.MIN:
        acc = np.full((n,) + tail, np.inf, dtype=data.dtype)
        np.minimum.at(acc, seg, data)
    else:
        acc = np.full((n,) + tail, -np.inf, dtype=data.dtype)
        np.maximum.at(acc, seg, data)
    return acc


def replay_superstep(
    plan: BlockedPlan,
    outgoing: np.ndarray,
    op: str,
    edge_transform=None,
    transform_cols=None,
    has_weight: bool = False,
    agg: str = "segment",
) -> np.ndarray:
    """The numpy twin of the device blocked superstep: same gathers, same
    per-shard reductions in the same edge order (segment scatter OR the
    packed gather + adjacent-pair tree), the same bin transpose standing
    in for the all_to_all, the same final combine — np.add.at /
    np.minimum.at match XLA CPU scatter reductions bitwise and
    tree_reduce is xp-generic, which makes this the blocked path's CPU
    oracle for BOTH aggregation formats."""
    S, Np, Eq, Hc = (
        plan.num_shards, plan.shard_size, plan.edges_per_owner,
        plan.halo_cap,
    )
    assert plan.owner_lo == 0 and plan.owner_hi == S, (
        "replay needs the full plan"
    )
    identity = np.float32(Combiner.IDENTITY[op])
    tail = outgoing.shape[1:]
    out = np.empty_like(outgoing)
    bins = np.empty((S, S * Hc) + tail, dtype=outgoing.dtype)
    local_parts = np.empty((S, Np) + tail, dtype=outgoing.dtype)
    nseg = Np + S * Hc + 1
    if agg == "ell":
        from janusgraph_tpu.olap.kernels import flat_take, tree_reduce

        if not hasattr(plan, "ell_buckets"):
            build_ell(plan, has_weight)
        pad_row = np.full((1,) + tail, identity, dtype=outgoing.dtype)
        for q in range(S):
            out_ext = np.concatenate(
                [outgoing[q * Np : (q + 1) * Np], pad_row], axis=0
            )
            parts = []
            for bucket, n_slots in zip(plan.ell_buckets, plan.ell_meta):
                idx, wm, va = bucket[0], bucket[1], bucket[2]
                rows = idx.shape[0] // S
                bi = idx[q * rows : (q + 1) * rows]
                m = flat_take(np, out_ext, bi)
                if wm is not None:
                    bw = wm[q * rows : (q + 1) * rows]
                    bv = va[q * rows : (q + 1) * rows]
                    m = apply_edge_transform(
                        np, m, bw, edge_transform, transform_cols
                    )
                    bv_ = bv.reshape(bv.shape + (1,) * (m.ndim - 2))
                    m = np.where(bv_ > 0, m, identity).astype(
                        outgoing.dtype
                    )
                    m = fp_fence(np, m)
                r = tree_reduce(np, m, op)
                if n_slots is not None:
                    rs = bucket[3][q * rows : (q + 1) * rows]
                    r = _seg_reduce_np(op, r, rs, n_slots + 1)[:n_slots]
                parts.append(r)
            stacked = np.concatenate(parts + [pad_row], axis=0)
            T = Np + S * Hc
            tab = stacked[plan.ell_unpermute[q * T : (q + 1) * T]]
            local_parts[q] = tab[:Np]
            bins[q] = tab[Np:]
    else:
        for q in range(S):
            base = q * Eq
            msgs = outgoing[q * Np + plan.blk_src_loc[base : base + Eq]]
            wq = plan.blk_weight[base : base + Eq] if has_weight else None
            msgs = apply_edge_transform(
                np, msgs, wq, edge_transform, transform_cols
            )
            valid = plan.blk_valid[base : base + Eq]
            vmask = valid.reshape((-1,) + (1,) * (msgs.ndim - 1))
            msgs = np.where(vmask > 0, msgs, identity).astype(outgoing.dtype)
            # mirror the device kernel's fp-contraction fence (+0.0, which
            # also normalizes -0.0 the same way on both sides)
            msgs = fp_fence(np, msgs)
            acc = _seg_reduce_np(
                op, msgs, plan.blk_seg[base : base + Eq], nseg
            )
            local_parts[q] = acc[:Np]
            bins[q] = acc[Np : Np + S * Hc]
    # all_to_all: shard s receives bins[q].reshape(S, Hc)[s] from each q
    binsq = bins.reshape((S, S, Hc) + tail)
    for s in range(S):
        recv = np.ascontiguousarray(binsq[:, s]).reshape((S * Hc,) + tail)
        if agg == "ell":
            from janusgraph_tpu.olap.kernels import flat_take, tree_reduce

            pad_row = np.full((1,) + tail, identity, dtype=outgoing.dtype)
            rtab = np.concatenate([recv, local_parts[s], pad_row], axis=0)
            ri = plan.recv_idx[s * Np : (s + 1) * Np]
            out[s * Np : (s + 1) * Np] = tree_reduce(
                np, flat_take(np, rtab, ri), op
            )
            continue
        rbase = s * (S * Hc)
        remote = _seg_reduce_np(
            op, recv, plan.recv_dst[rbase : rbase + S * Hc], Np + 1
        )[:Np]
        if op == Combiner.SUM:
            out[s * Np : (s + 1) * Np] = local_parts[s] + remote
        elif op == Combiner.MIN:
            out[s * Np : (s + 1) * Np] = np.minimum(local_parts[s], remote)
        else:
            out[s * Np : (s + 1) * Np] = np.maximum(local_parts[s], remote)
    return out


def measure_shard_walls(
    plan: BlockedPlan, repeats: int = 3
) -> List[float]:
    """MEASURED per-shard superstep walls (milliseconds): time each
    shard's real aggregation workload — the gather over its out-edges
    plus the local/bin segment reduction over its real edge count — on
    the host, taking the minimum over ``repeats`` (least scheduler
    noise). The SPMD barrier hides per-shard walls inside one dispatch;
    this probe runs the identical per-shard arithmetic shard-by-shard, so
    the skew report prices each shard from a measurement instead of the
    plan-derived share (cost_source="measured")."""
    S, Np, Eq, Hc = (
        plan.num_shards, plan.shard_size, plan.edges_per_owner,
        plan.halo_cap,
    )
    vals = (
        np.arange(plan.shard_size, dtype=np.float32) % 97 + 1.0
    )
    nseg = Np + S * Hc + 1
    walls: List[float] = []
    for row in range(plan.owner_hi - plan.owner_lo):
        base = row * Eq
        k = max(1, plan.edges_by_owner[row])
        src = plan.blk_src_loc[base : base + k]
        seg = plan.blk_seg[base : base + k]
        w = plan.blk_weight[base : base + k]
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            msgs = vals[src] * w
            acc = np.zeros(nseg, dtype=np.float32)
            np.add.at(acc, seg, msgs)
            best = min(best, time.perf_counter() - t0)
        walls.append(best * 1000.0)
    return walls
