"""Multi-host execution: the DCN-scale runtime path.

The reference scales OLAP beyond one machine by shipping vertex programs to
Spark executors over Hadoop input splits (reference:
janusgraph-hadoop/src/main/java/org/janusgraph/hadoop/formats/util/
HadoopInputFormat.java:34 + TinkerPop SparkGraphComputer via
janusgraph-hadoop/pom.xml:59); inter-node communication rides the storage
backend's RPC plus the KCVSLog control bus (SURVEY.md §2.4).

The TPU-native design needs no separate execution framework: JAX's
multi-controller runtime makes every host run the SAME program over one
global mesh, with XLA routing collectives over ICI within a slice and DCN
across slices. Everything the sharded executor already does — boundary
all_to_all exchange, psum aggregator barriers, fused while_loop spans —
works unchanged on a multi-host mesh, because shard_map compiles against
the mesh's GLOBAL device set. This module supplies the (small) glue:

  1. `init_multihost()` — jax.distributed.initialize wrapper (coordinator
     address + process count + process id, from args or the standard
     JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env).
  2. `global_mesh()` — a 1-D partition Mesh over the global device list,
     ordered so each host's local devices are contiguous (shard i lives on
     the host that loaded partition i's CSR block).
  3. `host_partition_range()` — which storage partitions this host should
     load (couples with olap/distributed_load.py, whose split unit is the
     same contiguous partition key range the mesh shards by).

Single-process operation (num_processes == 1) skips
jax.distributed.initialize entirely, so the same code path runs in tests
and on the virtual 8-device CPU mesh. The driver's dryrun certifies the
compile/execute path on a virtual mesh; real multi-host hardware is not
available in this environment (SURVEY.md §2.4.3), so the glue is kept
deliberately thin and fully exercised minus the actual DCN transport.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    config=None,
) -> int:
    """Initialize the JAX multi-controller runtime. Returns the process id.

    Arguments default to the standard env vars, then to the graph's
    cluster.* options when a GraphConfiguration is passed
    (cluster.coordinator-address / num-processes / process-id — the
    config-file deployment shape; env always wins so launchers can
    override). With one process (or no configuration at all) this is a
    no-op returning 0, so library code can call it unconditionally.
    """
    cfg_addr = cfg_procs = cfg_pid = None
    if config is not None:
        cfg_addr = config.get("cluster.coordinator-address") or None
        cfg_procs = config.get("cluster.num-processes") or None
        cfg_pid = config.get("cluster.process-id")
    coordinator_address = (
        coordinator_address
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or cfg_addr
    )
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env is not None else (cfg_procs or 1)
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env is not None else (cfg_pid or 0)
    if num_processes <= 1:
        return 0
    if not coordinator_address:
        raise ValueError(
            "multi-host run needs a coordinator address "
            "(JAX_COORDINATOR_ADDRESS or coordinator_address=)"
        )
    # fail the init fast (and say which spelling resolved) if this jax has
    # no usable shard_map — every sharded program compiled after distributed
    # init goes through the compat shim, so a broken resolution should
    # surface here, not at the first superstep compile on every host
    from janusgraph_tpu.parallel.compat import resolve_shard_map

    resolve_shard_map()
    import jax

    # CPU multi-process needs an explicit cross-host collectives transport:
    # without one, the first sharded device_put/psum dies with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Pick gloo (shipped in this jaxlib) unless the operator already chose;
    # harmless on TPU runs, which ride ICI/DCN and ignore the CPU setting.
    try:
        if jax.config.values.get(
            "jax_cpu_collectives_implementation"
        ) in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # unknown option on this jax: leave defaults alone
        pass

    # the black box should carry the cluster-formation timeline: a wedged
    # coordinator (or one host missing) is the first question an incident
    # review asks, and by then the process that knows may be gone
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.record(
        "multihost", action="init",
        processes=int(num_processes), process_id=int(process_id),
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        flight_recorder.record(
            "multihost", action="init_failed",
            processes=int(num_processes), process_id=int(process_id),
            error=f"{type(e).__name__}: {e}"[:200],
        )
        raise
    flight_recorder.record(
        "multihost", action="init_ok",
        processes=int(num_processes), process_id=int(process_id),
    )
    return process_id


def global_mesh(axis: str = "p"):
    """A 1-D Mesh over the GLOBAL device list (all hosts), host-contiguous.

    jax.devices() already orders devices process-by-process, so shard k of
    the mesh lands on host k // local_device_count — matching
    `host_partition_range`'s assignment of storage partitions to hosts.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


def host_partition_range(
    num_partitions: int,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> Tuple[int, int]:
    """[lo, hi) storage-partition ids this host loads (contiguous blocks,
    remainder spread over the leading hosts) — the input-split assignment
    for olap/distributed_load.py on a multi-host run."""
    import jax

    if process_id is None:
        process_id = jax.process_index()
    if num_processes is None:
        num_processes = jax.process_count()
    base, extra = divmod(num_partitions, num_processes)
    lo = process_id * base + min(process_id, extra)
    hi = lo + base + (1 if process_id < extra else 0)
    return lo, hi


def host_shard_range(
    num_shards: int,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> Tuple[int, int]:
    """[lo, hi) MESH SHARDS whose blocked-plan blocks this host builds
    (parallel/halo.BlockedPlan.build_local). Deliberately the same
    contiguous assignment as host_partition_range: a host's loaded
    storage partitions are exactly the source-side edge sets of its
    shards, so distributed CSR loading feeds the local plan build with
    no edge redistribution — only the compact per-pair destination
    lists (the halo index) are exchanged as metadata."""
    return host_partition_range(num_shards, process_id, num_processes)
