"""Multi-chip sharded BSP executor: shard_map over a device mesh.

This is the distributed-communication redesign mandated by SURVEY.md §2.4:
the reference has no NCCL/MPI — its "communication" is writing message cells
into the storage backend and re-scanning (KCVSLog for control plane). Here
the data plane is XLA collectives over ICI:

  - vertex state and in-edge CSR blocks are sharded over the mesh axis by
    contiguous vertex-index blocks (the analogue of the reference's
    partition-prefixed key ranges, IDManager.getKey:480);
  - each superstep exchanges ONLY boundary messages: at build time every
    (src-shard q → dst-shard s) pair gets a bucket of the distinct source
    vertices in q whose messages s actually needs (q's boundary set toward
    s); the superstep gathers those values and swaps buckets with ONE
    `lax.all_to_all` over ICI — per-shard comm volume is S·B elements
    (B = max boundary-bucket size) instead of the full O(n) vertex vector an
    all_gather would move. This replaces Fulgora's pull-based reversed slice
    rescans (VertexProgramScanJob.java:114-135) the way FulgoraVertexMemory
    holds only the messages each worker consumes (FulgoraVertexMemory.java:91-99);
  - local aggregation uses a degree-bucketed ELL layout (gather + dense
    axis-1 reduction, no scatter — see olap/kernels.py) whose bucket shapes
    are made uniform across shards so one SPMD program serves the mesh;
  - global aggregators reduce with psum/pmin/pmax at the superstep barrier —
    replacing FulgoraMemory's in-process sub-round barrier;
  - vertex-cut merging is subsumed at CSR-load canonicalization.

Shards are equal-sized (SPMD): vertices pad to S*Np, per-shard edge lists pad
to the max shard edge count with masked no-op entries. Programs see the same
interface as single-chip (`active` marks real vertices).

Runs identically on a real multi-chip mesh and on the CPU-device test mesh
(xla_force_host_platform_device_count) — the "multi-node without a cluster"
test technique.
"""

from __future__ import annotations

import inspect
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.csr import CSRGraph
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    Memory,
    VertexProgram,
    apply_edge_transform,
)

_ELL_MAX_CAPACITY = 1 << 14

#: modeled per-shard skew (slowest/mean) above which the run leaves a
#: ``shard_skew`` event on the flight-recorder timeline even without an
#: injected straggler — a 2x-imbalanced mesh wastes half its silicon
SKEW_FLIGHT_THRESHOLD = 2.0


class ShardedCSR:
    """Host-side sharded/padded representation, ready for device placement.

    Arrays with leading dim S*Np (vertex-sharded) or S*Em (edge-sharded):
      out_degree   (S*Np,) float32
      active       (S*Np,) float32
      in_src_glob  (S*Em,) int32  — global (padded) source vertex index
      in_dst_loc   (S*Em,) int32  — destination index local to its shard
      in_valid     (S*Em,) float32
      in_weight    (S*Em,) float32 (all ones if unweighted)

    Boundary-exchange plan (the all-to-all schedule):
      boundary_width B — max distinct cross-shard sources any (q→s) pair needs
      send_idx     (S*S, B) int32 — row q*S+s: indices LOCAL TO q of the
                   sources q must send to s (padded with 0; padded slots are
                   transmitted but never referenced by any receiver)
      in_src_tab   (S*Em,) int32 — per-edge index into the superstep message
                   table [own outgoing (Np) ++ received buckets (S*B)]

    Uniform ELL pack (SPMD-identical bucket shapes across shards):
      ell_buckets  list of (idx (S*N_c, c) int32, w (S*N_c, c) f32,
                   valid (S*N_c, c) f32); idx indexes the message table,
                   sentinel = Np + S*B
      ell_unpermute (S*Np,) int32 — position of each local vertex in the
                   concatenated bucket output (local length sum_c N_c)
    """

    def __init__(
        self,
        csr: CSRGraph,
        num_shards: int,
        undirected: bool,
        edges: Optional[Tuple] = None,
    ):
        n = csr.num_vertices
        S = num_shards
        Np = -(-max(n, 1) // S)  # ceil
        self.csr = csr
        self.num_shards = S
        self.shard_size = Np
        self.padded_n = S * Np
        self.real_n = n

        if edges is not None:
            # pre-filtered edge view (EdgeChannel): messages flow src -> dst
            src, dst, w = edges
            src = np.asarray(src, dtype=np.int64)
            dst = np.asarray(dst, dtype=np.int64)
            self.has_weight = w is not None
            w = (
                np.asarray(w, dtype=np.float32)
                if w is not None
                else np.ones(len(src), dtype=np.float32)
            )
        else:
            src = csr.in_src.astype(np.int64)
            dst = np.repeat(
                np.arange(n, dtype=np.int64), np.diff(csr.in_indptr)
            )
            self.has_weight = csr.in_edge_weight is not None
            w = (
                csr.in_edge_weight.astype(np.float32)
                if csr.in_edge_weight is not None
                else np.ones(len(src), dtype=np.float32)
            )
            if undirected:
                # symmetric closure: aggregate both orientations in one pass
                src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
                w = np.concatenate([w, w])

        # sorting by dst groups edges by owning shard (shard = dst // Np is
        # monotone in dst) AND keeps each shard's edges dst-sorted, which the
        # ELL fill below requires
        order = np.argsort(dst, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        shard_of = dst // Np
        counts = np.bincount(shard_of, minlength=S)
        Em = int(counts.max()) if len(counts) else 0
        Em = max(Em, 1)
        self.edges_per_shard = Em
        offsets = np.zeros(S + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])

        in_src_glob = np.zeros(S * Em, dtype=np.int32)
        in_dst_loc = np.zeros(S * Em, dtype=np.int32)
        in_valid = np.zeros(S * Em, dtype=np.float32)
        in_weight = np.ones(S * Em, dtype=np.float32)
        for s in range(S):
            lo, hi = offsets[s], offsets[s + 1]
            k = hi - lo
            base = s * Em
            in_src_glob[base : base + k] = src[lo:hi]
            in_dst_loc[base : base + k] = dst[lo:hi] - s * Np
            in_valid[base : base + k] = 1.0
            in_weight[base : base + k] = w[lo:hi]

        out_degree = np.zeros(S * Np, dtype=np.float32)
        out_degree[:n] = csr.out_degree
        active = np.zeros(S * Np, dtype=np.float32)
        active[:n] = 1.0
        # padded per-vertex in-degree of THIS edge view (dense programs
        # normalize by it — GCNForwardProgram's mean aggregation)
        in_degree = np.zeros(S * Np, dtype=np.float32)
        for s in range(S):
            k = int(offsets[s + 1] - offsets[s])
            np.add.at(
                in_degree, s * Np + in_dst_loc[s * Em : s * Em + k], 1.0
            )

        self.out_degree = out_degree
        self.active = active
        self.in_degree = in_degree
        self.in_src_glob = in_src_glob
        self.in_dst_loc = in_dst_loc
        self.in_valid = in_valid
        self.in_weight = in_weight

        # retained for the lazily-built exchange plan / ELL pack — each
        # executor configuration pays only for the structures it ships
        self._src_sorted = src
        self._offsets = offsets
        self._exchange_built = False
        self._ell_built = False

    def ensure_exchange_plan(self) -> None:
        """Build the boundary all-to-all plan (send_idx / in_src_tab) once,
        on first use — the gather/segment debug path never pays for it."""
        if self._exchange_built:
            return
        self._exchange_built = True
        S, Np, Em = self.num_shards, self.shard_size, self.edges_per_shard
        src, offsets = self._src_sorted, self._offsets

        # distinct sources per (q → s) pair
        uniq: Dict[Tuple[int, int], np.ndarray] = {}
        inv_parts: List[Tuple[int, np.ndarray, int, np.ndarray]] = []
        B = 1
        for s in range(S):
            lo, hi = offsets[s], offsets[s + 1]
            ssrc = src[lo:hi]
            qof = ssrc // Np
            for q in range(S):
                if q == s:
                    continue
                m = np.nonzero(qof == q)[0]
                if len(m) == 0:
                    continue
                u, inv = np.unique(ssrc[m], return_inverse=True)
                uniq[(q, s)] = u
                inv_parts.append((s, m, q, inv))
                B = max(B, len(u))
        self.boundary_width = B

        send_idx = np.zeros((S * S, B), dtype=np.int32)
        for (q, s), u in uniq.items():
            send_idx[q * S + s, : len(u)] = u - q * Np
        self.send_idx = send_idx

        in_src_tab = np.zeros(S * Em, dtype=np.int32)
        for s in range(S):
            lo, hi = offsets[s], offsets[s + 1]
            k = hi - lo
            ssrc = src[lo:hi]
            local = (ssrc // Np) == s
            seg = in_src_tab[s * Em : s * Em + k]
            seg[local] = (ssrc[local] - s * Np).astype(np.int32)
        for s, m, q, inv in inv_parts:
            in_src_tab[s * Em + m] = (Np + q * B + inv).astype(np.int32)
        self.in_src_tab = in_src_tab
        self.msg_table_len = Np + S * B
        # per-superstep comm volume (elements/shard): a2a vs all_gather
        self.comm_a2a_elems = S * B
        self.comm_gather_elems = self.padded_n

    def ensure_ring(self) -> None:
        """Build the ring-exchange plan once: per shard, edge slots grouped
        by SOURCE OWNER into uniform blocks of Eo = max edges any (shard,
        owner) pair holds, so ring step t reduces exactly one owner's block
        (dynamic-slice by traced owner index) instead of masking the whole
        edge list every step. Arrays (leading dim S, per-shard layout
        owner-major):
          ring_src_loc (S*S*Eo,) int32 — source index LOCAL to the owner
          ring_dst_loc (S*S*Eo,) int32 — destination local to this shard
          ring_valid   (S*S*Eo,) f32
          ring_weight  (S*S*Eo,) f32
        """
        if getattr(self, "_ring_built", False):
            return
        self._ring_built = True
        S, Np, Em = self.num_shards, self.shard_size, self.edges_per_shard
        src, offsets = self._src_sorted, self._offsets

        counts = np.zeros((S, S), dtype=np.int64)
        per_shard = []
        for s in range(S):
            lo, hi = offsets[s], offsets[s + 1]
            ssrc = src[lo:hi]
            owner = (ssrc // Np).astype(np.int64)
            order = np.argsort(owner, kind="stable")
            per_shard.append((lo, order, owner[order]))
            counts[s] = np.bincount(owner, minlength=S)
        Eo = max(1, int(counts.max()))
        self.ring_block = Eo

        ring_src = np.zeros((S, S * Eo), dtype=np.int32)
        ring_dst = np.zeros((S, S * Eo), dtype=np.int32)
        ring_valid = np.zeros((S, S * Eo), dtype=np.float32)
        ring_weight = np.ones((S, S * Eo), dtype=np.float32)
        for s in range(S):
            lo, order, owner_sorted = per_shard[s]
            k = len(order)
            if not k:
                continue
            gsrc = src[lo + order]
            # position within each owner block
            block_start = np.concatenate(
                ([0], np.cumsum(np.bincount(owner_sorted, minlength=S)))
            )
            pos = np.arange(k) - block_start[owner_sorted]
            col = owner_sorted * Eo + pos
            ring_src[s, col] = (gsrc - owner_sorted * Np).astype(np.int32)
            ring_dst[s, col] = self.in_dst_loc[s * Em + order]
            ring_valid[s, col] = 1.0
            ring_weight[s, col] = self.in_weight[s * Em + order]
        self.ring_src_loc = ring_src.reshape(-1)
        self.ring_dst_loc = ring_dst.reshape(-1)
        self.ring_valid = ring_valid.reshape(-1)
        self.ring_weight = ring_weight.reshape(-1)

    def ensure_frontier_plan(self) -> None:
        """Build the frontier-compaction plan once: per shard, a CSC over
        MESSAGE-TABLE SLOTS (own Np ++ received S*B buckets) so a superstep
        can expand only the edges whose source slot is fresh, instead of
        gathering all Em local edges (the sharded analogue of
        olap/frontier.py's capped expansion; VERDICT r4 #2). Arrays
        (leading dim divisible by S, device-shardable):
          ftr_ip        (S*(T+2),) int32 — per-shard CSC indptr over table
                        slots, +1 sentinel row (slot T reads degree 0 — the
                        compaction fill target)
          ftr_dst       (S*Em,) int32 — local destination, CSC order
          ftr_w         (S*Em,) f32  — edge weight, CSC order
          ftr_deg       (S*T,) int32 — edges per table slot (planning)
          ftr_src_glob  (S*T,) int32 — global source vertex index per slot
                        (predecessor tracking); bucket pad slots alias the
                        peer's vertex 0 but carry degree 0, so they can
                        never contribute a message
        Only VALID edges enter the CSC (the dense path's in_valid pad slots
        are excluded) — a padded-edge slot must not resurrect under slot-0.
        """
        if getattr(self, "_frontier_built", False):
            return
        self.ensure_exchange_plan()
        self._frontier_built = True
        S, Np, Em = self.num_shards, self.shard_size, self.edges_per_shard
        B, T = self.boundary_width, self.msg_table_len
        offsets = self._offsets

        ftr_ip = np.zeros(S * (T + 2), dtype=np.int32)
        ftr_dst = np.zeros(S * Em, dtype=np.int32)
        ftr_w = np.ones(S * Em, dtype=np.float32)
        ftr_deg = np.zeros(S * T, dtype=np.int32)
        ftr_src_glob = np.zeros(S * T, dtype=np.int32)
        for s in range(S):
            k = int(offsets[s + 1] - offsets[s])
            base = s * Em
            tabidx = self.in_src_tab[base : base + k]
            order = np.argsort(tabidx, kind="stable")
            deg = np.bincount(tabidx[order], minlength=T)
            ip = np.zeros(T + 2, dtype=np.int64)
            np.cumsum(deg, out=ip[1 : T + 1])
            ip[T + 1] = ip[T]
            ftr_ip[s * (T + 2) : (s + 1) * (T + 2)] = ip
            ftr_dst[base : base + k] = self.in_dst_loc[base : base + k][order]
            ftr_w[base : base + k] = self.in_weight[base : base + k][order]
            ftr_deg[s * T : s * T + T] = deg
            glob = np.zeros(T, dtype=np.int64)
            glob[:Np] = s * Np + np.arange(Np)
            for q in range(S):
                if q == s:
                    continue
                glob[Np + q * B : Np + (q + 1) * B] = (
                    q * Np + self.send_idx[q * S + s]
                )
            ftr_src_glob[s * T : s * T + T] = glob
        self.ftr_ip = ftr_ip
        self.ftr_dst = ftr_dst
        self.ftr_w = ftr_w
        self.ftr_deg = ftr_deg
        self.ftr_src_glob = ftr_src_glob

    def ensure_blocked_plan(self) -> None:
        """Build the propagation-blocked (source-partitioned) halo plan
        once, on first use (parallel/halo.py): per-owner edge blocks whose
        superstep kernel bins remote-bound messages by destination shard,
        merges them locally, and exchanges pow2-tiered bins in ONE
        all_to_all — the a2a boundary table is never materialized."""
        if getattr(self, "_blocked_built", False):
            return
        self._blocked_built = True
        from janusgraph_tpu.parallel import halo

        src, dst, w = halo.edges_from_sharded(self)
        plan = halo.BlockedPlan.build(
            src, dst, w, self.num_shards, self.shard_size
        )
        self.blocked_plan = plan
        self.blk_src_loc = plan.blk_src_loc
        self._blocked_ell_built = False
        self.blk_seg = plan.blk_seg
        self.blk_bin_seg = plan.blk_bin_seg
        self.blk_valid = plan.blk_valid
        self.blk_weight = plan.blk_weight
        self.recv_dst = plan.recv_dst
        self.halo_cap = plan.halo_cap
        self.edges_per_owner = plan.edges_per_owner
        # per-superstep comm volume (elements/shard), blocked exchange
        self.comm_blocked_elems = self.num_shards * plan.halo_cap

    def ensure_frontier_plan_blocked(self) -> None:
        """Frontier CSC over the BLOCKED message table [own Np ++ received
        merged bins S*Hc]: local slots keep their intra-shard edges; each
        used (q→s, j) bin slot collapses that pair's remote edges into ONE
        edge to its destination (weight 0 — the sender already folded the
        edge weight into the merged MIN), so remote expansion work shrinks
        from per-edge to per-distinct-destination and each hop exchanges
        S*Hc merged elements instead of the S*B boundary table."""
        if getattr(self, "_frontier_blocked_built", False):
            return
        self.ensure_blocked_plan()
        self._frontier_blocked_built = True
        from janusgraph_tpu.parallel import halo

        plan = self.blocked_plan
        S, Np, Hc = self.num_shards, self.shard_size, self.halo_cap
        T = Np + S * Hc
        src, dst, w = halo.edges_from_sharded(self)
        owner = src // Np
        dshard = dst // Np

        slot_parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        E2 = 1
        for s in range(S):
            loc = np.nonzero((owner == s) & (dshard == s))[0]
            slots = [src[loc] - s * Np]
            dsts = [dst[loc] - s * Np]
            ws = [w[loc]]
            for q in range(S):
                u = plan.pair_lists.get((q, s))
                if u is None:
                    continue
                slots.append(Np + q * Hc + np.arange(len(u)))
                dsts.append(u - s * Np)
                ws.append(np.zeros(len(u), dtype=np.float32))
            sl = np.concatenate(slots).astype(np.int64)
            dl = np.concatenate(dsts).astype(np.int64)
            wl = np.concatenate(ws).astype(np.float32)
            order = np.argsort(sl, kind="stable")
            slot_parts.append((sl[order], dl[order], wl[order]))
            E2 = max(E2, len(sl))
        self.fblk_edges = E2
        ftr_ip = np.zeros(S * (T + 2), dtype=np.int32)
        ftr_dst = np.zeros(S * E2, dtype=np.int32)
        ftr_w = np.ones(S * E2, dtype=np.float32)
        ftr_deg = np.zeros(S * T, dtype=np.int32)
        for s in range(S):
            sl, dl, wl = slot_parts[s]
            k = len(sl)
            deg = np.bincount(sl, minlength=T)
            ip = np.zeros(T + 2, dtype=np.int64)
            np.cumsum(deg, out=ip[1 : T + 1])
            ip[T + 1] = ip[T]
            ftr_ip[s * (T + 2) : (s + 1) * (T + 2)] = ip
            ftr_dst[s * E2 : s * E2 + k] = dl
            ftr_w[s * E2 : s * E2 + k] = wl
            ftr_deg[s * T : s * T + T] = deg
        self.fblk_ip = ftr_ip
        self.fblk_dst = ftr_dst
        self.fblk_w = ftr_w
        self.fblk_deg = ftr_deg

    def ensure_blocked_ell(self) -> None:
        """Build the packed aggregation for the blocked exchange once:
        sender-side uniform ELL over [local destinations ++ outgoing
        bins] + the receiver's width-R combine rows (halo.build_ell) —
        gathers and adjacent-pair trees only, no scatter."""
        self.ensure_blocked_plan()
        if self._blocked_ell_built:
            return
        self._blocked_ell_built = True
        from janusgraph_tpu.parallel import halo

        halo.build_ell(self.blocked_plan, self.has_weight)
        plan = self.blocked_plan
        self.bell_buckets = plan.ell_buckets
        self.bell_meta = plan.ell_meta
        self.bell_unpermute = plan.ell_unpermute
        self.bell_recv_idx = plan.recv_idx
        self.bell_recv_width = plan.recv_width

    def ensure_ell(self) -> None:
        """Build the uniform ELL pack once, on first use (requires the
        exchange plan: ELL indices point into the a2a message table)."""
        if self._ell_built:
            return
        self.ensure_exchange_plan()
        self._ell_built = True
        self._build_uniform_ell(self._offsets, self.edges_per_shard)

    def _build_uniform_ell(self, offsets: np.ndarray, Em: int) -> None:
        """Per-shard degree-bucketed ELL with bucket shapes made UNIFORM
        across shards (pad each capacity's row count to the max over shards)
        so the pack can be passed through shard_map as plain sharded arrays
        (SPMD requires identical per-shard shapes)."""
        from janusgraph_tpu import native

        S, Np = self.num_shards, self.shard_size
        sentinel = self.msg_table_len

        deg = np.zeros((S, Np), dtype=np.int64)
        indptr = np.zeros((S, Np + 1), dtype=np.int64)
        for s in range(S):
            k = int(offsets[s + 1] - offsets[s])
            d = np.bincount(
                self.in_dst_loc[s * Em : s * Em + k].astype(np.int64),
                minlength=Np,
            )
            deg[s] = d
            np.cumsum(d, out=indptr[s, 1:])

        # capacity per vertex: next pow2 >= degree (min 1), clamped to the
        # max capacity — larger degrees row-split into ceil(d/cap) rows of
        # the top bucket, folded by a rows-sized segment reduce (supernodes:
        # SURVEY.md §5.7; avoids padding a jumbo bucket to the max degree)
        caps = np.maximum(
            1, 1 << np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64)
        )
        caps = np.minimum(caps, _ELL_MAX_CAPACITY)

        from janusgraph_tpu.olap.kernels import split_rows

        cap_set = sorted(set(int(c) for c in np.unique(caps)))
        self.ell_buckets: List[Tuple] = []
        # static per-bucket metadata: None (rows == slots) or the slot count
        # (+1 dead slot for padded rows) of a row-split bucket
        self.ell_meta: List[Optional[int]] = []
        unpermute = np.zeros(S * Np, dtype=np.int32)
        out_off = 0
        for c in cap_set:
            members_per_shard = [
                np.nonzero(caps[s] == c)[0] for s in range(S)
            ]
            split = c == _ELL_MAX_CAPACITY and any(
                len(m) and int(deg[s][m].max()) > c
                for s, m in enumerate(members_per_shard)
            )
            shard_rows = []
            for s in range(S):
                m = members_per_shard[s]
                if split:
                    shard_rows.append(
                        split_rows(m, deg[s][m], indptr[s][m], c)
                    )
                else:
                    shard_rows.append(
                        (indptr[s][m], deg[s][m],
                         np.arange(len(m), dtype=np.int64))
                    )
            N_rows = max(len(r[0]) for r in shard_rows)
            N_slots = max(len(m) for m in members_per_shard)
            if N_rows == 0:
                continue
            idx = np.full((S * N_rows, c), sentinel, dtype=np.int32)
            # unweighted: idx only — padded slots point at the message
            # table's identity pad slot (mirrors olap/kernels.py ELLPack)
            if self.has_weight:
                wmat = np.zeros((S * N_rows, c), dtype=np.float32)
                valid = np.zeros((S * N_rows, c), dtype=np.float32)
            else:
                wmat = valid = None
            # padded rows point at the dead slot (N_slots) and are dropped
            rowseg = np.full(S * N_rows, N_slots, dtype=np.int32)
            for s in range(S):
                members = members_per_shard[s]
                starts_r, degs_r, rseg = shard_rows[s]
                rows = len(starts_r)
                if rows == 0:
                    continue
                src32 = np.ascontiguousarray(
                    self.in_src_tab[s * Em : (s + 1) * Em], dtype=np.int32
                )
                w32 = np.ascontiguousarray(
                    self.in_weight[s * Em : (s + 1) * Em], dtype=np.float32
                )
                bidx = idx[s * N_rows : s * N_rows + rows]
                bw = (
                    wmat[s * N_rows : s * N_rows + rows]
                    if wmat is not None else None
                )
                bv = (
                    valid[s * N_rows : s * N_rows + rows]
                    if valid is not None else None
                )
                if not native.ell_fill(c, starts_r, degs_r, src32, w32, bidx, bw, bv):
                    total = int(degs_r.sum())
                    if total:
                        row_ids = np.repeat(np.arange(rows), degs_r)
                        col_ids = np.arange(total) - np.repeat(
                            np.cumsum(degs_r) - degs_r, degs_r
                        )
                        edge_pos = np.repeat(starts_r, degs_r) + col_ids
                        bidx[row_ids, col_ids] = src32[edge_pos]
                        if bv is not None:
                            bv[row_ids, col_ids] = 1.0
                        if bw is not None:
                            bw[row_ids, col_ids] = w32[edge_pos]
                rowseg[s * N_rows : s * N_rows + rows] = rseg.astype(np.int32)
                unpermute[s * Np + members] = (
                    out_off + np.arange(len(members))
                ).astype(np.int32)
            if split:
                self.ell_buckets.append((idx, wmat, valid, rowseg))
                self.ell_meta.append(N_slots)
                out_off += N_slots
            else:
                self.ell_buckets.append((idx, wmat, valid))
                self.ell_meta.append(None)
                out_off += N_rows
        self.ell_unpermute = unpermute
        self.ell_out_len = out_off


class _GlobalView:
    """Padded global view handed to program.setup (host side)."""

    def __init__(self, sharded: ShardedCSR):
        self.num_vertices = sharded.real_n
        self.local_num_vertices = sharded.padded_n
        self.global_offset = 0
        self.out_degree = sharded.out_degree
        self.active = sharded.active
        self.in_degree = sharded.in_degree


class _ShardView:
    """Per-shard view inside shard_map (traced)."""

    def __init__(
        self, num_vertices, shard_size, offset, out_degree, active,
        in_degree=None,
    ):
        self.num_vertices = num_vertices          # real global count (static)
        self.local_num_vertices = shard_size      # padded local (static)
        self.global_offset = offset               # traced scalar
        self.out_degree = out_degree
        self.active = active
        self.in_degree = in_degree


class ShardedExecutor:
    """BSP executor over a jax.sharding.Mesh (1-D axis 'p').

    exchange: "blocked" — propagation-blocked halo exchange (the default
              fast path, PAPERS.md arXiv:2011.08451): remote-bound
              messages are binned by destination shard inside the
              superstep kernel, combiner-merged locally, and the pow2-
              tiered merged bins swap in ONE lax.all_to_all — comm volume
              S*halo_cap elements (distinct remote DESTINATIONS), no
              message-table concatenation, receiver work one S*halo_cap
              scatter-combine;
              "a2a" — eager boundary-bucket lax.all_to_all (ships raw
              boundary SOURCE values, S*B elements, receiver aggregates
              its remote edges);
              "ring" — S-step lax.ppermute rotation: each step one shard's
              outgoing block streams past and its contribution is folded in
              (the ring-attention pattern applied to message aggregation —
              peak comm memory O(Np) per step instead of the S*B bucket
              table; the right shape when boundary sets approach O(n));
              "gather" — full-vector all_gather (debug/reference path);
              "auto" — olap/autotune.decide_sharded picks from the graph's
              boundary/halo widths + the device roofline, keyed by shard
              count (decision recorded in run_info["autotune"]).
    agg:      "ell" (default; a2a only) — uniform degree-bucketed ELL;
              "segment" — flat segment reduction (ring/gather use this);
              "bin" — the blocked exchange's fused bin+local segment
              reduction (implied by exchange='blocked').
    """

    def __init__(
        self,
        csr: CSRGraph,
        mesh=None,
        axis: str = "p",
        exchange: str = "a2a",
        agg: str = "ell",
        frontier_tier_growth: int = None,
        shard_measure: bool = None,
    ):
        import jax
        from jax.sharding import Mesh

        self.jax = jax
        self.axis = axis
        if mesh is None:
            devices = np.array(jax.devices())
            mesh = Mesh(devices, (axis,))
        self.mesh = mesh
        self.num_shards = mesh.devices.size
        self.csr = csr
        if exchange not in ("a2a", "ring", "gather", "blocked", "auto"):
            raise ValueError(f"unknown exchange {exchange!r}")
        if exchange in ("gather", "ring") and agg == "ell":
            # the ELL pack indexes the a2a message table, which the other
            # exchanges never build — refuse rather than silently rewiring
            raise ValueError(
                "agg='ell' requires exchange='a2a' (the ELL indices point "
                "into the all-to-all message table); use agg='segment' with "
                f"exchange={exchange!r}"
            )
        if exchange == "blocked" and agg not in ("ell", "segment"):
            raise ValueError(
                "exchange='blocked' aggregates via 'ell' (packed gather + "
                f"tree) or 'segment' (fused scatter); got agg={agg!r}"
            )
        #: "auto" defers to olap/autotune.decide_sharded at first run
        self.exchange_requested = exchange
        self.exchange = exchange
        self.agg = agg
        #: measured per-shard superstep walls (host probe) feeding the
        #: skew report; None/True = on, False = plan-derived costs only
        self.shard_measure = True if shard_measure is None else shard_measure
        #: autotune decision record for the most recent auto resolution
        self._autotune_record = None
        #: fresh compiles this run (the registry's retrace/compile-cache
        #: economics; counted at every compiled-fn cache miss)
        self._new_execs = 0
        #: bytes device_put this run (h2d_arg_bytes in the run record)
        self._h2d_bytes = 0
        from collections import OrderedDict

        self._compiled: Dict[Tuple, object] = {}
        self._sharded_cache: Dict[object, ShardedCSR] = {}
        self._channel_views: "OrderedDict" = OrderedDict()
        self._device_cache: Dict[Tuple[object, str], object] = {}
        # (cache_key, op) -> {metric_key: combiner_op}; recorded when the
        # shard body is traced (see TPUExecutor._metric_ops)
        self._metric_ops: Dict[Tuple, Dict[str, str]] = {}
        self._frontier_engine = None
        # computer.frontier-tier-growth (ShardedFrontierEngine override)
        self._frontier_tier_growth = frontier_tier_growth
        #: observability for the most recent run (path + frontier tiers)
        self.last_run_info: Dict[str, object] = {}

    def comm_stats(self, undirected: bool = False) -> Dict[str, object]:
        """Per-superstep exchange volume in elements per shard. Each plan
        (a2a boundary table / blocked halo bins) is only materialized for
        executors configured to use it — ring exists precisely for the
        regime where the O(S*S*B) table is most expensive to build."""
        self._resolve_exchange(undirected)
        sc = self._sharded(undirected)
        stats: Dict[str, object] = {
            "gather_elems": sc.padded_n,
            # ring: S-1 hops x one Np block streamed per superstep (the own
            # block folds locally), peak resident comm buffer one Np block
            "ring_elems": (self.num_shards - 1) * sc.shard_size,
            "ring_peak_elems": sc.shard_size,
            "a2a_elems": None,
            "boundary_width": None,
            "blocked_elems": None,
            "halo_cap": None,
            #: collectives per superstep carrying message payload
            "batches": self.num_shards - 1 if self.exchange == "ring" else 1,
        }
        if self.exchange == "a2a":
            sc.ensure_exchange_plan()
            stats["a2a_elems"] = sc.comm_a2a_elems
            stats["boundary_width"] = sc.boundary_width
        if self.exchange == "blocked":
            sc.ensure_blocked_plan()
            stats["blocked_elems"] = sc.comm_blocked_elems
            stats["halo_cap"] = sc.halo_cap
        return stats

    def _exchange_info(self, sc: ShardedCSR) -> Dict[str, object]:
        """run_info["exchange"]: what the configured exchange actually
        ships per superstep and per shard — elements, f32 payload bytes,
        and the number of message-carrying collectives (batches)."""
        S = self.num_shards
        if self.exchange == "blocked":
            sc.ensure_blocked_plan()
            elems, width = sc.comm_blocked_elems, sc.halo_cap
        elif self.exchange == "a2a":
            sc.ensure_exchange_plan()
            elems, width = sc.comm_a2a_elems, sc.boundary_width
        elif self.exchange == "ring":
            elems, width = (S - 1) * sc.shard_size, sc.shard_size
        else:
            elems, width = sc.padded_n, sc.padded_n
        return {
            "mode": self.exchange,
            "agg": self.agg,
            "elems_per_superstep": int(elems),
            "bytes_per_superstep": int(elems) * 4,
            "batches_per_superstep": S - 1 if self.exchange == "ring" else 1,
            "width": int(width),
        }

    def _resolve_exchange(self, undirected: bool = False) -> None:
        """Resolve exchange='auto' into a concrete (exchange, agg) pair via
        the shard-count-keyed tuner (olap/autotune.decide_sharded). Pure in
        the graph + device kind, so the resolution is deterministic; the
        decision is recorded for run_info["autotune"]."""
        if self.exchange_requested != "auto" or self._autotune_record:
            return
        from janusgraph_tpu.olap import autotune
        from janusgraph_tpu.parallel import halo

        sc = self._sharded(undirected)
        src, dst, _w = halo.edges_from_sharded(sc)
        widths = halo.pair_widths(
            src, dst, self.num_shards, sc.shard_size
        )
        stats = autotune.GraphStats.from_csr(self.csr, undirected=undirected)
        decision = autotune.decide_sharded(
            stats, self._device_kind(), self.num_shards, widths,
            measured=getattr(self, "_measured_prior", None),
        )
        self.exchange = decision.exchange
        self.agg = decision.agg
        self._autotune_record = decision.as_dict()

    def _fetch(self, arr) -> np.ndarray:
        """Host copy of a mesh-sharded array. On a MULTI-PROCESS mesh each
        controller holds only its addressable shards (np.asarray raises on
        the rest), so gather across processes first — every host returns
        the identical global array (the SparkGraphComputer result-collect
        analogue)."""
        if self.jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True)
            )
        return np.asarray(arr)

    def _sharded(self, undirected: bool) -> ShardedCSR:
        sc = self._sharded_cache.get(undirected)
        if sc is None:
            sc = ShardedCSR(self.csr, self.num_shards, undirected)
            self._sharded_cache[undirected] = sc
        return sc

    #: distinct EdgeChannel views kept device-resident at once (LRU)
    CHANNEL_CACHE_SIZE = 8

    def _channel_view(self, program: VertexProgram, name: str):
        """(ShardedCSR, graph-args) for one named EdgeChannel, cached per
        channel VALUE — generic names (s0, s1, ...) recur across programs on
        a reused executor and must not alias each other's edge views.
        LRU-bounded: compiled sharded supersteps take the arrays as
        ARGUMENTS (not closures), so eviction actually frees them."""
        from janusgraph_tpu.olap.csr import channel_edges

        channel = program.edge_channels[name]
        hit = self._channel_views.get(channel)
        if hit is not None:
            self._channel_views.move_to_end(channel)
            return hit
        edges = channel_edges(self.csr, channel)
        sc = ShardedCSR(self.csr, self.num_shards, False, edges=edges)
        gargs = self._graph_args(sc, ("ch", channel), cache={})
        self._channel_views[channel] = (sc, gargs)
        while len(self._channel_views) > self.CHANNEL_CACHE_SIZE:
            evicted, _ = self._channel_views.popitem(last=False)
            # compiled supersteps close over the evicted ShardedCSR (static
            # shapes/metadata), pinning its O(E) host arrays — prune them
            # (their key layout is ("step", cache_key, op, exchange, agg,
            # ch_val))
            self._compiled = {
                k: v for k, v in self._compiled.items()
                if not (len(k) >= 6 and k[5] == evicted)
            }
        return sc, gargs

    def _dev(self, sc: ShardedCSR, view_key, name: str, cache=None):
        """Device-put a ShardedCSR array once, sharded over the mesh axis —
        re-uploading the static CSR blocks each superstep would dominate.
        view_key identifies the edge view (undirected flag or channel);
        `cache` overrides the executor-lifetime device cache (channel views
        use a private dict so LRU eviction frees their arrays)."""
        store = self._device_cache if cache is None else cache
        key = (view_key, name)
        arr = store.get(key)
        if arr is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self.mesh, P(self.axis))
            host = getattr(sc, name)
            if name in ("ell_buckets", "bell_buckets"):
                self._h2d_bytes += sum(
                    a.nbytes for b in host for a in b
                    if a is not None and hasattr(a, "nbytes")
                )
                arr = tuple(
                    tuple(
                        self.jax.device_put(a, sharding)
                        if a is not None else None
                        for a in bucket
                    )
                    for bucket in host
                )
            else:
                self._h2d_bytes += host.nbytes
                arr = self.jax.device_put(host, sharding)
            store[key] = arr
        return arr

    def _graph_args(self, sc: ShardedCSR, view_key, cache=None) -> Dict[str, object]:
        """The static per-shard graph arrays the configured body needs."""
        g = {
            "out_degree": self._dev(sc, view_key, "out_degree", cache),
            "active": self._dev(sc, view_key, "active", cache),
            "in_degree": self._dev(sc, view_key, "in_degree", cache),
        }
        if self.exchange == "blocked":
            sc.ensure_blocked_plan()
            if self.agg == "ell":
                sc.ensure_blocked_ell()
                g["bell_buckets"] = self._dev(
                    sc, view_key, "bell_buckets", cache
                )
                g["bell_unpermute"] = self._dev(
                    sc, view_key, "bell_unpermute", cache
                )
                g["bell_recv_idx"] = self._dev(
                    sc, view_key, "bell_recv_idx", cache
                )
                return g
            g["blk_src"] = self._dev(sc, view_key, "blk_src_loc", cache)
            g["blk_seg"] = self._dev(sc, view_key, "blk_seg", cache)
            g["blk_valid"] = self._dev(sc, view_key, "blk_valid", cache)
            if sc.has_weight:
                g["blk_w"] = self._dev(sc, view_key, "blk_weight", cache)
            g["recv_dst"] = self._dev(sc, view_key, "recv_dst", cache)
            return g
        if self.exchange == "a2a":
            sc.ensure_exchange_plan()
            g["send_idx"] = self._dev(sc, view_key, "send_idx", cache)
        if self.exchange == "ring":
            sc.ensure_ring()
            g["ring_src"] = self._dev(sc, view_key, "ring_src_loc", cache)
            g["ring_dst"] = self._dev(sc, view_key, "ring_dst_loc", cache)
            g["ring_valid"] = self._dev(sc, view_key, "ring_valid", cache)
            g["ring_weight"] = self._dev(sc, view_key, "ring_weight", cache)
            return g
        if self.agg == "ell":
            sc.ensure_ell()
            g["ell_buckets"] = self._dev(sc, view_key, "ell_buckets", cache)
            g["ell_unpermute"] = self._dev(sc, view_key, "ell_unpermute", cache)
        else:
            g["dst_loc"] = self._dev(sc, view_key, "in_dst_loc", cache)
            g["valid"] = self._dev(sc, view_key, "in_valid", cache)
            g["weight"] = self._dev(sc, view_key, "in_weight", cache)
            g["src_idx"] = (
                self._dev(sc, view_key, "in_src_tab", cache)
                if self.exchange == "a2a"
                else self._dev(sc, view_key, "in_src_glob", cache)
            )
        return g

    def _shard_body(self, program: VertexProgram, op: str, sc: ShardedCSR):
        """The per-shard superstep body (traced inside shard_map)."""
        import jax
        import jax.numpy as jnp

        axis = self.axis
        S = self.num_shards
        Np = sc.shard_size
        identity = Combiner.IDENTITY[op]
        exchange, agg = self.exchange, self.agg
        B = sc.boundary_width if exchange == "a2a" else 0
        Hc = sc.halo_cap if exchange == "blocked" else 0

        def seg_reduce_n(data, seg, n):
            if op == Combiner.SUM:
                return jax.ops.segment_sum(data, seg, num_segments=n)
            if op == Combiner.MIN:
                return jax.ops.segment_min(data, seg, num_segments=n)
            return jax.ops.segment_max(data, seg, num_segments=n)

        def seg_reduce(data, seg):
            return seg_reduce_n(data, seg, Np)

        def reduce_cols(m, axis_):
            if op == Combiner.SUM:
                return m.sum(axis=axis_)
            if op == Combiner.MIN:
                return m.min(axis=axis_)
            return m.max(axis=axis_)

        if exchange == "ring":
            sc.ensure_ring()
            Eo = sc.ring_block
        else:
            Eo = 0

        def ring_aggregate(g, outgoing):
            """S-step ring: rotate outgoing blocks with ppermute; step t
            reduces exactly the pre-partitioned edge block of the owner now
            passing by (dynamic-slice into the owner-major ring plan), so
            total edge work per superstep is ~Em + padding, not S*Em. The
            ring-attention streaming pattern: peak comm buffer is ONE Np
            block, not the S*B bucket table."""
            my = jax.lax.axis_index(axis)
            tail_shape = tuple(outgoing.shape[1:])
            acc0 = jnp.full((Np,) + tail_shape, identity, outgoing.dtype)
            perm = [(i, (i + 1) % S) for i in range(S)]

            def fold_owner(acc, block, owner):
                start = owner * Eo
                src = jax.lax.dynamic_slice(g["ring_src"], (start,), (Eo,))
                dst = jax.lax.dynamic_slice(g["ring_dst"], (start,), (Eo,))
                valid = jax.lax.dynamic_slice(g["ring_valid"], (start,), (Eo,))
                weight = jax.lax.dynamic_slice(g["ring_weight"], (start,), (Eo,))
                msgs = apply_edge_transform(
                    # ones-materialized pad weights must NOT transform on a
                    # weightless view — None matches every other executor
                    jnp, block[src], weight if sc.has_weight else None,
                    program.edge_transform, program.edge_transform_cols,
                )
                mask = valid[:, None] if msgs.ndim == 2 else valid
                msgs = jnp.where(mask > 0, msgs, identity)
                part = seg_reduce(msgs, dst)
                if op == Combiner.SUM:
                    return acc + part
                if op == Combiner.MIN:
                    return jnp.minimum(acc, part)
                return jnp.maximum(acc, part)

            # own block folds before any hop, so only S-1 ppermutes fire —
            # the final rotation (returning blocks home) would be dead comm
            acc0 = fold_owner(acc0, outgoing, my)

            def fold(carry, step_i):
                acc, block = carry
                block = jax.lax.ppermute(block, axis, perm)
                acc = fold_owner(acc, block, (my - step_i) % S)
                return (acc, block), None

            (acc, _), _ = jax.lax.scan(
                fold, (acc0, outgoing), jnp.arange(1, S, dtype=jnp.int32)
            )
            return acc

        def body(state, step, memory_in, g):
            offset = jax.lax.axis_index(axis) * Np
            view = _ShardView(
                sc.real_n, Np, offset, g["out_degree"], g["active"],
                g.get("in_degree"),
            )
            outgoing = program.message(state, step, view, jnp)
            tail = tuple(outgoing.shape[1:])

            if exchange == "ring":
                agg_v = ring_aggregate(g, outgoing)
                return _apply_and_reduce(state, agg_v, step, memory_in, view)

            if exchange == "blocked":
                # propagation blocking: per-edge messages bin by destination
                # shard and combiner-merge LOCALLY (local destinations
                # [0, Np) + outgoing bins [Np, Np+S*Hc)); the pow2-tiered
                # merged bins swap in ONE all_to_all and the receiver only
                # combines S*Hc merged values — no message-table concat, no
                # per-remote-edge work on the receiver. agg='ell' runs the
                # fused merge as packed gather + adjacent-pair trees over
                # the shard's own Np-row block; agg='segment' as one fused
                # scatter reduction.
                from janusgraph_tpu.olap.kernels import (
                    flat_take,
                    fp_fence,
                    tree_reduce,
                )

                pad = jnp.full((1,) + tail, identity, dtype=outgoing.dtype)
                if agg == "ell":
                    out_ext = jnp.concatenate([outgoing, pad], axis=0)
                    parts = []
                    for bucket, n_slots in zip(
                        g["bell_buckets"], sc.bell_meta
                    ):
                        idx, wm, va = bucket[0], bucket[1], bucket[2]
                        m = flat_take(jnp, out_ext, idx)
                        if wm is not None:
                            m = apply_edge_transform(
                                jnp, m, wm,
                                program.edge_transform,
                                program.edge_transform_cols,
                            )
                            va_ = va.reshape(
                                va.shape + (1,) * (m.ndim - 2)
                            )
                            m = jnp.where(va_ > 0, m, identity)
                            m = fp_fence(jnp, m)
                        r = tree_reduce(jnp, m, op)
                        if n_slots is not None:
                            r = seg_reduce_n(
                                r, bucket[3], n_slots + 1
                            )[:n_slots]
                        parts.append(r)
                    stacked = jnp.concatenate(parts + [pad], axis=0)
                    btab = stacked[g["bell_unpermute"]]
                    local_part = btab[:Np]
                    bins = btab[Np:].reshape((S, Hc) + tail)
                    recv = jax.lax.all_to_all(
                        bins, axis, split_axis=0, concat_axis=0
                    )
                    rtab = jnp.concatenate(
                        [recv.reshape((S * Hc,) + tail), local_part, pad],
                        axis=0,
                    )
                    m = flat_take(jnp, rtab, g["bell_recv_idx"])
                    agg_v = tree_reduce(jnp, m, op)
                    return _apply_and_reduce(
                        state, agg_v, step, memory_in, view
                    )
                msgs = outgoing[g["blk_src"]]
                msgs = apply_edge_transform(
                    jnp, msgs, g["blk_w"] if sc.has_weight else None,
                    program.edge_transform, program.edge_transform_cols,
                )
                valid = g["blk_valid"]
                vmask = valid.reshape((-1,) + (1,) * (msgs.ndim - 1))
                msgs = jnp.where(vmask > 0, msgs, identity)
                # the weighted product would otherwise contract into the
                # scatter-add as an FMA, breaking bitwise identity with
                # the numpy replay oracle (halo.replay_superstep)
                msgs = fp_fence(jnp, msgs)
                seg_out = seg_reduce_n(msgs, g["blk_seg"], Np + S * Hc + 1)
                local_part = seg_out[:Np]
                bins = seg_out[Np : Np + S * Hc].reshape((S, Hc) + tail)
                recv = jax.lax.all_to_all(
                    bins, axis, split_axis=0, concat_axis=0
                )
                remote = seg_reduce_n(
                    recv.reshape((S * Hc,) + tail), g["recv_dst"], Np + 1
                )[:Np]
                if op == Combiner.SUM:
                    agg_v = local_part + remote
                elif op == Combiner.MIN:
                    agg_v = jnp.minimum(local_part, remote)
                else:
                    agg_v = jnp.maximum(local_part, remote)
                return _apply_and_reduce(state, agg_v, step, memory_in, view)

            # ---- exchange: build the message table this shard reads from
            if exchange == "a2a":
                # boundary buckets only: gather the values each peer needs,
                # swap buckets with one all_to_all over ICI
                sends = outgoing[g["send_idx"]]            # (S, B, ...)
                recv = jax.lax.all_to_all(
                    sends, axis, split_axis=0, concat_axis=0
                )
                tab = jnp.concatenate(
                    [outgoing, recv.reshape((S * B,) + tail)], axis=0
                )
            else:
                tab = jax.lax.all_gather(outgoing, axis, axis=0, tiled=True)

            # ---- local aggregation by destination
            if agg == "ell":
                pad = jnp.full((1,) + tail, identity, dtype=outgoing.dtype)
                tab_ext = jnp.concatenate([tab, pad], axis=0)
                parts = []
                from janusgraph_tpu.olap.kernels import flat_take

                for bucket, n_slots in zip(g["ell_buckets"], sc.ell_meta):
                    idx, wm, va = bucket[0], bucket[1], bucket[2]
                    m = flat_take(jnp, tab_ext, idx)       # (rows, c[, k])
                    if wm is not None:
                        # weighted pack: transform, then re-assert the
                        # identity on padded slots (see kernels.py)
                        va_ = va[:, :, None] if m.ndim == 3 else va
                        m = apply_edge_transform(
                            jnp, m, wm,
                            program.edge_transform,
                            program.edge_transform_cols,
                        )
                        m = jnp.where(va_ > 0, m, identity)
                    r = reduce_cols(m, 1)
                    if n_slots is not None:
                        # fold supernode row partials (rows-sized reduce);
                        # padded rows land in the dead slot and are dropped
                        r = seg_reduce_n(r, bucket[3], n_slots + 1)[:n_slots]
                    parts.append(r)
                stacked = jnp.concatenate(parts, axis=0)
                agg_v = stacked[g["ell_unpermute"]]
            else:
                msgs = tab[g["src_idx"]]
                weight, valid = g["weight"], g["valid"]
                msgs = apply_edge_transform(
                    jnp, msgs, weight if sc.has_weight else None,
                    program.edge_transform, program.edge_transform_cols,
                )
                vmask = valid[:, None] if msgs.ndim == 2 else valid
                msgs = jnp.where(vmask > 0, msgs, identity)
                agg_v = seg_reduce(msgs, g["dst_loc"])

            return _apply_and_reduce(state, agg_v, step, memory_in, view)

        def _apply_and_reduce(state, agg_v, step, memory_in, view):
            new_state, metrics = program.apply(
                state, agg_v, step, memory_in, view, jnp
            )
            self._metric_ops[(program.cache_key(), op)] = {
                k: mop for k, (mop, _v) in metrics.items()
            }
            # barrier: global aggregator reduction over the mesh
            reduced = {}
            for k, (mop, v) in metrics.items():
                if mop == Combiner.SUM:
                    reduced[k] = jax.lax.psum(v, axis)
                elif mop == Combiner.MIN:
                    reduced[k] = jax.lax.pmin(v, axis)
                else:
                    reduced[k] = jax.lax.pmax(v, axis)
            return new_state, reduced

        return body

    def _specs(self):
        from jax.sharding import PartitionSpec as P

        return P(self.axis), P()

    def _superstep_fn(
        self, program: VertexProgram, op: str, sc: ShardedCSR, channel: str = None
    ):
        ch_val = program.edge_channels[channel] if channel is not None else None
        key = ("step", program.cache_key(), op, self.exchange, self.agg, ch_val)
        if key in self._compiled:
            return self._compiled[key]
        self._new_execs += 1

        import jax
        from janusgraph_tpu.parallel.compat import shard_map

        body = self._shard_body(program, op, sc)
        sharded_spec, rep = self._specs()
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                sharded_spec,  # state (leading dim sharded)
                rep,           # step
                rep,           # memory_in
                sharded_spec,  # graph arrays pytree (prefix: shard dim 0)
            ),
            out_specs=(sharded_spec, rep),
            check_vma=False,
        )
        fn = jax.jit(fn)
        self._compiled[key] = fn
        return fn

    def _fused_fn(self, program: VertexProgram, op: str, sc: ShardedCSR):
        """A span of the BSP run as ONE dispatch: lax.while_loop inside
        shard_map, collectives (boundary all_to_all exchange + psum barrier)
        in the loop body, `terminate_device` on the replicated aggregators as
        the on-device stop condition. steps/limit flow as traced scalars so
        one executable serves the full run and checkpoint-bounded chunks. See
        TPUExecutor._fused_fn."""
        key = ("fused", program.cache_key(), op, self.exchange, self.agg)
        if key in self._compiled:
            return self._compiled[key]
        self._new_execs += 1

        import jax
        import jax.numpy as jnp
        from janusgraph_tpu.parallel.compat import shard_map

        body = self._shard_body(program, op, sc)

        def run_span(state, mem, steps_done0, limit, g):
            def cond(carry):
                _s, m, steps_done = carry
                # terminate() is consulted AFTER each superstep, never
                # before the first (at steps_done == 0 the aggregators are
                # identity-seeded placeholders) — mirrors TPUExecutor
                return jnp.logical_and(
                    steps_done < limit,
                    jnp.logical_or(
                        steps_done == 0,
                        jnp.logical_not(
                            program.terminate_device(m, steps_done, jnp)
                        ),
                    ),
                )

            def loop(carry):
                s, m, steps_done = carry
                s2, m2 = body(s, steps_done, m, g)
                return (s2, m2, steps_done + 1)

            return jax.lax.while_loop(cond, loop, (state, mem, steps_done0))

        sharded_spec, rep = self._specs()
        fn = shard_map(
            run_span,
            mesh=self.mesh,
            in_specs=(sharded_spec, rep, rep, rep, sharded_spec),
            out_specs=(sharded_spec, rep, rep),
            check_vma=False,
        )
        fn = jax.jit(fn)
        self._compiled[key] = fn
        return fn

    def _frontier_eligible(self, program: VertexProgram, mode: str) -> bool:
        """Mirror of TPUExecutor._frontier_eligible on the mesh: the
        ShortestPath family dispatches to per-shard frontier compaction
        (parallel/sharded_frontier.py) unless numeric guards say no."""
        from janusgraph_tpu.olap.programs.connected_components import (
            ConnectedComponentsProgram,
        )
        from janusgraph_tpu.olap.programs.shortest_path import (
            ShortestPathProgram,
        )
        from janusgraph_tpu.olap.tpu_executor import TPUExecutor
        from janusgraph_tpu.parallel.sharded_frontier import (
            ShardedFrontierEngine,
        )

        if type(program) not in (
            ShortestPathProgram, ConnectedComponentsProgram
        ):
            return False
        if self.csr.num_edges >= ShardedFrontierEngine.MAX_EDGES:
            return False
        # float32-exact vertex-index encodings cover the PADDED index space
        padded_n = self._sharded(program.undirected).padded_n
        if type(program) is ShortestPathProgram:
            return not (program.track_paths and padded_n >= (1 << 24))
        # ConnectedComponents: labels are float32 padded indices
        return padded_n < (1 << 24) and (
            mode == "always"
            or self.csr.num_edges >= TPUExecutor.FRONTIER_CC_MIN_EDGES
        )

    def _run_frontier(
        self, program: VertexProgram, fault_hook=None
    ) -> Dict[str, np.ndarray]:
        from janusgraph_tpu.olap.programs.connected_components import (
            ConnectedComponentsProgram,
        )
        from janusgraph_tpu.parallel.sharded_frontier import (
            ShardedFrontierEngine,
        )

        if getattr(self, "_frontier_engine", None) is None:
            self._frontier_engine = ShardedFrontierEngine(self)
        t0 = time.perf_counter()
        if type(program) is ConnectedComponentsProgram:
            out = self._frontier_engine.run_cc(program, fault_hook=fault_hook)
        else:
            out = self._frontier_engine.run(program, fault_hook=fault_hook)
        trace = self._frontier_engine.last_trace
        self.last_run_info = {
            "path": "frontier",
            "supersteps": len(trace),
            "wall_s": round(time.perf_counter() - t0, 4),
            "tiers": trace,
        }
        return out

    # ------------------------------------------------- fault/checkpoint glue
    def _bind_hook(self, fault_hook):
        """Normalize a fault hook to hook(step) -> straggler events. Mesh-
        aware hooks (FaultPlan.sharded_hook) take (step, num_shards) and
        return straggler records; single-arg hooks (FaultPlan.olap_hook,
        test lambdas) are called as-is."""
        if fault_hook is None:
            return None
        try:
            params = [
                p for p in inspect.signature(fault_hook).parameters.values()
                if p.kind in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    inspect.Parameter.VAR_POSITIONAL,
                )
            ]
            mesh_aware = len(params) >= 2 or any(
                p.kind is inspect.Parameter.VAR_POSITIONAL for p in params
            )
        except (TypeError, ValueError):
            mesh_aware = False
        S = self.num_shards
        if mesh_aware:
            return lambda step: fault_hook(step, S)
        return fault_hook

    def _consult(self, hook, step: int) -> None:
        """One superstep-boundary fault consultation; straggler skew
        records accumulate for the run report."""
        if hook is None:
            return
        events = hook(step)
        if events:
            self._straggler_events.extend(events)

    def _save_ck(
        self, checkpoint_path, shard_dir, state_host, mem_values, steps,
        records=None,
    ) -> None:
        ck0 = time.perf_counter()
        if shard_dir:
            from janusgraph_tpu.olap.sharded_checkpoint import (
                save_sharded_checkpoint,
            )

            save_sharded_checkpoint(
                shard_dir, state_host, mem_values, steps, self.num_shards
            )
        else:
            from janusgraph_tpu.olap.checkpoint import save_checkpoint

            save_checkpoint(checkpoint_path, state_host, mem_values, steps)
        self._ck_saves += 1
        if records:
            # timeline marker (observability/timeline.py): the save's
            # wall, stamped on the superstep that paid it
            records[-1]["checkpoint_ms"] = round(
                (time.perf_counter() - ck0) * 1000.0, 3
            )

    def _load_ck(self, checkpoint_path, shard_dir):
        if shard_dir:
            from janusgraph_tpu.olap.sharded_checkpoint import (
                load_sharded_checkpoint,
            )

            ck = load_sharded_checkpoint(shard_dir)
        elif checkpoint_path:
            from janusgraph_tpu.olap.checkpoint import load_checkpoint

            ck = load_checkpoint(checkpoint_path)
        else:
            ck = None
        if ck is not None and self._resume_t_catch is not None:
            # catch -> state restored: the recovery latency an operator
            # actually pays (the replay itself is forward progress)
            self._resume_ms += (
                time.perf_counter() - self._resume_t_catch
            ) * 1000.0
            self._resume_t_catch = None
        return ck

    def _device_kind(self) -> str:
        try:
            return str(np.asarray(self.mesh.devices).flat[0].platform)
        except Exception:
            return "cpu"

    # -------------------------------------------------- per-shard reporting
    #: skip the measured-wall probe past this many edges — the probe runs
    #: every shard's aggregation once on the host, which must stay a
    #: negligible fraction of the run it prices
    MEASURE_MAX_EDGES = 20_000_000

    def _measured_walls(self, sc: ShardedCSR) -> Optional[List[float]]:
        """MEASURED per-shard superstep walls (ms): the SPMD barrier hides
        per-shard time inside one dispatch, so run each shard's real
        aggregation workload shard-by-shard on the host and time it
        (min of 3 repeats). Cached per edge view — the probe prices the
        layout, which does not change between runs."""
        if not self.shard_measure or self.csr.num_edges > self.MEASURE_MAX_EDGES:
            return None
        cached = getattr(sc, "_measured_walls", None)
        if cached is not None:
            return cached
        if self.exchange == "blocked":
            from janusgraph_tpu.parallel import halo

            sc.ensure_blocked_plan()
            walls = halo.measure_shard_walls(sc.blocked_plan)
        else:
            # dst-partitioned probe: gather + scatter over each shard's
            # real in-edge slice (the eager paths' per-shard work shape)
            S, Np, Em = sc.num_shards, sc.shard_size, sc.edges_per_shard
            offsets = sc._offsets
            ramp = np.arange(sc.padded_n, dtype=np.float32) % 97 + 1.0
            walls = []
            for s in range(S):
                k = max(1, int(offsets[s + 1] - offsets[s]))
                src = sc.in_src_glob[s * Em : s * Em + k]
                dst = sc.in_dst_loc[s * Em : s * Em + k]
                w = sc.in_weight[s * Em : s * Em + k]
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    msgs = ramp[src] * w
                    acc = np.zeros(Np, dtype=np.float32)
                    np.add.at(acc, dst, msgs)
                    best = min(best, time.perf_counter() - t0)
                walls.append(best * 1000.0)
        sc._measured_walls = walls
        return walls

    def _shard_report(self, sc: ShardedCSR, records: List[dict]) -> None:
        """Per-shard ledger + roofline, straggler detection, and the skew
        gauge. One SPMD dispatch runs every shard in lockstep (the barrier
        hides individual shard walls), so per-shard time comes from the
        MEASURED host probe (_measured_walls — each shard's real
        aggregation workload timed shard-by-shard, cost_source="measured")
        when available, else from the shard plan's edge counts
        (cost_source="plan"); the superstep wall is attributed by relative
        per-shard cost, and injected straggler skew (the chaos plan's
        records) adds on top. Host code only; nothing here is traced."""
        from janusgraph_tpu.observability import (
            flight_recorder,
            profiler,
            registry,
            tracer,
        )

        S = sc.num_shards
        Np = sc.shard_size
        offsets = getattr(sc, "_offsets", None)
        edges = (
            [int(offsets[s + 1] - offsets[s]) for s in range(S)]
            if offsets is not None else [0] * S
        )
        n_steps = max(1, len(records))
        mean_wall = (
            sum(r.get("wall_ms", 0.0) for r in records) / n_steps
            if records else 0.0
        )
        peaks = profiler.device_peaks(self._device_kind())
        strag: Dict[int, float] = {}
        for ev in self._straggler_events:
            strag[ev["shard"]] = strag.get(ev["shard"], 0.0) + float(ev["ms"])
        costs = []
        for s in range(S):
            verts = max(0, min(sc.real_n - s * Np, Np))
            costs.append((
                verts,
                profiler.estimate_superstep_cost(
                    max(verts, 1), max(edges[s], 1)
                ),
            ))
        max_edges = max(max(edges), 1)
        measured = self._measured_walls(sc)
        cost_source = "measured" if measured else "plan"
        max_meas = max(measured) if measured else 0.0
        per = []
        t_by_shard = []
        for s in range(S):
            verts, cost = costs[s]
            # the barrier wall is set by the busiest shard: scale the
            # measured mean superstep wall by each shard's measured share
            # of the slowest shard's probe wall (or, without the probe,
            # by relative modeled edge load)
            if measured and max_meas > 0:
                share = measured[s] / max_meas
            else:
                share = edges[s] / max_edges
            modeled_ms = mean_wall * share
            strag_ms = strag.get(s, 0.0)
            t_by_shard.append(modeled_ms + strag_ms / n_steps)
            point = profiler.roofline_point(
                cost["flops"], cost["bytes_accessed"],
                modeled_ms if modeled_ms > 0 else 0.0, peaks,
            )
            per.append({
                "shard": s,
                "vertices": verts,
                "edges": edges[s],
                "modeled_ms": round(modeled_ms, 4),
                "measured_ms": (
                    round(measured[s], 4) if measured else None
                ),
                "cost_source": cost_source,
                "straggler_ms": round(strag_ms, 3),
                "ledger": {
                    "cells_read": edges[s],
                    "bytes_read": int(cost["bytes_accessed"]),
                    "bytes_written": 8 * verts,
                },
                "roofline": {
                    "flops": cost["flops"],
                    "bytes_accessed": cost["bytes_accessed"],
                    "cost_source": cost["cost_source"],
                    **point,
                },
            })
        mean_t = sum(t_by_shard) / S if S else 0.0
        skew = (max(t_by_shard) / mean_t) if mean_t > 0 else 1.0
        slowest = int(np.argmax(t_by_shard)) if t_by_shard else 0
        block = {
            "count": S,
            "skew": round(skew, 4),
            "cost_source": cost_source,
            "slowest_shard": slowest,
            "straggler_events": len(self._straggler_events),
            "straggler_ms_total": round(sum(strag.values()), 3),
            "boundary_elems": getattr(sc, "comm_a2a_elems", None),
            "per_shard": per,
        }
        self.last_run_info["shards"] = block
        self.last_run_info["exchange"] = self._exchange_info(sc)
        if self._autotune_record is not None:
            self.last_run_info["autotune"] = self._autotune_record
        registry.gauge("olap.shard.skew").set(skew)
        # PR 8 dashboards read the skew gauge: publish whether it is now
        # measured-wall-derived (1) or still plan-derived (0)
        registry.gauge("olap.shard.skew.measured").set(
            1.0 if cost_source == "measured" else 0.0
        )
        registry.counter("olap.sharded.runs").inc()
        # ambient resource ledger: the run's plan-derived totals (one
        # message gather per edge + state write-back per vertex)
        profiler.accrue(
            cells_read=sum(edges),
            bytes_read=sum(int(c["bytes_accessed"]) for _v, c in costs),
            bytes_written=8 * sc.real_n,
        )
        # slowest-shard exemplar span: the flamegraph/trace hook for "which
        # shard sets the barrier pace" — plus a flight event when skew is
        # pathological or a straggler was injected
        with tracer.span(
            "olap.shard.slowest",
            shard=slowest,
            modeled_ms=round(t_by_shard[slowest], 4) if t_by_shard else 0.0,
            skew=round(skew, 4),
        ):
            pass
        if self._straggler_events or skew >= SKEW_FLIGHT_THRESHOLD:
            flight_recorder.record(
                "shard_skew",
                skew=round(skew, 4),
                slowest_shard=slowest,
                straggler_events=len(self._straggler_events),
                injected_ms=round(sum(strag.values()), 3),
            )

    def _persist_measured(
        self, sc: ShardedCSR, checkpoint_path, shard_dir, records
    ) -> None:
        """Measured-record persistence for the mesh: keyed by SHARD COUNT
        inside the shared .autotune.json, so an 8-chip run calibrates the
        next 8-chip run without clobbering the single-device record
        (olap/autotune.save_measured v2)."""
        if not records:
            return
        path = (
            os.path.join(shard_dir, "autotune.json") if shard_dir
            else (checkpoint_path + ".autotune.json" if checkpoint_path
                  else None)
        )
        if not path:
            return
        from janusgraph_tpu.olap import autotune

        prior = autotune.load_measured(path, shard_count=self.num_shards)
        mean_wall = sum(r.get("wall_ms", 0.0) for r in records) / max(
            1, len(records)
        )
        autotune.save_measured(
            path,
            {
                "strategy": f"sharded-{self.exchange}-{self.agg}",
                "pad_ratio": round(sc.padded_n / max(1, sc.real_n), 4),
                "superstep_ms": round(mean_wall, 3),
                "roofline_by_tier": None,
                # per-shard-layout fields (v2 records are keyed by shard
                # count; these let the next lifetime's decide_sharded
                # prefer the measured exchange layout)
                "exchange": self.exchange,
                "agg": self.agg,
                "halo_cap": getattr(sc, "halo_cap", None),
            },
            shard_count=self.num_shards,
        )
        self.last_run_info["autotune_persist"] = {
            "path": path,
            "shard_count": self.num_shards,
            "calibrated": prior is not None,
        }

    def run(
        self,
        program: VertexProgram,
        sync_every: int = 1,
        fused: bool = None,
        checkpoint_path: str = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        frontier: str = "auto",
        fault_hook=None,
        resume_attempts: int = 3,
        shard_checkpoint_dir: str = None,
    ) -> Dict[str, np.ndarray]:
        """Run to termination. `fused` (default auto): constant-combiner
        programs with terminate_device compile spans of the run into one
        dispatch (while_loop inside shard_map), optionally chunked for
        checkpointing; otherwise a host loop with `sync_every`-amortized
        aggregator fetches (see TPUExecutor.run). `frontier`:
        "auto"/"always"/"off" — the ShortestPath family runs per-shard
        frontier-compacted supersteps when eligible (checkpointing rides
        the dense path: frontier runs are short).

        `shard_checkpoint_dir` — save the SHARDED checkpoint format (per-
        shard slices + atomic manifest; olap/sharded_checkpoint.py) every
        `checkpoint_every` supersteps instead of the single-file
        `checkpoint_path` format.

        `fault_hook` (e.g. FaultPlan.sharded_hook) is consulted at every
        host-visible superstep boundary — the fused path's granularity is
        one checkpoint chunk — and may raise SuperstepPreempted (incl.
        ShardPreempted / CollectiveTimeout / HaloDropped). With
        checkpointing enabled, ALL shards roll back to the last complete
        manifest (the BSP barrier's consistency cut) and replay, up to
        `resume_attempts` times; the replay recomputes the identical SPMD
        program over exact saved arrays, so the final state is bitwise-
        identical to a fault-free run. Frontier runs carry no checkpoint
        and simply restart from scratch (they are short and deterministic).
        Mesh-aware hooks also return straggler skew records, which feed the
        run's per-shard report and the `olap.shard.skew` gauge.
        """
        from janusgraph_tpu.olap.vertex_program import (
            check_weighted_transforms,
        )

        check_weighted_transforms(program, self.csr)
        if frontier not in ("auto", "off", "always"):
            raise ValueError(f"unknown frontier mode: {frontier!r}")
        if not getattr(program, "sharded_compatible", True):
            # sddmm needs both endpoints' feature rows inside one kernel;
            # the halo exchange ships only source-side data — refuse with
            # the workaround instead of silently computing garbage
            raise NotImplementedError(
                "sddmm dense programs are not supported on the sharded "
                "executor (the per-edge dot needs dst features on the "
                "source side); run executor='tpu' or message_mode="
                "'copy'/'weighted'"
            )
        if self.exchange_requested == "auto" and self._autotune_record is None:
            # a persisted measured record for THIS shard count calibrates
            # the layout decision across process lifetimes (autotune v2)
            apath = (
                os.path.join(shard_checkpoint_dir, "autotune.json")
                if shard_checkpoint_dir
                else (checkpoint_path + ".autotune.json"
                      if checkpoint_path else None)
            )
            if apath:
                from janusgraph_tpu.olap import autotune

                self._measured_prior = autotune.load_measured(
                    apath, shard_count=self.num_shards
                )
        self._resolve_exchange(program.undirected)
        from janusgraph_tpu.olap.tpu_executor import TPUExecutor

        use_frontier = False
        if frontier != "off" and TPUExecutor._frontier_family(program):
            if checkpoint_path or shard_checkpoint_dir:
                # "always" must never silently time the dense path under a
                # frontier label (mirrors TPUExecutor.run)
                if frontier == "always":
                    raise ValueError(
                        "frontier='always' cannot be combined with "
                        "checkpointing (the frontier loop does not "
                        "checkpoint) — drop checkpoint_path or use "
                        "frontier='auto'"
                    )
            elif self._frontier_eligible(program, frontier):
                use_frontier = True
            elif frontier == "always":
                raise ValueError(
                    "frontier='always' but the graph exceeds the frontier "
                    f"engine's guards (|V|={self.csr.num_vertices}, "
                    f"|E|={self.csr.num_edges}; float32 label/predecessor "
                    "exactness needs padded |V| < 2^24, int32 expansion "
                    "needs |E| < 2^30) — use frontier='auto' or 'off'"
                )
        sc = self._sharded(program.undirected)
        if fused is None:
            fused = program.fused_eligible()
        use_fused = (
            not use_frontier
            and fused
            and type(program).combiner_for is VertexProgram.combiner_for
        )

        from janusgraph_tpu.observability import tracer

        hook = self._bind_hook(fault_hook)
        self._straggler_events: List[dict] = []
        self._ck_saves = 0
        self._resume_ms = 0.0
        self._resume_t_catch = None
        self._new_execs = 0
        self._h2d_bytes = 0
        t_run = time.perf_counter()
        with tracer.span(
            "olap.run", executor="sharded", shards=self.num_shards,
            exchange=self.exchange,
        ) as sp:
            out = self._run_guarded(
                program, sc, sync_every, checkpoint_path, checkpoint_every,
                resume, frontier, hook, resume_attempts,
                shard_checkpoint_dir, use_frontier, use_fused,
            )
            self._publish_run(sp, program, out, time.perf_counter() - t_run)
            return out

    def _run_guarded(
        self, program, sc, sync_every, checkpoint_path, checkpoint_every,
        resume, frontier, hook, resume_attempts, shard_checkpoint_dir,
        use_frontier, use_fused,
    ):
        from janusgraph_tpu.exceptions import SuperstepPreempted
        from janusgraph_tpu.observability import flight_recorder, registry

        can_resume = bool(
            (shard_checkpoint_dir or checkpoint_path) and checkpoint_every
        )
        resumes = 0
        while True:
            try:
                if use_frontier:
                    out = self._run_frontier(program, fault_hook=hook)
                elif use_fused:
                    out = self._run_fused(
                        program, sc, checkpoint_path, checkpoint_every,
                        resume, hook, shard_checkpoint_dir,
                    )
                else:
                    out = self._run_host_loop(
                        program, sc, sync_every, checkpoint_path,
                        checkpoint_every, resume, hook,
                        shard_checkpoint_dir,
                    )
                break
            except SuperstepPreempted as e:
                registry.counter("olap.preemptions").inc()
                # frontier runs restart from scratch (deterministic and
                # short); dense paths need a checkpoint to roll back to
                if resumes >= resume_attempts or not (
                    use_frontier or can_resume
                ):
                    raise
                resumes += 1
                resume = True
                self._resume_t_catch = time.perf_counter()
                registry.counter("olap.resumes").inc()
                registry.counter("olap.sharded.resumes").inc()
                flight_recorder.record(
                    "olap_resume", executor="sharded", attempt=resumes,
                    program=type(program).__name__,
                    fault=type(e).__name__,
                    format="sharded" if shard_checkpoint_dir else "single",
                )
                if use_frontier:
                    # nothing to reload: the restart IS the recovery
                    self._resume_ms += (
                        time.perf_counter() - self._resume_t_catch
                    ) * 1000.0
                    self._resume_t_catch = None
        if resumes:
            self.last_run_info["resumes"] = resumes
            self.last_run_info["resume_ms"] = round(self._resume_ms, 3)
        if self._ck_saves or can_resume:
            self.last_run_info["checkpoint"] = {
                "format": "sharded" if shard_checkpoint_dir else "single",
                "saves": self._ck_saves,
                "location": shard_checkpoint_dir or checkpoint_path,
            }
        return out

    def _publish_run(self, sp, program, result, wall_s) -> None:
        """Publish the finished run in the SAME record vocabulary as
        TPUExecutor._finish_run — path/supersteps/superstep_records,
        transfer bytes, compile-cache economics, device memory, slowest-
        superstep exemplar, and the olap.* gauges — so dashboards and
        tests read one shape regardless of which executor a submit()
        routed to. Host code only."""
        from janusgraph_tpu.observability import registry, tracer

        info = self.last_run_info
        info["executor"] = "sharded"
        info["wall_s"] = round(wall_s, 4)
        info["retraces"] = self._new_execs
        info["h2d_arg_bytes"] = int(self._h2d_bytes)
        info["d2h_bytes"] = int(
            sum(np.asarray(v).nbytes for v in result.values())
        )
        sc = self._sharded(bool(getattr(program, "undirected", False)))
        pad_ratio = round(sc.padded_n / max(1, sc.real_n), 4)
        info["pad_ratio"] = pad_ratio
        info["ell_pad_ratio"] = pad_ratio
        records = info.get("superstep_records")
        if records is None:
            # frontier path: the tier trace IS the per-superstep record
            records = [
                {
                    "step": int(t.get("hop", i)),
                    "frontier": int(t.get("frontier", 0)),
                    "edges": int(t.get("edges", 0)),
                    "e_cap": int(t.get("E_cap", 0)),
                }
                for i, t in enumerate(info.get("tiers", []))
            ]
        n = sc.real_n
        for i, r in enumerate(records):
            r.setdefault("frontier", n)
            r.setdefault("pad_ratio", pad_ratio)
            r.setdefault(
                "h2d_bytes", info["h2d_arg_bytes"] if i == 0 else 0
            )
        info["superstep_records"] = records

        dispatches = max(len(records), 1)
        misses = min(self._new_execs, dispatches)
        info["compile_cache"] = {
            "hits": dispatches - misses,
            "misses": misses,
            "compiled_total": len(self._compiled),
        }
        registry.counter("olap.compile_cache.hits").inc(dispatches - misses)
        registry.counter("olap.compile_cache.misses").inc(misses)

        stats = None
        try:
            stats = np.asarray(self.mesh.devices).flat[0].memory_stats()
        except Exception:  # noqa: BLE001 - backend-dependent API
            stats = None
        if stats and "bytes_in_use" in stats:
            info["device_memory"] = {
                "source": "device",
                "bytes_in_use": int(stats["bytes_in_use"]),
            }
        else:
            info["device_memory"] = {
                "source": "host-estimate",
                "bytes_in_use": int(info["h2d_arg_bytes"])
                + int(info["d2h_bytes"]),
            }
        registry.set_gauge(
            "olap.device.bytes_in_use",
            float(info["device_memory"]["bytes_in_use"]),
        )

        slowest = None
        for r in records[:128]:
            s = tracer.record_span(
                "superstep", float(r.get("wall_ms", 0.0)),
                **{k: v for k, v in r.items() if k != "wall_ms"},
            )
            if slowest is None or s.duration_ms > slowest.duration_ms:
                slowest = s
        if slowest is not None:
            info["slowest_superstep"] = {
                "step": slowest.attrs.get("step"),
                "wall_ms": round(slowest.duration_ms, 4),
                "span_id": f"{slowest.span_id:016x}",
                "trace_id": f"{slowest.trace_id:016x}",
            }
        sp.annotate(
            path=info.get("path"),
            supersteps=info.get("supersteps"),
            wall_s=info["wall_s"],
            retraces=self._new_execs,
            ell_pad_ratio=pad_ratio,
            h2d_arg_bytes=info["h2d_arg_bytes"],
            d2h_bytes=info["d2h_bytes"],
        )
        registry.counter("olap.runs").inc()
        registry.timer("olap.run").update(int(wall_s * 1e9))
        registry.set_gauge(
            "olap.superstep.count", float(info.get("supersteps", 0) or 0)
        )
        registry.set_gauge("olap.run.wall_ms", round(wall_s * 1000.0, 3))
        registry.set_gauge(
            "olap.transfer.h2d_bytes", float(info["h2d_arg_bytes"])
        )
        registry.set_gauge(
            "olap.transfer.d2h_bytes", float(info["d2h_bytes"])
        )
        registry.record_run("olap", info)

    def _run_host_loop(
        self,
        program: VertexProgram,
        sc: ShardedCSR,
        sync_every: int,
        checkpoint_path: str,
        checkpoint_every: int,
        resume: bool,
        hook,
        shard_checkpoint_dir: str,
    ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        memory = Memory()
        state = None
        start_step = 0
        if resume and (checkpoint_path or shard_checkpoint_dir):
            ck = self._load_ck(checkpoint_path, shard_checkpoint_dir)
            if ck is not None:
                ck_state, ck_mem, start_step = ck
                fresh, _m = program.setup(_GlobalView(sc), np)
                state = {}
                for k, pad in fresh.items():
                    arr = np.asarray(pad).copy()
                    arr[: sc.real_n] = np.asarray(ck_state[k])
                    state[k] = jnp.asarray(arr)
                memory.values = {k: float(v) for k, v in ck_mem.items()}
                memory.superstep = start_step
        if state is None:
            state, init_metrics = program.setup(_GlobalView(sc), np)
            state = {k: jnp.asarray(v) for k, v in state.items()}
            memory.reduce_in(init_metrics)
            memory.superstep = 0
        device_memory = {
            k: jnp.asarray(v, dtype=jnp.float32) for k, v in memory.values.items()
        }

        gargs = self._graph_args(sc, program.undirected)
        steps_done = start_step
        records: List[dict] = []
        for step in range(start_step, program.max_iterations):
            # fault boundary: the barrier between supersteps — the one
            # point where no shard holds partial superstep state
            self._consult(hook, step)
            t_step = time.perf_counter()
            op = program.combiner_for(step)
            ch = program.channel_for(step)
            if ch is not None:
                sc_step, gargs_step = self._channel_view(program, ch)
            else:
                sc_step, gargs_step = sc, gargs
            fn = self._superstep_fn(program, op, sc_step, ch)
            state, metrics = fn(
                state,
                jnp.asarray(step, dtype=jnp.int32),
                device_memory,
                gargs_step,
            )
            device_memory = {
                k: metrics.get(k, device_memory.get(k))
                for k in set(device_memory) | set(metrics)
            }
            steps_done += 1
            last = step == program.max_iterations - 1
            records.append({
                "step": step,
                "wall_ms": round(
                    (time.perf_counter() - t_step) * 1000.0, 3
                ),
            })
            if steps_done % sync_every == 0 or last:
                host_vals = self.jax.device_get(metrics)
                memory.values = {k: float(v) for k, v in host_vals.items()}
                memory.superstep = steps_done
                if checkpoint_every and (
                    checkpoint_path or shard_checkpoint_dir
                ) and (steps_done % checkpoint_every == 0 or last):
                    self._save_ck(
                        checkpoint_path, shard_checkpoint_dir,
                        {
                            k: self._fetch(v)[: sc.real_n]
                            for k, v in state.items()
                        },
                        memory.values,
                        steps_done,
                        records=records,
                    )
                if program.terminate(memory):
                    break

        # strip padding
        self.last_run_info = {
            "path": "host-loop", "supersteps": steps_done,
            "superstep_records": records,
        }
        self._shard_report(sc, records)
        self._persist_measured(
            sc, checkpoint_path, shard_checkpoint_dir, records
        )
        return {
            k: self._fetch(v)[: sc.real_n] for k, v in state.items()
        }

    def _run_fused(
        self,
        program: VertexProgram,
        sc: ShardedCSR,
        checkpoint_path: str,
        checkpoint_every: int,
        resume: bool,
        hook=None,
        shard_checkpoint_dir: str = None,
    ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        op = program.combiner
        max_iter = program.max_iterations
        gargs = self._graph_args(sc, program.undirected)
        steps_done = 0
        state = mem = None

        if resume and (checkpoint_path or shard_checkpoint_dir):
            ck = self._load_ck(checkpoint_path, shard_checkpoint_dir)
            if ck is not None:
                ck_state, ck_mem, steps_done = ck
                # checkpoints store the real_n rows (portable across shard
                # counts); padding rows are re-derived from a fresh setup()
                fresh, _m = program.setup(_GlobalView(sc), np)
                state = {}
                for k, pad in fresh.items():
                    arr = np.asarray(pad).copy()
                    arr[: sc.real_n] = np.asarray(ck_state[k])
                    state[k] = jnp.asarray(arr)
                mem = {k: jnp.asarray(v, jnp.float32) for k, v in ck_mem.items()}

        if state is None:
            state, init_metrics = program.setup(_GlobalView(sc), np)
            state = {k: jnp.asarray(v) for k, v in state.items()}
            mem0 = {
                k: jnp.asarray(v, dtype=jnp.float32)
                for k, (_o, v) in init_metrics.items()
            }
            if max_iter == 0:
                return {
                    k: self._fetch(v)[: sc.real_n] for k, v in state.items()
                }
            # learn apply's aggregator pytree by abstract trace (records
            # each metric's monoid op, no XLA compile), seed missing keys
            # with the monoid identity, and run superstep 0 INSIDE the
            # fused executable — one compile per program instead of two
            # (mirrors TPUExecutor._run_fused)
            mkey = (program.cache_key(), op)
            if mkey not in self._metric_ops:
                step_fn = self._superstep_fn(program, op, sc)
                self.jax.eval_shape(
                    step_fn, state, jnp.asarray(0, jnp.int32), mem0, gargs
                )
            mops = self._metric_ops[mkey]
            mem = {
                k: (
                    mem0[k]
                    if k in mem0
                    else jnp.asarray(Combiner.IDENTITY[mops[k]], jnp.float32)
                )
                for k in mops
            }
            steps_done = 0

        fn = self._fused_fn(program, op, sc)
        records: List[dict] = []
        while steps_done < max_iter:
            # fault boundary: once per dispatched chunk (the while_loop
            # owns the intra-chunk superstep boundaries on device)
            self._consult(hook, steps_done)
            t_chunk = time.perf_counter()
            limit = max_iter
            if checkpoint_every:
                limit = min(steps_done + checkpoint_every, max_iter)
            state, mem, steps_dev = fn(
                state,
                mem,
                jnp.asarray(steps_done, jnp.int32),
                jnp.asarray(limit, jnp.int32),
                gargs,
            )
            new_steps = int(steps_dev)
            terminated = new_steps < limit or new_steps == steps_done
            chunk_steps = max(1, new_steps - steps_done)
            chunk_ms = (time.perf_counter() - t_chunk) * 1000.0
            for i in range(steps_done, max(new_steps, steps_done)):
                records.append({
                    "step": i,
                    "wall_ms": round(chunk_ms / chunk_steps, 3),
                })
            steps_done = max(new_steps, steps_done)
            if checkpoint_every and (checkpoint_path or shard_checkpoint_dir):
                self._save_ck(
                    checkpoint_path, shard_checkpoint_dir,
                    {
                        k: self._fetch(v)[: sc.real_n]
                        for k, v in state.items()
                    },
                    {k: float(np.asarray(v)) for k, v in mem.items()},
                    steps_done,
                    records=records,
                )
            if terminated:
                break
        self.last_run_info = {
            "path": "fused", "supersteps": steps_done,
            "superstep_records": records,
        }
        self._shard_report(sc, records)
        self._persist_measured(
            sc, checkpoint_path, shard_checkpoint_dir, records
        )
        return {k: self._fetch(v)[: sc.real_n] for k, v in state.items()}


def shard_csr(csr: CSRGraph, num_shards: int, undirected: bool = False) -> ShardedCSR:
    return ShardedCSR(csr, num_shards, undirected)
