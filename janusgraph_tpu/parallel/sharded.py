"""Multi-chip sharded BSP executor: shard_map over a device mesh.

This is the distributed-communication redesign mandated by SURVEY.md §2.4:
the reference has no NCCL/MPI — its "communication" is writing message cells
into the storage backend and re-scanning (KCVSLog for control plane). Here
the data plane is XLA collectives over ICI:

  - vertex state and in-edge CSR blocks are sharded over the mesh axis by
    contiguous vertex-index blocks (the analogue of the reference's
    partition-prefixed key ranges, IDManager.getKey:480);
  - each superstep all_gathers the per-vertex message vector (O(n) on ICI),
    gathers per-edge messages locally, and segment-reduces into the local
    shard — replacing Fulgora's pull-based reversed slice rescans
    (VertexProgramScanJob.java:114-135);
  - global aggregators reduce with psum/pmin/pmax at the superstep barrier —
    replacing FulgoraMemory's in-process sub-round barrier;
  - vertex-cut merging is subsumed at CSR-load canonicalization.

Shards are equal-sized (SPMD): vertices pad to S*Np, per-shard edge lists pad
to the max shard edge count with masked no-op entries. Programs see the same
interface as single-chip (`active` marks real vertices).

Runs identically on a real multi-chip mesh and on the CPU-device test mesh
(xla_force_host_platform_device_count) — the "multi-node without a cluster"
test technique.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from janusgraph_tpu.olap.csr import CSRGraph
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeTransform,
    Memory,
    VertexProgram,
)


class ShardedCSR:
    """Host-side sharded/padded representation, ready for device placement.

    Arrays with leading dim S*Np (vertex-sharded) or S*Em (edge-sharded):
      out_degree   (S*Np,) float32
      active       (S*Np,) float32
      in_src_glob  (S*Em,) int32  — global (padded) source vertex index
      in_dst_loc   (S*Em,) int32  — destination index local to its shard
      in_valid     (S*Em,) float32
      in_weight    (S*Em,) float32 (all ones if unweighted)
    """

    def __init__(self, csr: CSRGraph, num_shards: int, undirected: bool):
        n = csr.num_vertices
        S = num_shards
        Np = -(-max(n, 1) // S)  # ceil
        self.csr = csr
        self.num_shards = S
        self.shard_size = Np
        self.padded_n = S * Np
        self.real_n = n

        src = csr.in_src.astype(np.int64)
        dst = np.repeat(
            np.arange(n, dtype=np.int64), np.diff(csr.in_indptr)
        )
        w = (
            csr.in_edge_weight.astype(np.float32)
            if csr.in_edge_weight is not None
            else np.ones(len(src), dtype=np.float32)
        )
        if undirected:
            # symmetric closure: aggregate over both orientations in one pass
            src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
            w = np.concatenate([w, w])

        shard_of = dst // Np
        counts = np.bincount(shard_of, minlength=S)
        Em = int(counts.max()) if len(counts) else 0
        Em = max(Em, 1)
        self.edges_per_shard = Em

        in_src_glob = np.zeros(S * Em, dtype=np.int32)
        in_dst_loc = np.zeros(S * Em, dtype=np.int32)
        in_valid = np.zeros(S * Em, dtype=np.float32)
        in_weight = np.ones(S * Em, dtype=np.float32)
        order = np.argsort(shard_of, kind="stable")
        offsets = np.zeros(S + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        for s in range(S):
            sl = order[offsets[s] : offsets[s + 1]]
            k = len(sl)
            base = s * Em
            in_src_glob[base : base + k] = src[sl]
            in_dst_loc[base : base + k] = dst[sl] - s * Np
            in_valid[base : base + k] = 1.0
            in_weight[base : base + k] = w[sl]

        out_degree = np.zeros(S * Np, dtype=np.float32)
        out_degree[:n] = csr.out_degree
        active = np.zeros(S * Np, dtype=np.float32)
        active[:n] = 1.0

        self.out_degree = out_degree
        self.active = active
        self.in_src_glob = in_src_glob
        self.in_dst_loc = in_dst_loc
        self.in_valid = in_valid
        self.in_weight = in_weight


class _GlobalView:
    """Padded global view handed to program.setup (host side)."""

    def __init__(self, sharded: ShardedCSR):
        self.num_vertices = sharded.real_n
        self.local_num_vertices = sharded.padded_n
        self.global_offset = 0
        self.out_degree = sharded.out_degree
        self.active = sharded.active


class _ShardView:
    """Per-shard view inside shard_map (traced)."""

    def __init__(self, num_vertices, shard_size, offset, out_degree, active):
        self.num_vertices = num_vertices          # real global count (static)
        self.local_num_vertices = shard_size      # padded local (static)
        self.global_offset = offset               # traced scalar
        self.out_degree = out_degree
        self.active = active


_PREDUCE = {
    Combiner.SUM: "psum",
    Combiner.MIN: "pmin",
    Combiner.MAX: "pmax",
}


class ShardedExecutor:
    """BSP executor over a jax.sharding.Mesh (1-D axis 'p')."""

    def __init__(self, csr: CSRGraph, mesh=None, axis: str = "p"):
        import jax
        from jax.sharding import Mesh

        self.jax = jax
        self.axis = axis
        if mesh is None:
            devices = np.array(jax.devices())
            mesh = Mesh(devices, (axis,))
        self.mesh = mesh
        self.num_shards = mesh.devices.size
        self.csr = csr
        self._compiled: Dict[Tuple[str, bool], object] = {}
        self._sharded_cache: Dict[bool, ShardedCSR] = {}

    def _sharded(self, undirected: bool) -> ShardedCSR:
        sc = self._sharded_cache.get(undirected)
        if sc is None:
            sc = ShardedCSR(self.csr, self.num_shards, undirected)
            # place the static CSR blocks on the mesh ONCE, sharded over the
            # axis — re-uploading them each superstep would dominate runtime
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(self.mesh, P(self.axis))
            for name in (
                "out_degree", "active", "in_src_glob", "in_dst_loc",
                "in_valid", "in_weight",
            ):
                setattr(sc, name, self.jax.device_put(getattr(sc, name), sharding))
            self._sharded_cache[undirected] = sc
        return sc

    def _shard_body(self, program: VertexProgram, op: str, sc: ShardedCSR):
        """The per-shard superstep body (traced inside shard_map)."""
        import jax
        import jax.numpy as jnp

        axis = self.axis
        Np = sc.shard_size
        identity = Combiner.IDENTITY[op]

        def seg_reduce(data, seg):
            if op == Combiner.SUM:
                return jax.ops.segment_sum(data, seg, num_segments=Np)
            if op == Combiner.MIN:
                return jax.ops.segment_min(data, seg, num_segments=Np)
            return jax.ops.segment_max(data, seg, num_segments=Np)

        def body(
            state,          # pytree of (Np, ...) local arrays
            step,           # scalar
            memory_in,      # dict of replicated scalars
            out_degree,     # (Np,)
            active,         # (Np,)
            src_glob,       # (Em,)
            dst_loc,        # (Em,)
            valid,          # (Em,)
            weight,         # (Em,)
        ):
            offset = jax.lax.axis_index(axis) * Np
            view = _ShardView(sc.real_n, Np, offset, out_degree, active)
            outgoing = program.message(state, step, view, jnp)
            # exchange: every shard needs message values for its in-edge
            # sources — all_gather over ICI, then local gather
            all_msgs = jax.lax.all_gather(outgoing, axis, axis=0, tiled=True)
            msgs = all_msgs[src_glob]
            if program.edge_transform == EdgeTransform.MUL_WEIGHT:
                msgs = msgs * (weight[:, None] if msgs.ndim == 2 else weight)
            elif program.edge_transform == EdgeTransform.ADD_WEIGHT:
                msgs = msgs + (weight[:, None] if msgs.ndim == 2 else weight)
            # mask padded edge slots to the monoid identity
            vmask = valid[:, None] if msgs.ndim == 2 else valid
            msgs = jnp.where(vmask > 0, msgs, identity)
            agg = seg_reduce(msgs, dst_loc)
            new_state, metrics = program.apply(
                state, agg, step, memory_in, view, jnp
            )
            # barrier: global aggregator reduction over the mesh
            reduced = {}
            for k, (mop, v) in metrics.items():
                if mop == Combiner.SUM:
                    reduced[k] = jax.lax.psum(v, axis)
                elif mop == Combiner.MIN:
                    reduced[k] = jax.lax.pmin(v, axis)
                else:
                    reduced[k] = jax.lax.pmax(v, axis)
            return new_state, reduced

        return body

    def _specs(self):
        from jax.sharding import PartitionSpec as P

        return P(self.axis), P()

    def _superstep_fn(self, program: VertexProgram, op: str, sc: ShardedCSR):
        key = ("step", program.cache_key(), op)
        if key in self._compiled:
            return self._compiled[key]

        import jax
        from jax import shard_map

        body = self._shard_body(program, op, sc)
        sharded_spec, rep = self._specs()
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                sharded_spec,  # state (leading dim sharded)
                rep,           # step
                rep,           # memory_in
                sharded_spec,  # out_degree
                sharded_spec,  # active
                sharded_spec,  # src_glob
                sharded_spec,  # dst_loc
                sharded_spec,  # valid
                sharded_spec,  # weight
            ),
            out_specs=(sharded_spec, rep),
            check_vma=False,
        )
        fn = jax.jit(fn)
        self._compiled[key] = fn
        return fn

    def _fused_fn(self, program: VertexProgram, op: str, sc: ShardedCSR):
        """A span of the BSP run as ONE dispatch: lax.while_loop inside
        shard_map, collectives (all_gather exchange + psum barrier) in the
        loop body, `terminate_device` on the replicated aggregators as the
        on-device stop condition. steps/limit flow as traced scalars so one
        executable serves the full run and checkpoint-bounded chunks. See
        TPUExecutor._fused_fn."""
        key = ("fused", program.cache_key(), op)
        if key in self._compiled:
            return self._compiled[key]

        import jax
        import jax.numpy as jnp
        from jax import shard_map

        body = self._shard_body(program, op, sc)

        def run_span(state, mem, steps_done0, limit,
                     out_degree, active, src_glob, dst_loc, valid, weight):
            args = (out_degree, active, src_glob, dst_loc, valid, weight)

            def cond(carry):
                _s, m, steps_done = carry
                return jnp.logical_and(
                    steps_done < limit,
                    jnp.logical_not(
                        program.terminate_device(m, steps_done, jnp)
                    ),
                )

            def loop(carry):
                s, m, steps_done = carry
                s2, m2 = body(s, steps_done, m, *args)
                return (s2, m2, steps_done + 1)

            return jax.lax.while_loop(cond, loop, (state, mem, steps_done0))

        sharded_spec, rep = self._specs()
        fn = shard_map(
            run_span,
            mesh=self.mesh,
            in_specs=(
                sharded_spec, rep, rep, rep,
                sharded_spec, sharded_spec, sharded_spec,
                sharded_spec, sharded_spec, sharded_spec,
            ),
            out_specs=(sharded_spec, rep, rep),
            check_vma=False,
        )
        fn = jax.jit(fn)
        self._compiled[key] = fn
        return fn

    def run(
        self,
        program: VertexProgram,
        sync_every: int = 1,
        fused: bool = None,
        checkpoint_path: str = None,
        checkpoint_every: int = 0,
        resume: bool = False,
    ) -> Dict[str, np.ndarray]:
        """Run to termination. `fused` (default auto): constant-combiner
        programs with terminate_device compile spans of the run into one
        dispatch (while_loop inside shard_map), optionally chunked for
        checkpointing; otherwise a host loop with `sync_every`-amortized
        aggregator fetches (see TPUExecutor.run)."""
        import jax.numpy as jnp

        sc = self._sharded(program.undirected)
        if fused is None:
            fused = program.fused_eligible()
        if fused and type(program).combiner_for is VertexProgram.combiner_for:
            return self._run_fused(
                program, sc, checkpoint_path, checkpoint_every, resume
            )

        memory = Memory()
        state = None
        start_step = 0
        if resume and checkpoint_path:
            from janusgraph_tpu.olap.checkpoint import load_checkpoint

            ck = load_checkpoint(checkpoint_path)
            if ck is not None:
                ck_state, ck_mem, start_step = ck
                fresh, _m = program.setup(_GlobalView(sc), np)
                state = {}
                for k, pad in fresh.items():
                    arr = np.asarray(pad).copy()
                    arr[: sc.real_n] = np.asarray(ck_state[k])
                    state[k] = jnp.asarray(arr)
                memory.values = {k: float(v) for k, v in ck_mem.items()}
                memory.superstep = start_step
        if state is None:
            state, init_metrics = program.setup(_GlobalView(sc), np)
            state = {k: jnp.asarray(v) for k, v in state.items()}
            memory.reduce_in(init_metrics)
            memory.superstep = 0
        device_memory = {
            k: jnp.asarray(v, dtype=jnp.float32) for k, v in memory.values.items()
        }

        steps_done = start_step
        for step in range(start_step, program.max_iterations):
            op = program.combiner_for(step)
            fn = self._superstep_fn(program, op, sc)
            state, metrics = fn(
                state,
                jnp.asarray(step, dtype=jnp.int32),
                device_memory,
                sc.out_degree,
                sc.active,
                sc.in_src_glob,
                sc.in_dst_loc,
                sc.in_valid,
                sc.in_weight,
            )
            device_memory = {
                k: metrics.get(k, device_memory.get(k))
                for k in set(device_memory) | set(metrics)
            }
            steps_done += 1
            last = step == program.max_iterations - 1
            if steps_done % sync_every == 0 or last:
                host_vals = self.jax.device_get(metrics)
                memory.values = {k: float(v) for k, v in host_vals.items()}
                memory.superstep = steps_done
                if checkpoint_path and checkpoint_every and (
                    steps_done % checkpoint_every == 0 or last
                ):
                    from janusgraph_tpu.olap.checkpoint import save_checkpoint

                    save_checkpoint(
                        checkpoint_path,
                        {k: np.asarray(v)[: sc.real_n] for k, v in state.items()},
                        memory.values,
                        steps_done,
                    )
                if program.terminate(memory):
                    break

        # strip padding
        return {
            k: np.asarray(v)[: sc.real_n] for k, v in state.items()
        }

    def _run_fused(
        self,
        program: VertexProgram,
        sc: ShardedCSR,
        checkpoint_path: str,
        checkpoint_every: int,
        resume: bool,
    ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        op = program.combiner
        max_iter = program.max_iterations
        csr_args = (
            sc.out_degree, sc.active, sc.in_src_glob,
            sc.in_dst_loc, sc.in_valid, sc.in_weight,
        )
        steps_done = 0
        state = mem = None

        if resume and checkpoint_path:
            from janusgraph_tpu.olap.checkpoint import load_checkpoint

            ck = load_checkpoint(checkpoint_path)
            if ck is not None:
                ck_state, ck_mem, steps_done = ck
                # checkpoints store the real_n rows (portable across shard
                # counts); padding rows are re-derived from a fresh setup()
                fresh, _m = program.setup(_GlobalView(sc), np)
                state = {}
                for k, pad in fresh.items():
                    arr = np.asarray(pad).copy()
                    arr[: sc.real_n] = np.asarray(ck_state[k])
                    state[k] = jnp.asarray(arr)
                mem = {k: jnp.asarray(v, jnp.float32) for k, v in ck_mem.items()}

        if state is None:
            state, init_metrics = program.setup(_GlobalView(sc), np)
            state = {k: jnp.asarray(v) for k, v in state.items()}
            mem0 = {
                k: jnp.asarray(v, dtype=jnp.float32)
                for k, (_o, v) in init_metrics.items()
            }
            if max_iter == 0:
                return {
                    k: np.asarray(v)[: sc.real_n] for k, v in state.items()
                }
            step_fn = self._superstep_fn(program, op, sc)
            state, mem = step_fn(
                state, jnp.asarray(0, jnp.int32), mem0, *csr_args
            )
            steps_done = 1

        fn = self._fused_fn(program, op, sc)
        while steps_done < max_iter:
            limit = max_iter
            if checkpoint_every:
                limit = min(steps_done + checkpoint_every, max_iter)
            state, mem, steps_dev = fn(
                state,
                mem,
                jnp.asarray(steps_done, jnp.int32),
                jnp.asarray(limit, jnp.int32),
                *csr_args,
            )
            new_steps = int(steps_dev)
            terminated = new_steps < limit or new_steps == steps_done
            steps_done = max(new_steps, steps_done)
            if checkpoint_path and checkpoint_every:
                from janusgraph_tpu.olap.checkpoint import save_checkpoint

                save_checkpoint(
                    checkpoint_path,
                    {k: np.asarray(v)[: sc.real_n] for k, v in state.items()},
                    {k: np.asarray(v) for k, v in mem.items()},
                    steps_done,
                )
            if terminated:
                break
        return {k: np.asarray(v)[: sc.real_n] for k, v in state.items()}


def shard_csr(csr: CSRGraph, num_shards: int, undirected: bool = False) -> ShardedCSR:
    return ShardedCSR(csr, num_shards, undirected)
