"""jax API compat shims for the parallel (multi-chip) family.

The sharded executor family is written against the modern top-level
``jax.shard_map`` (keyword ``check_vma``); older jax releases (including
this container's 0.4.x) only ship ``jax.experimental.shard_map.shard_map``
(keyword ``check_rep``). One import-helper here resolves whichever the
runtime provides and papers over the keyword rename, so
``parallel/sharded.py``, ``parallel/sharded_frontier.py`` and
``parallel/multihost.py`` never import jax's shard_map directly — the
whole 43-test sharded/multihost tier-1 family rides this shim.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

#: memoized (implementation, source) — resolution is import-time cheap but
#: the source string is surfaced in diagnostics (multihost init, tests)
_RESOLVED: Optional[Tuple[Callable, str]] = None


def resolve_shard_map() -> Tuple[Callable, str]:
    """(shard_map implementation, dotted source path). Raises ImportError
    only when NEITHER spelling exists — an actual unsupported jax."""
    global _RESOLVED
    if _RESOLVED is not None:
        return _RESOLVED
    try:
        from jax import shard_map as impl  # jax >= 0.5 spelling

        _RESOLVED = (impl, "jax.shard_map")
    except ImportError:
        from jax.experimental.shard_map import shard_map as impl

        _RESOLVED = (impl, "jax.experimental.shard_map")
    return _RESOLVED


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Call-compatible with modern ``jax.shard_map``. On the experimental
    fallback the ``check_vma`` flag maps onto its older ``check_rep`` name
    (same semantics: verify per-output replication/varying-axis claims)."""
    impl, source = resolve_shard_map()
    if source == "jax.shard_map":
        return impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
