from janusgraph_tpu.parallel.sharded import ShardedExecutor, shard_csr  # noqa: F401
