"""Frontier-compacted supersteps on the sharded mesh executor.

Reference behavior modeled: FulgoraGraphComputer special-cases the
ShortestPath programs (FulgoraGraphComputer.java:249-253), and the
reference's storage-partition parallelism shards that work across
key ranges (IDManager.java:472-496). The single-chip TPU form of the
special case is capped frontier expansion (olap/frontier.py); this module
is its mesh form: per-shard compaction + the EXISTING boundary-bucket
all_to_all carrying only frontier messages.

Superstep anatomy (2 executables, 2 host round trips per hop — same
structure as the single-chip engine):

  plan  (one per edge view): mask the outgoing vertex values to INF off
        the frontier, swap boundary buckets with one ``lax.all_to_all``
        (fixed S*B elements — comm volume is unchanged; the win is in
        aggregation), concatenate the message table
        [own Np ++ received S*B], and count fresh slots / their edges
        (pmax for tier sizing, psum for the trace).
  step  (one per (F_cap, E_cap, mode) tier): compact fresh table slots to
        a capped index buffer, expand via the scatter+cumsum pointer
        spread over the per-shard table-slot CSC
        (ShardedCSR.ensure_frontier_plan), gather/scatter-min only the
        frontier's edges, update distances and the next-hop mask.

Per-step output is bit-identical to the dense sharded path: a
non-frontier source contributes INF (the MIN identity) to the table, so
every edge the compaction skips would have been a no-op relaxation —
the same argument as olap/frontier.py, applied per shard. The top tier
(F_cap=T, E_cap=Em) degrades to one full local edge pass: dense-
equivalent cost, nothing dropped.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from janusgraph_tpu.olap.frontier import _tier, capped_expand
from janusgraph_tpu.olap.programs.shortest_path import INF


class ShardedFrontierEngine:
    """Per-executor engine: owns the device placement of the frontier plan
    and the tier-compiled plan/step executables (cached in the executor's
    compiled-fn table)."""

    F_MIN = 1 << 10
    E_MIN = 1 << 13
    GROWTH = 4
    #: int32 telescoping-cumsum headroom (see olap/frontier.py)
    MAX_EDGES = 1 << 30

    def __init__(self, executor):
        self.ex = executor
        if getattr(executor, "_frontier_tier_growth", None):
            self.GROWTH = executor._frontier_tier_growth
        self.jax = executor.jax
        self.axis = executor.axis
        self.mesh = executor.mesh
        self.last_trace = []

    # ------------------------------------------------------------- graph args
    def _mode(self, track: bool) -> str:
        """The frontier exchange mode: 'blocked' merges remote relaxations
        sender-side (min is exactly order-insensitive, so the hop is
        bitwise-identical to the eager table) and collapses remote
        expansion to one edge per used bin; predecessor tracking needs the
        per-source identity that a merged bin discards, so track runs stay
        on the eager boundary table."""
        return (
            "blocked"
            if self.ex.exchange == "blocked" and not track
            else "a2a"
        )

    def _table_len(self, sc, mode: str) -> int:
        if mode == "blocked":
            sc.ensure_blocked_plan()
            return sc.shard_size + sc.num_shards * sc.halo_cap
        sc.ensure_exchange_plan()
        return sc.msg_table_len

    def _gargs(self, sc, view_key, weighted: bool, track: bool,
               mode: str = "a2a"):
        """Device-resident plan arrays for one edge view (reuses the
        executor's sharded device cache — the a2a send_idx / blocked bin
        maps are shared with the dense path)."""
        ex = self.ex
        if mode == "blocked":
            sc.ensure_frontier_plan_blocked()
            g = {
                "blk_src": ex._dev(sc, view_key, "blk_src_loc"),
                "blk_bin_seg": ex._dev(sc, view_key, "blk_bin_seg"),
                "blk_valid": ex._dev(sc, view_key, "blk_valid"),
                "ftr_ip": ex._dev(sc, view_key, "fblk_ip"),
                "ftr_dst": ex._dev(sc, view_key, "fblk_dst"),
                "ftr_deg": ex._dev(sc, view_key, "fblk_deg"),
            }
            if weighted:
                g["blk_w"] = ex._dev(sc, view_key, "blk_weight")
                g["ftr_w"] = ex._dev(sc, view_key, "fblk_w")
            return g
        sc.ensure_frontier_plan()
        g = {
            "send_idx": ex._dev(sc, view_key, "send_idx"),
            "ftr_ip": ex._dev(sc, view_key, "ftr_ip"),
            "ftr_dst": ex._dev(sc, view_key, "ftr_dst"),
            "ftr_deg": ex._dev(sc, view_key, "ftr_deg"),
        }
        if weighted:  # callers pass the resolved use-weights flag
            g["ftr_w"] = ex._dev(sc, view_key, "ftr_w")
        if track:
            g["ftr_src_glob"] = ex._dev(sc, view_key, "ftr_src_glob")
        return g

    # ------------------------------------------------------------------ plan
    def _plan_fn(self, sc, view_key, mode: str = "a2a", has_w: bool = False):
        """(value, mask, g) -> (tab, count_max, edge_max, count_sum,
        edge_sum): builds the frontier-masked message table (the exchange
        lives HERE, so the tier choice can follow it) and prices the
        coming expansion. mode='blocked' ships sender-merged relaxation
        bins (propagation blocking: segment-min by destination bin, ONE
        all_to_all of S*Hc merged elements) instead of the raw S*B
        boundary values."""
        key = (
            "sfrontier-plan", view_key, mode, has_w,
            self._table_len(sc, mode),
        )
        cache = self.ex._compiled
        if key in cache:
            return cache[key]
        import jax
        import jax.numpy as jnp
        from janusgraph_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        S = sc.num_shards

        if mode == "blocked":
            Hc = sc.halo_cap

            def plan_body(value, mask, g):
                outgoing = jnp.where(mask, value, INF)
                msgs = outgoing[g["blk_src"]]
                if has_w:
                    # fold the edge weight into the merged candidate: the
                    # receiver's bin edge carries weight 0
                    msgs = msgs + g["blk_w"]
                msgs = jnp.where(g["blk_valid"] > 0, msgs, INF)
                bins = jax.ops.segment_min(
                    msgs, g["blk_bin_seg"], num_segments=S * Hc + 1
                )[: S * Hc]
                recv = jax.lax.all_to_all(
                    bins.reshape(S, Hc), axis,
                    split_axis=0, concat_axis=0,
                )
                tab = jnp.concatenate([outgoing, recv.reshape(S * Hc)])
                fresh = tab < INF
                zero = jnp.zeros((), jnp.int32)
                count = jnp.sum(fresh.astype(jnp.int32))
                edges = jnp.sum(jnp.where(fresh, g["ftr_deg"], zero))
                return (
                    tab,
                    jax.lax.pmax(count, axis),
                    jax.lax.pmax(edges, axis),
                    jax.lax.psum(count, axis),
                    jax.lax.psum(edges, axis),
                )
        else:
            B = sc.boundary_width

            def plan_body(value, mask, g):
                outgoing = jnp.where(mask, value, INF)
                sends = outgoing[g["send_idx"]]              # (S, B)
                recv = jax.lax.all_to_all(
                    sends, axis, split_axis=0, concat_axis=0
                )
                tab = jnp.concatenate([outgoing, recv.reshape(S * B)])
                fresh = tab < INF
                zero = jnp.zeros((), jnp.int32)
                count = jnp.sum(fresh.astype(jnp.int32))
                edges = jnp.sum(jnp.where(fresh, g["ftr_deg"], zero))
                return (
                    tab,
                    jax.lax.pmax(count, axis),
                    jax.lax.pmax(edges, axis),
                    jax.lax.psum(count, axis),
                    jax.lax.psum(edges, axis),
                )

        sh, rep = P(self.axis), P()
        fn = jax.jit(shard_map(
            plan_body,
            mesh=self.mesh,
            in_specs=(sh, sh, sh),
            out_specs=(sh, rep, rep, rep, rep),
            check_vma=False,
        ))
        self.ex._new_execs = getattr(self.ex, "_new_execs", 0) + 1
        cache[key] = fn
        return fn

    # ------------------------------------------------------------------ step
    def _step_fn(
        self, sc, view_key, F_cap, E_cap, weighted, track, has_w, T=None,
    ):
        key = (
            "sfrontier-step", view_key, F_cap, E_cap, weighted, track,
            has_w, T,
        )
        cache = self.ex._compiled
        if key in cache:
            return cache[key]
        import jax
        import jax.numpy as jnp
        from janusgraph_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        Np = sc.shard_size
        if T is None:
            T = sc.msg_table_len

        def step_body(value, pred, tab, t, g):
            fresh = tab < INF
            idx = jnp.nonzero(fresh, size=F_cap, fill_value=T)[0]
            idx = idx.astype(jnp.int32)
            own, pos, nbr, valid = capped_expand(
                jnp, idx, g["ftr_ip"], g["ftr_dst"], E_cap, Np
            )
            safe = jnp.clip(idx, 0, T - 1)
            if weighted:
                msg = tab[safe][own]
                if has_w:
                    msg = msg + g["ftr_w"][pos]
            elif track:
                msg = g["ftr_src_glob"][safe].astype(jnp.float32)[own]
            else:
                msg = jnp.zeros((E_cap,), jnp.float32)
            msg = jnp.where(valid, msg, INF)
            tmp = jnp.full((Np + 1,), INF, jnp.float32).at[nbr].min(msg)
            tmp = tmp[:Np]
            if weighted:
                new = jnp.minimum(value, tmp)
                changed = new < value
            else:
                changed = (value >= INF) & (tmp < INF)
                new = jnp.where(changed, t + 1.0, value)
                if track:
                    pred = jnp.where(changed, tmp, pred)
            n_changed = jax.lax.psum(
                jnp.sum(changed.astype(jnp.int32)), axis
            )
            return new, pred, changed, n_changed

        sh, rep = P(self.axis), P()
        if track:
            body = step_body
            in_specs = (sh, sh, sh, rep, sh)
        else:
            def body(value, tab, t, g):
                v, _p, m, c = step_body(value, None, tab, t, g)
                return v, m, c

            in_specs = (sh, sh, rep, sh)
        out_specs = (sh, sh, sh, rep) if track else (sh, sh, rep)
        fn = jax.jit(shard_map(
            body,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        ))
        self.ex._new_execs = getattr(self.ex, "_new_execs", 0) + 1
        cache[key] = fn
        return fn

    # ------------------------------------------------------------- host loop
    def _hop_loop(
        self, sc, view_key, value, pred, mask, weighted, track,
        max_iterations, use_weights=None, fault_hook=None,
    ):
        """`use_weights` decouples value-message semantics (weighted=True)
        from edge-weight application: CC propagates labels as value
        messages but must never add a weight (see run_cc).

        `fault_hook` is consulted once per hop (the host-visible
        boundary). Frontier runs carry no checkpoint: a raised
        SuperstepPreempted propagates to ShardedExecutor.run, whose
        auto-resume RESTARTS the frontier run from scratch — hops are
        short and the loop is deterministic, so the restart reproduces
        the identical result."""
        import jax.numpy as jnp

        jax = self.jax
        has_w = (
            weighted if use_weights is None else use_weights
        ) and sc.has_weight
        mode = self._mode(track)
        if mode == "blocked":
            sc.ensure_frontier_plan_blocked()
            T = self._table_len(sc, mode)
            Em = sc.fblk_edges
            exchange_elems = sc.num_shards * sc.halo_cap
        else:
            sc.ensure_frontier_plan()  # also builds the exchange plan
            T = sc.msg_table_len
            Em = sc.edges_per_shard
            exchange_elems = sc.num_shards * sc.boundary_width
        g = self._gargs(sc, view_key, has_w, track, mode)
        plan = self._plan_fn(sc, view_key, mode, has_w)
        trace = []
        for t in range(max_iterations):
            if fault_hook is not None:
                fault_hook(t)
            tab, cmax, emax, csum, esum = plan(value, mask, g)
            cmax, emax, csum, esum = (
                int(x) for x in jax.device_get((cmax, emax, csum, esum))
            )
            if csum == 0:
                break
            f_cap = _tier(max(cmax, 1), self.F_MIN, T, self.GROWTH)
            e_cap = _tier(max(emax, 1), self.E_MIN, Em, self.GROWTH)
            trace.append({
                "hop": t, "frontier": csum, "edges": esum,
                "shard_max_frontier": cmax, "shard_max_edges": emax,
                "F_cap": f_cap, "E_cap": e_cap,
                "exchange": mode, "exchange_elems": exchange_elems,
            })
            fn = self._step_fn(
                sc, view_key, f_cap, e_cap, weighted, track, has_w, T
            )
            tf = jnp.asarray(t, jnp.float32)
            if track:
                value, pred, mask, _c = fn(value, pred, tab, tf, g)
            else:
                value, mask, _c = fn(value, tab, tf, g)
        self.last_trace = trace
        return value, pred

    def _device_put_sharded(self, arr):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return self.jax.device_put(
            arr, NamedSharding(self.mesh, P(self.axis))
        )

    # -------------------------------------------------------------- entry
    def run(self, program, fault_hook=None) -> Dict[str, np.ndarray]:
        """SSSP/BFS (ShortestPathProgram) through the sharded hop loop."""
        sc = self.ex._sharded(program.undirected)
        view_key = program.undirected
        track = program.track_paths
        idx0 = np.arange(sc.padded_n, dtype=np.int64)
        value = self._device_put_sharded(
            np.where(idx0 == program.seed_index, 0.0, INF).astype(np.float32)
        )
        pred = None
        if track:
            pred = self._device_put_sharded(
                np.where(
                    idx0 == program.seed_index,
                    float(program.seed_index), -1.0,
                ).astype(np.float32)
            )
        mask = self._device_put_sharded(idx0 == program.seed_index)
        value, pred = self._hop_loop(
            sc, view_key, value, pred, mask, program.weighted, track,
            program.max_iterations, fault_hook=fault_hook,
        )
        out = {"distance": self.ex._fetch(value)[: sc.real_n]}
        if track:
            out["predecessor"] = self.ex._fetch(pred)[: sc.real_n]
        return out

    def run_cc(self, program, fault_hook=None) -> Dict[str, np.ndarray]:
        """Frontier-compacted connected components on the mesh: min-label
        propagation with a changed-vertex frontier, value-messages through
        the weighted step with NO weight arrays (a label must never absorb
        an edge weight — the same reuse as olap/frontier.py.run_cc)."""
        sc = self.ex._sharded(True)  # symmetric closure = both orientations
        labels = self._device_put_sharded(
            np.arange(sc.padded_n, dtype=np.float32)
        )
        mask = self._device_put_sharded(sc.active > 0)
        labels, _ = self._hop_loop(
            sc, True, labels, None, mask, True, False,
            program.max_iterations, use_weights=False,
            fault_hook=fault_hook,
        )
        return {"component": self.ex._fetch(labels)[: sc.real_n]}
