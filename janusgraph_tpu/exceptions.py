"""Exception hierarchy.

Mirrors the capability split of the reference's exception model
(reference: janusgraph-core .../core/JanusGraphException.java,
diskstorage/BackendException.java): backend errors distinguish *temporary*
(retriable with backoff) from *permanent* failures, which drives the retry
policy in BackendOperation-equivalent wrappers.
"""


class JanusGraphTPUError(Exception):
    """Base class for all framework errors."""


class BackendError(JanusGraphTPUError):
    """Storage backend failure."""


class TemporaryBackendError(BackendError):
    """Transient failure; the operation may be retried with backoff."""


class PermanentBackendError(BackendError):
    """Non-retriable failure."""


class TemporaryLockingError(TemporaryBackendError):
    """Lock contention; retry may succeed."""


class PermanentLockingError(PermanentBackendError):
    """Lock protocol failure (e.g. expectation check failed)."""


class CircuitOpenError(PermanentBackendError):
    """A circuit breaker is open: the call failed fast without touching the
    backend. Permanent from the retry guard's point of view (replaying an
    open circuit inside one operation is pointless spin); the breaker itself
    recovers independently via its half-open probe cycle."""


class InjectedFaultError(TemporaryBackendError):
    """A fault deliberately injected by the chaos engine (storage/faults.py).
    Temporary: the retry/recovery machinery is expected to absorb it."""


class InjectedCrashError(PermanentBackendError):
    """A chaos-engine crash point: the batch was deliberately torn mid-flight
    (some rows applied, some not). Permanent so no retry guard papers over
    it — torn-commit recovery on reopen is the path under test."""


class SuperstepPreempted(JanusGraphTPUError):
    """An OLAP superstep was preempted (injected or real). Executors with
    checkpointing enabled auto-resume from the last checkpoint."""


class IDPoolExhaustedError(JanusGraphTPUError):
    """No more IDs available in the allocation namespace."""


class InvalidElementError(JanusGraphTPUError):
    """Operation on a removed or invalid graph element."""

    def __init__(self, msg, element=None):
        super().__init__(msg)
        self.element = element


class InvalidIDError(JanusGraphTPUError):
    """Malformed or out-of-range element ID."""


class SchemaViolationError(JanusGraphTPUError):
    """Schema constraint (multiplicity, cardinality, uniqueness, type) violated."""


class ReadOnlyTransactionError(JanusGraphTPUError):
    """Mutation attempted in a read-only transaction."""


class QueryError(JanusGraphTPUError):
    """Malformed or unsupported query."""


class ConfigurationError(JanusGraphTPUError):
    """Invalid configuration."""
