"""Exception hierarchy.

Mirrors the capability split of the reference's exception model
(reference: janusgraph-core .../core/JanusGraphException.java,
diskstorage/BackendException.java): backend errors distinguish *temporary*
(retriable with backoff) from *permanent* failures, which drives the retry
policy in BackendOperation-equivalent wrappers.
"""


class JanusGraphTPUError(Exception):
    """Base class for all framework errors."""


class BackendError(JanusGraphTPUError):
    """Storage backend failure."""


class TemporaryBackendError(BackendError):
    """Transient failure; the operation may be retried with backoff."""


class PermanentBackendError(BackendError):
    """Non-retriable failure."""


class TemporaryLockingError(TemporaryBackendError):
    """Lock contention; retry may succeed."""


class PermanentLockingError(PermanentBackendError):
    """Lock protocol failure (e.g. expectation check failed)."""


class CircuitOpenError(PermanentBackendError):
    """A circuit breaker is open: the call failed fast without touching the
    backend. Permanent from the retry guard's point of view (replaying an
    open circuit inside one operation is pointless spin); the breaker itself
    recovers independently via its half-open probe cycle."""


class DeadlineExceededError(PermanentBackendError):
    """The caller's propagated deadline (core/deadline.py) is spent.
    Permanent from the retry guard's point of view: replaying an operation
    whose answer nobody will wait for is pure waste — backend_op raises
    this BEFORE touching the backend (so circuit breakers never count the
    aborted attempt), killing retry storms at the bottom of the stack."""


class ServerOverloadedError(JanusGraphTPUError):
    """The serving path refused work under overload (admission shed, or a
    brownout rung refusing OLAP submits). Carries ``retry_after_s`` when
    the refuser computed a backoff hint."""

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class InjectedFaultError(TemporaryBackendError):
    """A fault deliberately injected by the chaos engine (storage/faults.py).
    Temporary: the retry/recovery machinery is expected to absorb it."""


class InjectedCrashError(PermanentBackendError):
    """A chaos-engine crash point: the batch was deliberately torn mid-flight
    (some rows applied, some not). Permanent so no retry guard papers over
    it — torn-commit recovery on reopen is the path under test."""


class SuperstepPreempted(JanusGraphTPUError):
    """An OLAP superstep was preempted (injected or real). Executors with
    checkpointing enabled auto-resume from the last checkpoint."""


class ShardPreempted(SuperstepPreempted):
    """One shard of a multi-chip BSP run was preempted mid-superstep
    (injected or real). The superstep's collective barrier means no shard
    can commit the superstep alone, so ALL shards roll back to the last
    complete sharded-checkpoint manifest (the consistency cut) and replay."""


class CollectiveTimeout(SuperstepPreempted):
    """A cross-shard collective (the halo all_to_all / ring ppermute / psum
    barrier) timed out or failed. Recoverable exactly like a shard
    preemption: the superstep never committed on any shard, so the run
    rolls back to the last manifest and replays."""


class HaloDropped(SuperstepPreempted):
    """A destination-binned halo batch was dropped in flight. The receiving
    shard cannot aggregate a complete superstep, so the run treats it as a
    failed collective: roll back to the last manifest and replay."""


class IDPoolExhaustedError(JanusGraphTPUError):
    """No more IDs available in the allocation namespace."""


class InvalidElementError(JanusGraphTPUError):
    """Operation on a removed or invalid graph element."""

    def __init__(self, msg, element=None):
        super().__init__(msg)
        self.element = element


class InvalidIDError(JanusGraphTPUError):
    """Malformed or out-of-range element ID."""


class SchemaViolationError(JanusGraphTPUError):
    """Schema constraint (multiplicity, cardinality, uniqueness, type) violated."""


class ReadOnlyTransactionError(JanusGraphTPUError):
    """Mutation attempted in a read-only transaction."""


class QueryError(JanusGraphTPUError):
    """Malformed or unsupported query."""


class ConfigurationError(JanusGraphTPUError):
    """Invalid configuration."""
