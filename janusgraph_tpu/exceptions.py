"""Exception hierarchy.

Mirrors the capability split of the reference's exception model
(reference: janusgraph-core .../core/JanusGraphException.java,
diskstorage/BackendException.java): backend errors distinguish *temporary*
(retriable with backoff) from *permanent* failures, which drives the retry
policy in BackendOperation-equivalent wrappers.
"""


class JanusGraphTPUError(Exception):
    """Base class for all framework errors."""


class BackendError(JanusGraphTPUError):
    """Storage backend failure."""


class TemporaryBackendError(BackendError):
    """Transient failure; the operation may be retried with backoff."""


class PermanentBackendError(BackendError):
    """Non-retriable failure."""


class TemporaryLockingError(TemporaryBackendError):
    """Lock contention; retry may succeed."""


class PermanentLockingError(PermanentBackendError):
    """Lock protocol failure (e.g. expectation check failed)."""


class IDPoolExhaustedError(JanusGraphTPUError):
    """No more IDs available in the allocation namespace."""


class InvalidElementError(JanusGraphTPUError):
    """Operation on a removed or invalid graph element."""

    def __init__(self, msg, element=None):
        super().__init__(msg)
        self.element = element


class InvalidIDError(JanusGraphTPUError):
    """Malformed or out-of-range element ID."""


class SchemaViolationError(JanusGraphTPUError):
    """Schema constraint (multiplicity, cardinality, uniqueness, type) violated."""


class ReadOnlyTransactionError(JanusGraphTPUError):
    """Mutation attempted in a read-only transaction."""


class QueryError(JanusGraphTPUError):
    """Malformed or unsupported query."""


class ConfigurationError(JanusGraphTPUError):
    """Invalid configuration."""
