"""Sharded distributed store manager — the CQL-analogue backend.

Capability parity with the reference's distributed backend
(reference: janusgraph-cql CQLStoreManager.java:533 — token-partitioned
distributed store, key-consistent quorum reads, async batched mutateMany,
unordered token-range getKeys). Re-designed for this runtime: keys hash onto
N child stores ("nodes"). Children are any KCVS manager — in-process
in-memory children model a multi-node cluster in one process (the
"multi-node without a cluster" test technique, SURVEY.md §4), persistent
LocalKVStore children model a disk-backed cluster; a future RPC child makes
it a real remote cluster without touching this layer.

Failure semantics for testing: `fail_node(i)` makes a child raise
TemporaryBackendError (node down); `heal_node(i)` restores it — the
substrate for retry/failure-detection tests (BackendOperation parity).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.exceptions import PermanentBackendError, TemporaryBackendError
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def _shard_of(key: bytes, n: int) -> int:
    # stable content hash (NOT Python hash()) so placement survives restarts
    return int.from_bytes(hashlib.blake2b(key, digest_size=4).digest(), "big") % n


class ShardedKCVStore(KeyColumnValueStore):
    def __init__(self, manager: "ShardedStoreManager", name: str):
        self._manager = manager
        self._name = name
        self._children: List[KeyColumnValueStore] = [
            m.open_database(name) for m in manager.nodes
        ]

    @property
    def name(self) -> str:
        return self._name

    def _child(self, key: bytes) -> KeyColumnValueStore:
        i = _shard_of(key, len(self._children))
        self._manager._check_up(i)
        return self._children[i]

    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        return self._child(query.key).get_slice(query, txh)

    def get_slice_multi(self, keys, slice_query, txh):
        out: Dict[bytes, EntryList] = {}
        by_child: Dict[int, List[bytes]] = {}
        for k in keys:
            by_child.setdefault(_shard_of(k, len(self._children)), []).append(k)
        for i, ks in by_child.items():
            self._manager._check_up(i)
            out.update(self._children[i].get_slice_multi(ks, slice_query, txh))
        return out

    def mutate(self, key, additions, deletions, txh) -> None:
        self._child(key).mutate(key, additions, deletions, txh)

    def get_keys(self, query, txh) -> Iterator[Tuple[bytes, EntryList]]:
        if isinstance(query, KeyRangeQuery):
            raise PermanentBackendError(
                "sharded store supports unordered scans only "
                "(reference: CQL token-range getKeys)"
            )
        for i, child in enumerate(self._children):
            self._manager._check_up(i)
            yield from child.get_keys(query, txh)


class ShardedStoreManager(KeyColumnValueStoreManager):
    """Hash-partitioned composite of N child KCVS managers."""

    def __init__(
        self,
        num_nodes: int = 3,
        node_factory: Optional[Callable[[int], KeyColumnValueStoreManager]] = None,
        config: Optional[dict] = None,
    ):
        factory = node_factory or (lambda i: InMemoryStoreManager())
        self.nodes: List[KeyColumnValueStoreManager] = [
            factory(i) for i in range(num_nodes)
        ]
        self._down: set = set()
        self._stores: Dict[str, ShardedKCVStore] = {}

    # ----------------------------------------------------- failure injection
    def fail_node(self, i: int) -> None:
        self._down.add(i)

    def heal_node(self, i: int) -> None:
        self._down.discard(i)

    def _check_up(self, i: int) -> None:
        if i in self._down:
            raise TemporaryBackendError(f"node {i} unavailable")

    # ----------------------------------------------------------------- SPI
    @property
    def features(self) -> StoreFeatures:
        return StoreFeatures(
            unordered_scan=True,
            multi_query=True,
            batch_mutation=True,
            key_consistent=True,
            distributed=True,
            persists=any(m.features.persists for m in self.nodes),
            # a composite over network clients crosses the trust boundary
            # wherever any node does (drives the allow-pickle=auto guard)
            network_attached=any(
                m.features.network_attached for m in self.nodes
            ),
        )

    @property
    def name(self) -> str:
        return f"sharded({len(self.nodes)}x{type(self.nodes[0]).__name__})"

    @property
    def ledger_self_accounting(self) -> bool:
        """A composite of remote clients accounts cells at the wire; only
        when EVERY node does is BackendTransaction counting redundant."""
        return all(
            getattr(m, "ledger_self_accounting", False) for m in self.nodes
        )

    def open_database(self, name: str) -> ShardedKCVStore:
        if name not in self._stores:
            self._stores[name] = ShardedKCVStore(self, name)
        return self._stores[name]

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return StoreTransaction(config)

    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        # group by child node, delegate one batched call each (the analogue
        # of CQL's per-node async batch futures, CQLStoreManager.java:446-510)
        per_node: Dict[int, Dict[str, Dict[bytes, KCVMutation]]] = {}
        for store_name, rows in mutations.items():
            for key, mut in rows.items():
                i = _shard_of(key, len(self.nodes))
                per_node.setdefault(i, {}).setdefault(store_name, {})[key] = mut
        for i, node_muts in per_node.items():
            self._check_up(i)
            self.nodes[i].mutate_many(node_muts, txh)

    def get_local_key_partition(self):
        return None

    def close(self) -> None:
        for m in self.nodes:
            m.close()

    def clear_storage(self) -> None:
        for m in self.nodes:
            m.clear_storage()

    def exists(self) -> bool:
        return any(m.exists() for m in self.nodes)
