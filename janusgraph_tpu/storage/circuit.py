"""Circuit breaker for networked backend clients.

The retry guard (storage/backend_op.py) absorbs *transient* flake; a
breaker handles the other regime — a backend that is DOWN. Without one,
every caller burns its full retry budget against a dead endpoint (the
thundering-retry problem the reference inherits from BackendOperation's
unconditional replay loop). With one, the first caller pays the probes and
everyone else fails fast until the backend proves healthy again.

Classic three-state machine:

  CLOSED     normal operation; `failure_threshold` CONSECUTIVE temporary
             failures trip it open
  OPEN       every call raises CircuitOpenError immediately (no network
             touch) until `reset_timeout_s` elapses
  HALF_OPEN  up to `half_open_probes` concurrent calls go through as
             probes; one success closes the breaker, one failure re-opens
             it (fresh timeout)

Failure accounting: only ``TemporaryBackendError`` counts — a
``PermanentBackendError`` means the backend *responded* (an application
error, not an availability signal) and resets the consecutive-failure
count. ``CircuitOpenError`` subclasses ``PermanentBackendError`` so the
retry guard propagates it immediately instead of spinning on an open
circuit.

Observability: per-breaker state gauge ``breaker.<name>.state``
(0 closed / 1 half-open / 2 open), trip counter ``breaker.<name>.trips``,
and fail-fast counter ``breaker.<name>.rejected`` — all surfaced by
``GET /healthz`` (ok/degraded).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, TypeVar

from janusgraph_tpu.exceptions import (
    CircuitOpenError,
    PermanentBackendError,
    TemporaryBackendError,
)

T = TypeVar("T")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: gauge encoding, stable across the exposition surface
STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        reset_timeout_s: float = 1.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_probes = half_open_probes
        self._clock = clock
        self._state = CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probes_in_flight = 0
        self._lock = threading.Lock()
        self._published: str = ""
        self._publish(CLOSED)

    # -------------------------------------------------------------- telemetry
    def _publish(self, state: str) -> None:
        from janusgraph_tpu.observability import (
            flight_recorder,
            get_logger,
            registry,
        )

        # graphlint: disable=JG110 -- breaker names are one-per-protocol (store/index remote managers): a fixed, tiny set
        registry.set_gauge(
            f"breaker.{self.name}.state", STATE_VALUES[state]
        )
        prev, self._published = self._published, state
        if prev and prev != state:
            # every state transition is a flight-recorder event: the
            # reconstructable timeline of a failover, not just a gauge
            flight_recorder.record(
                "breaker", name=self.name, from_state=prev, to_state=state,
            )
            get_logger("storage.circuit").warning(
                "breaker-transition",
                breaker=self.name, from_state=prev, to_state=state,
            )

    def _trip(self) -> None:
        from janusgraph_tpu.observability import registry

        self._state = OPEN
        self._open_until = self._clock() + self.reset_timeout_s
        self._failures = 0
        self._probes_in_flight = 0
        # graphlint: disable=JG110 -- breaker names are one-per-protocol: a fixed, tiny set
        registry.counter(f"breaker.{self.name}.trips").inc()
        self._publish(OPEN)

    @property
    def state(self) -> str:
        with self._lock:
            # surface the would-be transition so callers polling state see
            # half-open as soon as the window elapses
            if self._state == OPEN and self._clock() >= self._open_until:
                return HALF_OPEN
            return self._state

    # -------------------------------------------------------------- protocol
    def _before_attempt(self) -> bool:
        """Admit or reject one attempt; returns True when the attempt is a
        half-open probe (must be accounted on completion)."""
        from janusgraph_tpu.observability import registry

        with self._lock:
            if self._state == OPEN:
                if self._clock() < self._open_until:
                    # graphlint: disable=JG110 -- breaker names are one-per-protocol: a fixed, tiny set
                    registry.counter(f"breaker.{self.name}.rejected").inc()
                    raise CircuitOpenError(
                        f"circuit {self.name} is open (fail-fast; retry "
                        f"window {self.reset_timeout_s}s)"
                    )
                self._state = HALF_OPEN
                self._probes_in_flight = 0
                self._publish(HALF_OPEN)
            if self._state == HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    # graphlint: disable=JG110 -- breaker names are one-per-protocol: a fixed, tiny set
                    registry.counter(f"breaker.{self.name}.rejected").inc()
                    raise CircuitOpenError(
                        f"circuit {self.name} is half-open and its probe "
                        "slots are taken (fail-fast)"
                    )
                self._probes_in_flight += 1
                return True
            return False

    def _on_success(self, probe: bool) -> None:
        with self._lock:
            self._failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probes_in_flight = 0
                self._publish(CLOSED)
            elif probe:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)

    def _on_failure(self, probe: bool) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._trip()
                return
            if probe:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()

    def call(self, fn: Callable[[], T]) -> T:
        """Run one backend attempt through the breaker."""
        probe = self._before_attempt()
        try:
            result = fn()
        except TemporaryBackendError:
            self._on_failure(probe)
            raise
        except PermanentBackendError:
            # the backend answered: availability-wise that is a success
            self._on_success(probe)
            raise
        self._on_success(probe)
        return result
