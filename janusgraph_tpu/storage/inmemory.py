"""In-memory KCVS backend — the default host store and test fake.

Capability parity with the reference's inmemory backend
(reference: janusgraph-inmemory .../inmemory/InMemoryStoreManager.java:200,
InMemoryKeyColumnValueStore.java:444, copy-on-write page buffers
MultiPageEntryBuffer.java:406): ordered key scans, snapshot reads, no
native locking/transactions.

Design differences from the reference (TPU-first, not a port): rows are
copy-on-write *immutable tuples* of parallel (columns, values) lists —
a mutation builds a fresh row and swaps one reference, so readers get
consistent snapshots without locks (single-swap atomicity under the GIL,
mirroring the reference's volatile page-list swap). The OLAP bulk loader
reads whole rows at once and vectorizes decoding with numpy, so there is
no per-page structure to maintain.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.storage.kcvs import (
    Entry,
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)


class _Row:
    """Immutable snapshot of one row: parallel sorted columns/values."""

    __slots__ = ("columns", "values")

    def __init__(self, columns: List[bytes], values: List[bytes]):
        self.columns = columns
        self.values = values

    def slice(self, q: SliceQuery) -> EntryList:
        cols = self.columns
        lo = bisect.bisect_left(cols, q.start)
        hi = len(cols) if q.end is None else bisect.bisect_left(cols, q.end)
        if q.limit is not None and hi - lo > q.limit:
            hi = lo + q.limit
        vals = self.values
        return [(cols[i], vals[i]) for i in range(lo, hi)]

    def mutated(self, additions: EntryList, deletions: Sequence[bytes]) -> "_Row":
        """Return a new row with the mutation applied (additions override
        deletions of the same column, matching reference semantics).
        Single O(n+m) two-way merge — bulk loads write thousands of columns
        per call."""
        added = {c: v for c, v in additions}
        deleted = set(deletions) - set(added)
        cols: List[bytes] = []
        vals: List[bytes] = []
        old_cols, old_vals = self.columns, self.values
        add_cols = sorted(added)
        i = j = 0
        n, m = len(old_cols), len(add_cols)
        while i < n or j < m:
            if j >= m or (i < n and old_cols[i] < add_cols[j]):
                c = old_cols[i]
                if c not in deleted and c not in added:
                    cols.append(c)
                    vals.append(old_vals[i])
                i += 1
            else:
                c = add_cols[j]
                cols.append(c)
                vals.append(added[c])
                j += 1
                if i < n and old_cols[i] == c:
                    i += 1
        return _Row(cols, vals)

    def is_empty(self) -> bool:
        return not self.columns


_EMPTY_ROW = _Row([], [])


class InMemoryKeyColumnValueStore(KeyColumnValueStore):
    def __init__(self, name: str):
        self._name = name
        self._rows: Dict[bytes, _Row] = {}
        self._write_lock = threading.Lock()
        # cell-TTL side table: (key, column) -> expire_ns. Populated only by
        # 3-tuple additions (column, value, expire_ns) — the reference
        # delegates per-cell TTL to backends advertising it (cassandra cell
        # TTL; StoreFeatures.cell_ttl); this store is such a backend.
        self._expiry: Dict[Tuple[bytes, bytes], int] = {}
        # per-row count of TTL'd cells: limited slices only widen their
        # range for rows that actually hold expiring cells
        self._expiry_rows: Dict[bytes, int] = {}

    @property
    def name(self) -> str:
        return self._name

    def _filter_expired(self, key: bytes, entries: EntryList) -> EntryList:
        if not self._expiry_rows.get(key):
            return entries
        import time

        now = time.time_ns()
        out = []
        for e in entries:
            exp = self._expiry.get((key, e[0]))
            if exp is not None and exp <= now:
                continue
            out.append(e)
        return out

    def _drop_expiry(self, key: bytes, col: bytes) -> None:
        if self._expiry.pop((key, col), None) is not None:
            n = self._expiry_rows.get(key, 0) - 1
            if n > 0:
                self._expiry_rows[key] = n
            else:
                self._expiry_rows.pop(key, None)

    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        row = self._rows.get(query.key)
        if row is None:
            return []
        sq = query.slice
        if sq.limit is not None and self._expiry_rows.get(query.key):
            # filter BEFORE limiting: expired cells must not occupy the
            # limit window (native cell-TTL backends count live cells only)
            live = self._filter_expired(query.key, row.slice(
                SliceQuery(sq.start, sq.end)
            ))
            return live[: sq.limit]
        return self._filter_expired(query.key, row.slice(sq))

    def mutate(
        self,
        key: bytes,
        additions: EntryList,
        deletions: Sequence[bytes],
        txh: StoreTransaction,
    ) -> None:
        with self._write_lock:
            plain = []
            added_cols = set()
            for e in additions:
                if len(e) >= 3 and e[2]:
                    if (key, e[0]) not in self._expiry:
                        self._expiry_rows[key] = (
                            self._expiry_rows.get(key, 0) + 1
                        )
                    self._expiry[(key, e[0])] = e[2]
                else:
                    self._drop_expiry(key, e[0])
                plain.append((e[0], e[1]))
                added_cols.add(e[0])
            for col in deletions:
                # additions override same-column deletions (_Row.mutated
                # contract) — their freshly-recorded expiry must survive too
                if col not in added_cols:
                    self._drop_expiry(key, col)
            row = self._rows.get(key, _EMPTY_ROW)
            new_row = row.mutated(plain, deletions)
            if new_row.is_empty():
                self._rows.pop(key, None)
            else:
                self._rows[key] = new_row

    def get_keys(
        self, query, txh: StoreTransaction
    ) -> Iterator[Tuple[bytes, EntryList]]:
        if isinstance(query, KeyRangeQuery):
            sq = query.slice
            keys = sorted(
                k for k in self._rows if query.key_start <= k < query.key_end
            )
        else:
            sq = query
            keys = sorted(self._rows)
        for k in keys:
            row = self._rows.get(k)
            if row is None:
                continue
            entries = self._filter_expired(k, row.slice(sq))
            if entries:
                yield k, entries

    def purge_expired(self) -> int:
        """Eagerly reclaim expired cells (reads only FILTER them — without
        purging, short-TTL churn grows _rows/_expiry without bound; same
        contract as TTLKCVStore.purge_expired). Returns cells purged."""
        import time

        now = time.time_ns()
        with self._write_lock:
            dead = [
                (k, c) for (k, c), exp in self._expiry.items() if exp <= now
            ]
            by_key: Dict[bytes, List[bytes]] = {}
            for k, c in dead:
                by_key.setdefault(k, []).append(c)
                self._drop_expiry(k, c)
            for k, cols in by_key.items():
                row = self._rows.get(k)
                if row is None:
                    continue
                new_row = row.mutated([], cols)
                if new_row.is_empty():
                    self._rows.pop(k, None)
                else:
                    self._rows[k] = new_row
        return len(dead)

    # -- introspection used by the OLAP bulk loader ------------------------
    def row_count(self) -> int:
        return len(self._rows)

    def clear(self) -> None:
        with self._write_lock:
            self._rows.clear()
            self._expiry.clear()
            self._expiry_rows.clear()


class InMemoryStoreManager(KeyColumnValueStoreManager):
    """Heap-backed store manager; ordered scans, no locking, no tx."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self._stores: Dict[str, InMemoryKeyColumnValueStore] = {}
        self._lock = threading.Lock()
        self._features = StoreFeatures(
            ordered_scan=True,
            unordered_scan=True,
            multi_query=True,
            batch_mutation=True,
            key_consistent=True,
            persists=False,
            cell_ttl=True,
        )

    @property
    def features(self) -> StoreFeatures:
        return self._features

    def open_database(self, name: str) -> InMemoryKeyColumnValueStore:
        with self._lock:
            store = self._stores.get(name)
            if store is None:
                store = InMemoryKeyColumnValueStore(name)
                self._stores[name] = store
            return store

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return StoreTransaction(config)

    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        for store_name, rows in mutations.items():
            store = self.open_database(store_name)
            for key, m in rows.items():
                if not m.is_empty():
                    store.mutate(key, m.additions, m.deletions, txh)

    def close(self) -> None:
        pass

    def clear_storage(self) -> None:
        with self._lock:
            for s in self._stores.values():
                s.clear()
            self._stores.clear()

    def exists(self) -> bool:
        return bool(self._stores)
