"""Persistent local ordered-KV engine — the BerkeleyJE-analogue backend.

The reference ships a local persistent backend (janusgraph-berkeleyje:
BerkeleyJEStoreManager/BerkeleyJEKeyValueStore — an ordered KV store with
durable writes, adapted to KCVS). This is its TPU-framework counterpart,
built as a log-structured engine instead of a B-tree:

  - memtable: dict + lazily-sorted key index (bisect range scans)
  - durability: append-only WAL per directory, length-framed CRC32 records
    (PUT/DEL/COMMIT); replayed on open; commit() fsyncs
  - compaction: `compact()` writes a point-in-time snapshot file and
    truncates the WAL; open loads snapshot then replays the tail

Used through OrderedKVAdapterManager (kvstore.py) it is a full persistent
KCVS backend: `open_local_kcvs(directory)`.
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from janusgraph_tpu.exceptions import PermanentBackendError
from janusgraph_tpu.storage.kcvs import StoreFeatures, StoreTransaction
from janusgraph_tpu.storage.kvstore import (
    OrderedKeyValueStore,
    OrderedKeyValueStoreManager,
    OrderedKVAdapterManager,
)

_OP_PUT = 1
_OP_DEL = 2
_OP_COMMIT = 3

_HDR = struct.Struct(">BIII")  # op, store_len, key_len, val_len  (+crc32 u32)


def _frame(op: int, store: bytes, key: bytes, val: bytes) -> bytes:
    body = _HDR.pack(op, len(store), len(key), len(val)) + store + key + val
    return struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body


class _Memtable:
    """Sorted map with lazy key index. Thread-safe: writes and scan-snapshot
    creation take the lock; scans iterate over a point-in-time snapshot, so
    concurrent OLAP scans and OLTP writes never see a mutating dict."""

    def __init__(self):
        self.data: Dict[bytes, bytes] = {}
        self._sorted: Optional[List[bytes]] = None
        self._lock = threading.RLock()

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self.data:
                self._sorted = None
            self.data[key] = value

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self.data.get(key)

    def delete(self, key: bytes) -> None:
        with self._lock:
            if self.data.pop(key, None) is not None:
                self._sorted = None

    def sorted_keys(self) -> List[bytes]:
        with self._lock:
            if self._sorted is None:
                self._sorted = sorted(self.data)
            return self._sorted

    def scan(self, start: bytes, end: Optional[bytes]) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            keys = self.sorted_keys()
            lo = bisect.bisect_left(keys, start)
            hi = len(keys) if end is None else bisect.bisect_left(keys, end)
            snapshot = [
                (keys[i], self.data[keys[i]])
                for i in range(lo, hi)
                if keys[i] in self.data
            ]
        return iter(snapshot)


class LocalKVStore(OrderedKeyValueStore):
    def __init__(self, manager: "LocalKVStoreManager", name: str):
        self._manager = manager
        self._name = name
        self.mem = _Memtable()

    @property
    def name(self) -> str:
        return self._name

    def get(self, key: bytes, txh: StoreTransaction) -> Optional[bytes]:
        return self.mem.get(key)

    def insert(self, key: bytes, value: bytes, txh: StoreTransaction) -> None:
        self._manager._log(_OP_PUT, self._name, key, value)
        self.mem.put(key, value)

    def delete(self, key: bytes, txh: StoreTransaction) -> None:
        self._manager._log(_OP_DEL, self._name, key, b"")
        self.mem.delete(key)

    def scan(
        self, start: bytes, end: Optional[bytes], txh: StoreTransaction
    ) -> Iterator[Tuple[bytes, bytes]]:
        return self.mem.scan(start, end)


class _LocalTx(StoreTransaction):
    def __init__(self, manager: "LocalKVStoreManager", config=None):
        super().__init__(config)
        self._manager = manager

    def commit(self) -> None:
        self._manager._commit_mark()

    def rollback(self) -> None:
        # writes are already durable in the WAL; rollback is not supported
        # at this layer (matching autocommit-style local stores); the graph
        # layer's WAL/recovery handles logical rollback
        pass


class LocalKVStoreManager(OrderedKeyValueStoreManager):
    WAL_FILE = "store.wal"
    SNAP_FILE = "store.snapshot"

    def __init__(self, directory: str, fsync: bool = True):
        self.directory = directory
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._stores: Dict[str, LocalKVStore] = {}
        self._wal = None
        self._wal_lock = threading.Lock()
        self._recover()
        # 4MB userspace buffer: bulk loads write millions of WAL frames;
        # commit() still flushes (+fsync) so durability semantics are unchanged
        self._wal = open(self._path(self.WAL_FILE), "ab", buffering=4 << 20)

    # ------------------------------------------------------------ durability
    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def _log(self, op: int, store: str, key: bytes, val: bytes) -> None:
        if self._wal is None:  # during recovery replay
            return
        with self._wal_lock:
            self._wal.write(_frame(op, store.encode(), key, val))

    def _commit_mark(self) -> None:
        if self._wal is None:
            return
        with self._wal_lock:
            self._wal.write(_frame(_OP_COMMIT, b"", b"", b""))
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())

    def _recover(self) -> None:
        snap = self._path(self.SNAP_FILE)
        if os.path.exists(snap):
            self._replay_file(snap)
        wal = self._path(self.WAL_FILE)
        if os.path.exists(wal):
            self._replay_file(wal)

    def _replay_file(self, path: str) -> None:
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        n = len(data)
        while pos + 4 + _HDR.size <= n:
            (crc,) = struct.unpack_from(">I", data, pos)
            op, sl, kl, vl = _HDR.unpack_from(data, pos + 4)
            end = pos + 4 + _HDR.size + sl + kl + vl
            if end > n:
                break  # torn tail record
            body = data[pos + 4 : end]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                break  # corrupt tail: stop replay (prefix is consistent)
            off = _HDR.size
            store = body[off : off + sl].decode()
            key = body[off + sl : off + sl + kl]
            val = body[off + sl + kl : off + sl + kl + vl]
            if op == _OP_PUT:
                self.open_database(store).mem.put(key, val)
            elif op == _OP_DEL:
                self.open_database(store).mem.delete(key)
            pos = end

    def compact(self) -> None:
        """Write a snapshot of all stores and truncate the WAL."""
        tmp = self._path(self.SNAP_FILE + ".tmp")
        with open(tmp, "wb") as f:
            for name, store in self._stores.items():
                for k, v in store.mem.scan(b"", None):
                    f.write(_frame(_OP_PUT, name.encode(), k, v))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(self.SNAP_FILE))
        self._wal.close()
        self._wal = open(self._path(self.WAL_FILE), "wb", buffering=4 << 20)

    # ----------------------------------------------------------------- SPI
    @property
    def features(self) -> StoreFeatures:
        return StoreFeatures(
            ordered_scan=True,
            multi_query=False,
            batch_mutation=True,
            persists=True,
            key_consistent=True,
        )

    def open_database(self, name: str) -> LocalKVStore:
        if name not in self._stores:
            self._stores[name] = LocalKVStore(self, name)
        return self._stores[name]

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return _LocalTx(self, config)

    def close(self) -> None:
        if self._wal is not None:
            self._commit_mark()
            self._wal.close()
            self._wal = None

    def clear_storage(self) -> None:
        # reset memtables IN PLACE: adapters (OrderedKVAdapterManager) hold
        # references to these LocalKVStore objects, so replacing the dict
        # would orphan them and a later compact() would miss their data
        for store in self._stores.values():
            store.mem = _Memtable()
        if self._wal is not None:
            self._wal.close()
        for f in (self.WAL_FILE, self.SNAP_FILE):
            p = self._path(f)
            if os.path.exists(p):
                os.unlink(p)
        self._wal = open(self._path(self.WAL_FILE), "ab", buffering=4 << 20)

    def exists(self) -> bool:
        return os.path.exists(self._path(self.WAL_FILE)) or os.path.exists(
            self._path(self.SNAP_FILE)
        )


def open_local_kcvs(directory: str, fsync: bool = True) -> OrderedKVAdapterManager:
    """A persistent local KCVS backend (BerkeleyJE-analogue)."""
    return OrderedKVAdapterManager(LocalKVStoreManager(directory, fsync=fsync))
