"""Cluster-unique ID block allocation over the KCVS itself.

Capability parity with the reference's consistent-key ID authority
(reference: diskstorage/idmanagement/ConsistentKeyIDAuthority.java:206-320 —
claim-then-verify block allocation needing only key-consistent reads, no
CAS; graphdb/database/idassigner/StandardIDPool.java:301 — double-buffered
block prefetch).

Protocol per (namespace, partition):
  1. read the current frontier (largest claimed block end),
  2. propose the next block and write a claim cell
     column = [block_end:8 BE][timestamp_ns:8 BE][uid:16],
  3. wait out the write-propagation window (`wait_ms`) so every rival claim
     written before our re-read is visible under key-consistent reads,
  4. re-read claims for that block end: the lexicographically-first claim
     (earliest timestamp, uid tiebreak) wins; losers delete their claim and
     retry from a fresh frontier.

The wait window is the same assumption the reference makes: with
key-consistent reads and a window exceeding the store's write latency, all
contenders observe the same rival set and agree on the winner.

Block size is a cluster-global constant (the reference's `ids.block-size`
is GLOBAL_OFFLINE): the first authority persists it in the id store and
every later authority must match or fails fast — differing sizes would make
claim columns incomparable and blocks overlap.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import uuid
from enum import Enum
from typing import Dict, Optional

from janusgraph_tpu.exceptions import (
    ConfigurationError,
    IDPoolExhaustedError,
    TemporaryBackendError,
)
from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)

ID_STORE_NAME = "janusgraph_ids"

_BLOCK_SIZE_KEY = b"\x00block_size"
_BLOCK_SIZE_COL = b"size"


class ConflictAvoidanceMode(Enum):
    """How allocators avoid contending on the same id-block claim key
    (reference: diskstorage/idmanagement/ConflictAvoidanceMode.java:76 —
    a user-visible config enum serialized into global config).

    NONE          — all instances race on one claim key per (ns, partition);
                    the claim protocol resolves conflicts (default).
    LOCAL_MANUAL  — this instance uses its locally configured tag.
    GLOBAL_MANUAL — every instance is expected to carry a (distinct)
                    configured tag; same mechanics as LOCAL_MANUAL here,
                    the distinction is operational intent.
    GLOBAL_AUTO   — each authority draws a random tag at startup.

    A tagged authority claims under key+tag and owns the tag's whole
    block-number subsequence (global block = local * num_tags + tag), so
    tagged allocators NEVER contend — at the cost of id-space striping.
    """

    NONE = "none"
    LOCAL_MANUAL = "local_manual"
    GLOBAL_MANUAL = "global_manual"
    GLOBAL_AUTO = "global_auto"


def _partition_key(namespace: int, partition: int) -> bytes:
    return struct.pack(">BI", namespace, partition)


class IDBlock:
    __slots__ = ("start", "size", "_next")

    def __init__(self, start: int, size: int):
        self.start = start
        self.size = size
        self._next = 0

    def next_id(self) -> Optional[int]:
        if self._next >= self.size:
            return None
        v = self.start + self._next
        self._next += 1
        return v

    def next_span(self, count: int):
        """Consume up to `count` contiguous ids; returns (start, taken)."""
        taken = min(count, self.size - self._next)
        start = self.start + self._next
        self._next += taken
        return start, taken

    @property
    def remaining(self) -> int:
        return self.size - self._next


class ConsistentKeyIDAuthority:
    """Allocates disjoint ID blocks from the shared `janusgraph_ids` store."""

    # namespaces (the reference separates vertex/relation/schema counters by key)
    NS_VERTEX = 0
    NS_RELATION = 1
    NS_SCHEMA = 2

    def __init__(
        self,
        store: KeyColumnValueStore,
        txh: StoreTransaction,
        block_size: int = 10_000,
        uid: Optional[bytes] = None,
        max_retries: int = 20,
        wait_ms: float = 2.0,
        conflict_mode: ConflictAvoidanceMode = ConflictAvoidanceMode.NONE,
        conflict_tag: int = 0,
        conflict_tag_bits: int = 4,
        read_only: bool = False,
    ):
        self.store = store
        self.txh = txh
        self.block_size = block_size
        #: storage.read-only: refuse block claims up front — the claim
        #: protocol writes to the id store before anything else would
        self.read_only = read_only
        self.conflict_mode = conflict_mode
        if conflict_mode is ConflictAvoidanceMode.NONE:
            self.num_tags = 1
            self.tag = 0
        else:
            self.num_tags = 1 << conflict_tag_bits
            if conflict_mode is ConflictAvoidanceMode.GLOBAL_AUTO:
                import random

                self.tag = random.randrange(self.num_tags)
            else:
                if not 0 <= conflict_tag < self.num_tags:
                    raise ValueError(
                        f"conflict-avoidance tag {conflict_tag} outside "
                        f"[0, 2^{conflict_tag_bits})"
                    )
                self.tag = conflict_tag
        self.uid = uid if uid is not None else (
            uuid.uuid4().bytes[:12] + os.getpid().to_bytes(4, "big")
        )
        assert len(self.uid) == 16
        self.max_retries = max_retries
        self.wait_ms = wait_ms
        self._frontier_cache: Dict[bytes, int] = {}
        self._check_block_size_agreement()

    def _check_block_size_agreement(self) -> None:
        stored = self.store.get_slice(
            KeySliceQuery(
                _BLOCK_SIZE_KEY,
                SliceQuery(_BLOCK_SIZE_COL, _BLOCK_SIZE_COL + b"\x00"),
            ),
            self.txh,
        )
        if not stored:
            self.store.mutate(
                _BLOCK_SIZE_KEY,
                [(_BLOCK_SIZE_COL, struct.pack(">Q", self.block_size))],
                [],
                self.txh,
            )
            stored = self.store.get_slice(
                KeySliceQuery(
                    _BLOCK_SIZE_KEY,
                    SliceQuery(_BLOCK_SIZE_COL, _BLOCK_SIZE_COL + b"\x00"),
                ),
                self.txh,
            )
        (agreed,) = struct.unpack(">Q", stored[0][1])
        if agreed != self.block_size:
            raise ConfigurationError(
                f"id block_size {self.block_size} disagrees with the cluster "
                f"value {agreed}; block size is a global constant"
            )

    def get_id_block(self, namespace: int, partition: int) -> IDBlock:
        if self.read_only:
            from janusgraph_tpu.exceptions import PermanentBackendError

            raise PermanentBackendError(
                "storage.read-only: id-block claims write to the id store"
            )
        key = _partition_key(namespace, partition)
        if self.num_tags > 1:
            # tagged claim space: no cross-tag contention; the frontier
            # under key+tag counts TAG-LOCAL blocks, remapped to a globally
            # disjoint block-number stripe below
            key += struct.pack(">H", self.tag)
        for _ in range(self.max_retries):
            frontier = self._read_frontier(key)
            block_end = frontier + self.block_size
            claim_col = (
                struct.pack(">QQ", block_end, time.time_ns()) + self.uid
            )
            self.store.mutate(key, [(claim_col, b"")], [], self.txh)
            # wait out write propagation so all contenders see the same rivals
            time.sleep(self.wait_ms / 1000.0)
            rivals = self.store.get_slice(
                KeySliceQuery(
                    key,
                    SliceQuery(
                        struct.pack(">Q", block_end),
                        struct.pack(">Q", block_end + 1),
                    ),
                ),
                self.txh,
            )
            if rivals and rivals[0][0] == claim_col:
                self._frontier_cache[key] = block_end
                if self.num_tags > 1:
                    # local block b -> global block b*num_tags + tag: every
                    # tag owns a disjoint stripe of the id space
                    b = frontier // self.block_size
                    start = (b * self.num_tags + self.tag) * self.block_size
                    return IDBlock(start + 1, self.block_size)
                return IDBlock(frontier + 1, self.block_size)
            # lost the race: withdraw and retry from a fresh frontier
            self.store.mutate(key, [], [claim_col], self.txh)
        raise TemporaryBackendError(
            f"could not allocate id block for ns={namespace} partition={partition} "
            f"after {self.max_retries} attempts"
        )

    def _read_frontier(self, key: bytes) -> int:
        """Largest claimed block end (0 if none). Claim columns sort by block
        end, so the frontier is the last column. Reads are incremental: we
        only slice claims beyond the last frontier this authority observed,
        so allocation cost doesn't grow with the claim history."""
        cached = self._frontier_cache.get(key, 0)
        entries = self.store.get_slice(
            KeySliceQuery(key, SliceQuery(struct.pack(">Q", cached + 1))),
            self.txh,
        )
        if entries:
            (end,) = struct.unpack(">Q", entries[-1][0][:8])
            cached = max(cached, end)
        self._frontier_cache[key] = cached
        return cached


class StandardIDPool:
    """Double-buffered per-(namespace, partition) ID pool: hands out single
    IDs from the current block and prefetches the next block in a background
    thread before exhaustion (reference: StandardIDPool.java:301)."""

    RENEW_FRACTION = 0.3  # prefetch when <30% remaining (ids.renew-percentage)

    def __init__(
        self,
        authority: ConsistentKeyIDAuthority,
        namespace: int,
        partition: int,
        max_id: Optional[int] = None,
        renew_fraction: Optional[float] = None,
        renew_timeout_ms: float = 0.0,
    ):
        self.authority = authority
        self.namespace = namespace
        self.partition = partition
        self.max_id = max_id
        #: ids.renew-timeout-ms: bound the wait for an in-flight background
        #: block fetch (0 = wait forever; reference: ids.renew-timeout)
        self.renew_timeout_ms = renew_timeout_ms
        self.RENEW_FRACTION = (
            renew_fraction if renew_fraction is not None else type(self).RENEW_FRACTION
        )
        self._lock = threading.Lock()
        self._current: Optional[IDBlock] = None
        self._next_block: Optional[IDBlock] = None
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_error: Optional[Exception] = None

    def next_id(self) -> int:
        with self._lock:
            while True:
                if self._current is not None:
                    v = self._current.next_id()
                    if v is not None:
                        if (
                            self._current.remaining
                            < self.authority.block_size * self.RENEW_FRACTION
                        ):
                            self._start_prefetch()
                        if self.max_id is not None and v > self.max_id:
                            raise IDPoolExhaustedError(
                                f"id namespace {self.namespace} partition "
                                f"{self.partition} exhausted"
                            )
                        return v
                # current exhausted (or absent): install the prefetched block,
                # or wait for an in-flight prefetch, or fetch synchronously.
                if self._next_block is not None:
                    self._current, self._next_block = self._next_block, None
                    continue
                t = self._prefetch_thread
                if t is not None:
                    # drop the lock while waiting; afterwards loop re-checks
                    # state, since another thread may have swapped already
                    self._lock.release()
                    try:
                        timeout = (
                            self.renew_timeout_ms / 1000.0
                            if self.renew_timeout_ms > 0 else None
                        )
                        t.join(timeout)
                        if t.is_alive():
                            raise TemporaryBackendError(
                                "id-block renewal exceeded "
                                f"ids.renew-timeout-ms "
                                f"({self.renew_timeout_ms:.0f}ms)"
                            )
                    finally:
                        self._lock.acquire()
                    if self._next_block is None and self._prefetch_error is not None:
                        err, self._prefetch_error = self._prefetch_error, None
                        raise err
                    continue
                # synchronous fallback: the double-buffer missed, so there
                # are NO ids to hand out until the claim round-trip (incl.
                # its propagation wait) completes — contenders must block
                # graphlint: disable=JG203 -- intentional: empty pool, callers must wait for the block claim
                self._current = self._fetch()

    def next_ids(self, count: int):
        """Bulk allocation: spans of contiguous ids drawn from successive
        blocks (the columnar write-back path needs millions of relation ids;
        one next_id() round trip per id would dominate). Returns a list of
        (start, length) spans covering exactly `count` ids."""
        spans = []
        remaining = count
        with self._lock:
            while remaining > 0:
                if self._current is None or self._current.remaining == 0:
                    if self._next_block is not None:
                        self._current, self._next_block = self._next_block, None
                    else:
                        # same synchronous-fallback contract as next_id
                        # graphlint: disable=JG203 -- intentional: empty pool, callers must wait for the block claim
                        self._current = self._fetch()
                start, taken = self._current.next_span(remaining)
                if taken:
                    if self.max_id is not None and start + taken - 1 > self.max_id:
                        raise IDPoolExhaustedError(
                            f"id namespace {self.namespace} exhausted"
                        )
                    spans.append((start, taken))
                    remaining -= taken
        return spans

    def _fetch(self) -> IDBlock:
        return self.authority.get_id_block(self.namespace, self.partition)

    def _start_prefetch(self) -> None:
        if self._prefetch_thread is not None or self._next_block is not None:
            return

        def run():
            try:
                blk = self._fetch()
                with self._lock:
                    self._next_block = blk
                    self._prefetch_error = None
                    self._prefetch_thread = None
            except Exception as e:  # surfaced on next exhaustion
                with self._lock:
                    self._prefetch_error = e
                    self._prefetch_thread = None

        t = threading.Thread(target=run, daemon=True, name="id-prefetch")
        # graphlint: disable=JG401 -- _start_prefetch is only called from next_id with self._lock already held; the prefetch thread's writes take the same lock
        self._prefetch_thread = t
        t.start()
