"""Distributed locking for backends without native transactions: optimistic
consistent-key lock claims plus in-process mediation plus expected-value
assertions at commit.

Capability parity with the reference's locking stack (reference:
diskstorage/locking/consistentkey/ConsistentKeyLocker.java — write a claim
column ``[timestamp, rid]`` to the lock row, wait ``lock.wait-time``, re-read
and let the lexicographically-first unexpired claim win, delete the claim on
loss; locking/LocalLockMediator.java:273 — in-process arbitration so
co-resident transactions never pay the storage round-trip;
consistentkey/ExpectedValueCheckingStore.java:133 +
ExpectedValueCheckingTransaction.java:285 — the slice observed at lock time
must still hold at commit, otherwise the commit fails).

The protocol needs only key-consistent reads from the store — no CAS — which
is exactly what every storage adapter of this framework guarantees.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# the canonical locking errors: TemporaryLockingError IS a
# TemporaryBackendError, so workload-level retry loops written against the
# backend taxonomy ('except TemporaryBackendError: retry the tx') absorb
# lock contention and lease expiry without special-casing
from janusgraph_tpu.exceptions import (
    PermanentLockingError,
    TemporaryLockingError,
)
from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)


@dataclass(frozen=True)
class KeyColumn:
    """The logical lock target: one (store row, column) cell."""

    key: bytes
    column: bytes


def lock_row_key(target: KeyColumn) -> bytes:
    """Lock-store row for a target cell: length-prefixed key ⧺ column so
    distinct (key, column) pairs can never collide."""
    return (
        len(target.key).to_bytes(4, "big") + target.key + target.column
    )


class LocalLockMediator:
    """In-process lock arbitration per lock namespace. Two transactions in
    the same process contending for one cell resolve here and only the
    winner talks to the store (reference: LocalLockMediator.java:273)."""

    def __init__(self):
        self._held: Dict[KeyColumn, Tuple[object, float]] = {}
        self._cv = threading.Condition()

    def claim(self, target: KeyColumn, holder: object, expiry: float) -> bool:
        with self._cv:
            cur = self._held.get(target)
            now = time.monotonic()
            if cur is not None and cur[0] is not holder and cur[1] > now:
                return False
            self._held[target] = (holder, expiry)
            return True

    def release(self, target: KeyColumn, holder: object) -> None:
        with self._cv:
            cur = self._held.get(target)
            if cur is not None and cur[0] is holder:
                del self._held[target]
                self._cv.notify_all()


#: one mediator namespace per store-manager instance — instances sharing a
#: manager (the "multiple graphs in one process" test technique) share it
_MEDIATORS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_MEDIATORS_LOCK = threading.Lock()


def mediator_for(manager) -> LocalLockMediator:
    # injector/decorator managers (FaultInjectingStoreManager) expose the
    # real manager as .wrapped — mediation must key on the SHARED backend,
    # or two graphs over one store would stop mediating in-process
    while hasattr(manager, "wrapped"):
        manager = manager.wrapped
    with _MEDIATORS_LOCK:
        med = _MEDIATORS.get(manager)
        if med is None:
            med = LocalLockMediator()
            _MEDIATORS[manager] = med
        return med


@dataclass
class _LockStatus:
    write_timestamp_ns: int
    expected: Optional[list]  # EntryList observed at lock time (None = unread)
    checked: bool = False


class ConsistentKeyLocker:
    """Claim-then-verify locking on a dedicated lock store.

    Claim column encoding: ``[timestamp_ns (8B big-endian)][rid]`` — sorting
    by column therefore sorts by claim time, and the first unexpired claim in
    the row owns the lock (reference: ConsistentKeyLocker.java claim
    write/check/delete cycle).
    """

    def __init__(
        self,
        lock_store: KeyColumnValueStore,
        store_tx_factory,
        rid: bytes,
        mediator: LocalLockMediator,
        wait_ms: float = 1.0,
        expiry_ms: float = 10_000.0,
        retries: int = 3,
        clean_expired: bool = False,
        clock_ns=None,
    ):
        self.store = lock_store
        self._tx_factory = store_tx_factory
        self.rid = rid
        self.mediator = mediator
        self.wait_ms = wait_ms
        self.expiry_ms = expiry_ms
        self.retries = retries
        #: lease-expiry clock used by check_locks. Injectable so tests and
        #: the chaos engine (FaultPlan.lock_clock_ns) can skew it — an
        #: expired lease must raise TemporaryLockingError and be
        #: re-acquirable, and that path needs to be exercisable without
        #: real 10s waits. Claim WRITE timestamps stay on the real clock:
        #: skewing only the check models a holder whose lease ran out.
        self.clock_ns = clock_ns or time.time_ns
        #: locks.clean-expired: delete expired claim columns encountered
        #: during checks (dead holders' claims otherwise linger until a
        #: compaction; reference: ConsistentKeyLocker CLEAN_EXPIRED)
        self.clean_expired = clean_expired
        self._locks: Dict[object, Dict[KeyColumn, _LockStatus]] = {}
        self._guard = threading.Lock()

    # ------------------------------------------------------------- claim path
    def _claim_column(self, ts_ns: int) -> bytes:
        return ts_ns.to_bytes(8, "big") + self.rid

    def write_lock(
        self, target: KeyColumn, tx: object, expected: Optional[list] = None
    ) -> None:
        """Acquire (or re-enter) the lock on `target` for holder `tx`."""
        with self._guard:
            held = self._locks.setdefault(tx, {})
            if target in held:
                if expected is not None and held[target].expected is None:
                    held[target].expected = expected
                return
        from janusgraph_tpu.observability import registry, span

        with span("lock.acquire"), registry.time("locks.write_lock"):
            self._write_claim(target, tx, expected)

    def _write_claim(
        self, target: KeyColumn, tx: object, expected: Optional[list]
    ) -> None:
        if not self.mediator.claim(
            target, tx, time.monotonic() + self.expiry_ms / 1000.0
        ):
            raise TemporaryLockingError(
                f"local lock contention on {target.key!r}/{target.column!r}"
            )
        row = lock_row_key(target)
        stx = self._tx_factory()
        last_exc: Optional[Exception] = None
        for _attempt in range(self.retries):
            ts = time.time_ns()
            col = self._claim_column(ts)
            try:
                self.store.mutate(row, [(col, b"")], [], stx)
            except Exception as e:  # claim write failed: clean up, retry
                last_exc = e
                try:
                    self.store.mutate(row, [], [col], stx)
                except Exception:
                    pass
                continue
            with self._guard:
                self._locks.setdefault(tx, {})[target] = _LockStatus(
                    ts, expected
                )
            return
        self.mediator.release(target, tx)
        raise TemporaryLockingError(
            f"failed to write lock claim after {self.retries} attempts"
        ) from last_exc

    # ------------------------------------------------------------- check path
    def check_locks(self, tx: object) -> None:
        """After all claims: wait out the claim window once, then verify every
        claim of `tx` is the first unexpired claim in its row."""
        with self._guard:
            held = dict(self._locks.get(tx, {}))
        if not held:
            return
        newest = max(s.write_timestamp_ns for s in held.values())
        elapsed_ms = (time.time_ns() - newest) / 1e6
        if elapsed_ms < self.wait_ms:
            time.sleep((self.wait_ms - elapsed_ms) / 1000.0)
        stx = self._tx_factory()
        now_ns = self.clock_ns()
        cutoff_ns = now_ns - int(self.expiry_ms * 1e6)
        for target, status in held.items():
            if status.checked:
                continue
            row = lock_row_key(target)
            if status.write_timestamp_ns < cutoff_ns:
                # the holder's OWN lease ran out (slow tx, GC pause, clock
                # skew): surface it as the retriable lease-expiry error and
                # release so the target is immediately re-acquirable
                self._release_target(target, status, tx, stx)
                raise TemporaryLockingError(
                    f"lock lease expired on {target.key!r}/"
                    f"{target.column!r} (claim age exceeds locks.expiry-ms="
                    f"{self.expiry_ms}) — re-acquire and retry"
                )
            entries = self.store.get_slice(
                KeySliceQuery(row, SliceQuery()), stx
            )
            winner = None
            stale: list = []
            for col, _val in entries:  # columns sort by timestamp
                ts = int.from_bytes(col[:8], "big")
                if ts < cutoff_ns:
                    stale.append(col)  # expired claim
                    continue
                winner = col[8:]
                break
            if self.clean_expired and stale:
                try:  # best-effort: cleanup must never fail the check
                    self.store.mutate(row, [], stale, stx)
                except Exception:  # noqa: BLE001
                    pass
            if winner != self.rid:
                self._release_target(target, status, tx, stx)
                raise TemporaryLockingError(
                    f"lost lock race on {target.key!r}/{target.column!r}"
                )
            status.checked = True

    def check_expected_values(self, tx: object, reader) -> None:
        """The expected-value half: `reader(target) -> EntryList` re-reads the
        data store; any drift since lock time fails the commit (reference:
        ExpectedValueCheckingTransaction.checkAllExpectedValues)."""
        with self._guard:
            held = dict(self._locks.get(tx, {}))
        for target, status in held.items():
            if status.expected is None:
                continue
            current = reader(target)
            if list(current) != list(status.expected):
                raise PermanentLockingError(
                    f"expected value changed under lock for "
                    f"{target.key!r}/{target.column!r}"
                )

    # ----------------------------------------------------------- release path
    def _release_target(
        self, target: KeyColumn, status: _LockStatus, tx: object, stx
    ) -> None:
        try:
            self.store.mutate(
                lock_row_key(target),
                [],
                [self._claim_column(status.write_timestamp_ns)],
                stx,
            )
        finally:
            self.mediator.release(target, tx)
            with self._guard:
                # drop the registration: a released (lost/expired) target
                # must be re-acquirable with a FRESH claim, not re-entered
                # on the stale timestamp
                held = self._locks.get(tx)
                if held is not None:
                    held.pop(target, None)

    def delete_locks(self, tx: object) -> None:
        with self._guard:
            held = self._locks.pop(tx, {})
        if not held:
            return
        stx = self._tx_factory()
        for target, status in held.items():
            self._release_target(target, status, tx, stx)

    def held_by(self, tx: object) -> List[KeyColumn]:
        with self._guard:
            return list(self._locks.get(tx, {}))
