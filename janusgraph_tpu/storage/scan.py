"""Full-table parallel scan framework — the substrate for ALL OLAP.

Capability parity with the reference's scanner
(reference: diskstorage/keycolumnvalue/scan/StandardScanner.java:39,
StandardScannerExecutor.java:98-216 row assembly + processor pipeline,
ScanJob.java:32 SPI, ScanMetrics.java:81), re-shaped for the TPU build:

A `ScanJob` declares the column slices it needs; the scanner streams every
row (optionally one partition key-range at a time), assembles the per-row
slice results, and feeds (key, {query: entries}) to the job. Jobs are
expected to be *batch-oriented* — the OLAP CSR loader consumes whole
partitions and vectorizes with numpy — so unlike the reference's
one-vertex-at-a-time Processor threads, the unit of work here is a
partition chunk, which is also the natural unit for device sharding.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from janusgraph_tpu.exceptions import TemporaryBackendError
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KeyColumnValueStore,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)


@dataclass
class ScanMetrics:
    """Progress counters (reference: scan/ScanMetrics.java)."""

    rows_processed: int = 0
    rows_skipped: int = 0
    custom: Dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def increment(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.custom[name] = self.custom.get(name, 0) + delta

    def add_rows(self, processed: int, skipped: int = 0) -> None:
        with self._lock:
            self.rows_processed += processed
            self.rows_skipped += skipped

    def merge(self, other: "ScanMetrics") -> None:
        with self._lock:
            self.rows_processed += other.rows_processed
            self.rows_skipped += other.rows_skipped
            for k, v in other.custom.items():
                self.custom[k] = self.custom.get(k, 0) + v


class ScanJob:
    """SPI for whole-store scans (reference: ScanJob.java:32)."""

    def get_queries(self) -> List[SliceQuery]:
        """Column slices to fetch per row; the first is the primary query —
        rows with no entries for it are skipped."""
        raise NotImplementedError

    def setup(self, metrics: ScanMetrics) -> None:
        pass

    def process(
        self,
        rows: List[Tuple[bytes, Dict[SliceQuery, EntryList]]],
        metrics: ScanMetrics,
    ) -> None:
        """Process a batch of assembled rows. Called concurrently from worker
        threads for different batches."""
        raise NotImplementedError

    def teardown(self, metrics: ScanMetrics) -> None:
        pass


class StandardScanner:
    """Runs ScanJobs over a store with partition-parallel workers."""

    def __init__(
        self,
        store: KeyColumnValueStore,
        txh: StoreTransaction,
        ordered_scan: bool = True,
        retries: int = 3,
    ):
        self.store = store
        self.txh = txh
        self.ordered_scan = ordered_scan
        #: per-partition retry budget for TemporaryBackendErrors mid-scan
        #: (a killed scan worker, a flaking shard): the range resumes from
        #: just past the last FULLY PROCESSED batch's key, so every row
        #: reaches the job exactly once (storage.scan-retries)
        self.retries = retries

    def execute(
        self,
        job: ScanJob,
        key_ranges: Optional[Sequence[Tuple[bytes, bytes]]] = None,
        num_workers: int = 1,
        batch_size: int = 4096,
    ) -> ScanMetrics:
        """Scan rows (optionally restricted to key ranges, e.g. one range per
        graph partition) and feed batches to the job.

        With `key_ranges`, ranges are scanned in parallel across
        `num_workers` threads — the analogue of the reference's
        DataPuller-per-query pipeline, except parallelism follows the
        partition structure that the TPU mesh will also use.
        """
        metrics = ScanMetrics()
        queries = job.get_queries()
        if not queries:
            raise ValueError("ScanJob declared no queries")
        from janusgraph_tpu.observability import capture_scope, registry, span

        with span(
            "store.scan", job=type(job).__name__, store=self.store.name,
            workers=num_workers,
        ) as sp, registry.time("storage.scan"):
            job.setup(metrics)
            try:
                if key_ranges is None:
                    self._scan_range(job, queries, None, metrics, batch_size)
                elif not self.ordered_scan:
                    # unordered backend: ONE full scan routed against the
                    # union of ranges (a per-range scan would re-read the
                    # whole store P times)
                    self._scan_unordered(
                        job, queries, key_ranges, metrics, batch_size
                    )
                elif num_workers <= 1 or len(key_ranges) <= 1:
                    for rng in key_ranges:
                        self._scan_range(job, queries, rng, metrics, batch_size)
                else:
                    # capture_scope: worker threads re-enter this span's
                    # context so per-range store reads stay attributed to
                    # the scan's trace/ledger/deadline (JG402 handoff)
                    scan_range = capture_scope(self._scan_range)
                    with ThreadPoolExecutor(max_workers=num_workers) as pool:
                        futs = [
                            pool.submit(
                                scan_range, job, queries, rng, metrics,
                                batch_size,
                            )
                            for rng in key_ranges
                        ]
                        for f in futs:
                            f.result()
            finally:
                job.teardown(metrics)
                sp.annotate(rows=metrics.rows_processed)
        return metrics

    def _scan_unordered(
        self,
        job: ScanJob,
        queries: List[SliceQuery],
        key_ranges: Sequence[Tuple[bytes, bytes]],
        metrics: ScanMetrics,
        batch_size: int,
    ) -> None:
        """One full unordered scan with client-side range filtering
        (reference: the CQL token-range getKeys path)."""
        primary, rest = queries[0], queries[1:]
        batch: List[Tuple[bytes, Dict[SliceQuery, EntryList]]] = []
        for key, primary_entries in self.store.get_keys(primary, self.txh):
            if not any(lo <= key < hi for lo, hi in key_ranges):
                continue
            slices: Dict[SliceQuery, EntryList] = {primary: primary_entries}
            for q in rest:
                slices[q] = self.store.get_slice(KeySliceQuery(key, q), self.txh)
            batch.append((key, slices))
            if len(batch) >= batch_size:
                job.process(batch, metrics)
                metrics.add_rows(len(batch))
                batch = []
        if batch:
            job.process(batch, metrics)
            metrics.add_rows(len(batch))

    def _scan_range(
        self,
        job: ScanJob,
        queries: List[SliceQuery],
        key_range: Optional[Tuple[bytes, bytes]],
        metrics: ScanMetrics,
        batch_size: int,
    ) -> None:
        """One partition range, with retry + resume: a TemporaryBackendError
        mid-stream (killed worker, flaking shard, injected chaos) re-issues
        the range from just past the last batch handed to the job. Rows of a
        PARTIAL batch are dropped and re-read — the job sees every row
        exactly once. Full unbounded scans (key_range=None) cannot resume
        precisely on an unordered backend and propagate the error."""
        primary, rest = queries[0], queries[1:]
        resume_after: Optional[bytes] = None
        attempt = 0
        while True:
            try:
                if key_range is None:
                    row_iter = self.store.get_keys(primary, self.txh)
                else:
                    start = (
                        key_range[0] if resume_after is None else resume_after
                    )
                    row_iter = self.store.get_keys(
                        KeyRangeQuery(start, key_range[1], primary), self.txh
                    )
                batch: List[Tuple[bytes, Dict[SliceQuery, EntryList]]] = []
                for key, primary_entries in row_iter:
                    slices: Dict[SliceQuery, EntryList] = {
                        primary: primary_entries
                    }
                    for q in rest:
                        slices[q] = self.store.get_slice(
                            KeySliceQuery(key, q), self.txh
                        )
                    batch.append((key, slices))
                    if len(batch) >= batch_size:
                        job.process(batch, metrics)
                        metrics.add_rows(len(batch))
                        # smallest key strictly after the processed prefix
                        resume_after = key + b"\x00"
                        batch = []
                if batch:
                    job.process(batch, metrics)
                    metrics.add_rows(len(batch))
                return
            except TemporaryBackendError:
                attempt += 1
                if key_range is None or attempt > self.retries:
                    raise
                from janusgraph_tpu.observability import registry

                metrics.increment("scan.retries")
                registry.counter("storage.scan.retries").inc()
