"""Durable pub/sub log implemented ON the key-column-value store itself.

Capability parity with the reference's KCVSLog
(reference: diskstorage/log/kcvs/KCVSLog.java:79 — time-bucketed row keys
with N buckets for write parallelism, a background send thread batching
appends, and per-bucket message-puller threads reading forward from a
ReadMarker; KCVSLogManager.java:244 — one store per log;
log/ReadMarker.java:128 — start-time / saved-position semantics;
log/MessageReader.java — the consumer SPI).

The same bus carries the three control-plane feeds of the system, exactly as
in the reference: the transaction WAL (``txlog``), management/schema-eviction
broadcast (``systemlog``), and user change-data-capture feeds (``ulog_*``).

Storage layout:
  row key  = [bucket:1][timeslice:8 BE]      (timeslice = ts_ns // slice_ns)
  column   = [timestamp_ns:8 BE][sender:8][seq:4 BE]   — time-ordered, unique
  value    = message content
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from janusgraph_tpu.storage.kcvs import (
    KeyColumnValueStore,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
)

_SLICE_MS = 100  # default row time-granularity (log.slice-granularity-ms)


@dataclass(frozen=True)
class LogMessage:
    content: bytes
    timestamp_ns: int
    sender: bytes  # 8-byte instance rid


class ReadMarker:
    """Where a reader starts (reference: ReadMarker.java:128)."""

    def __init__(self, start_ns: Optional[int] = None):
        self.start_ns = start_ns

    @classmethod
    def from_now(cls) -> "ReadMarker":
        return cls(time.time_ns())

    @classmethod
    def from_epoch(cls) -> "ReadMarker":
        return cls(0)

    @classmethod
    def from_time_ns(cls, ts: int) -> "ReadMarker":
        return cls(ts)


class KCVSLog:
    """One named durable log over one dedicated store."""

    def __init__(
        self,
        name: str,
        store: KeyColumnValueStore,
        tx_factory: Callable,
        sender: bytes,
        num_buckets: int = 4,
        send_batch_size: int = 256,
        send_interval_ms: float = 10.0,
        read_interval_ms: float = 20.0,
        timestamps=None,
        read_lag_ms: float = -1.0,
        read_only: bool = False,
        slice_granularity_ms: int = _SLICE_MS,
    ):
        from janusgraph_tpu.util.timestamps import TimestampProviders

        #: log.slice-granularity-ms — row time window (FIXED: row keys
        #: derive from it, so all writers/readers of a log must agree)
        self._slice_ns = slice_granularity_ms * 1_000_000
        self.name = name
        self.store = store
        self._tx_factory = tx_factory
        #: graph.timestamps: resolution all appended messages are stamped
        #: at (reference: KCVSLog times from the cluster TimestampProvider)
        self.timestamps = timestamps or TimestampProviders.NANO
        #: log.read-lag-ms: pullers stop this far behind now, so a message
        #: stamped in the window still counts as "not yet visible". The
        #: race is STAMP-TO-FLUSH delay, independent of resolution: a
        #: message is stamped at add() but flushes up to send_interval
        #: later, and a cross-sender message stamped earlier but flushed
        #: later would sort below the advanced cursor and be skipped
        #: forever (reference: KCVSLog maxReadTime / read-lag-time).
        #: auto (-1): 3x the send interval (covers the batch flush delay
        #: with margin) + one resolution tick for coarse stamps.
        if read_lag_ms < 0:
            read_lag_ms = (
                3.0 * send_interval_ms
                + self.timestamps.resolution_ns / 1e6
            )
        self._read_lag_ns = int(read_lag_ms * 1e6)
        self.read_only = read_only
        self.sender = (sender + b"\x00" * 8)[:8]
        self.num_buckets = num_buckets
        self.send_batch_size = send_batch_size
        self.send_interval_ms = send_interval_ms
        self.read_interval_ms = read_interval_ms
        self._seq = 0
        self._rr_bucket = 0
        self._outbox: List[Tuple[int, bytes, bytes]] = []  # (bucket, col, val)
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._flush_wakeup = threading.Event()
        self._send_thread: Optional[threading.Thread] = None
        self._readers: List[threading.Thread] = []

    # ------------------------------------------------------------------ write
    def _row_key(self, bucket: int, ts_ns: int) -> bytes:
        return bytes([bucket]) + (ts_ns // self._slice_ns).to_bytes(8, "big")

    def add(self, content: bytes, bucket: Optional[int] = None) -> None:
        """Append a message (batched; the send thread flushes). A partition
        key may pin the bucket so one entity's messages stay ordered."""
        if self.read_only:
            from janusgraph_tpu.exceptions import PermanentBackendError

            raise PermanentBackendError(
                "storage.read-only: log appends write to the log store"
            )
        with self._lock:
            ts = self.timestamps.time_ns()
            self._seq += 1
            col = (
                ts.to_bytes(8, "big")
                + self.sender
                + (self._seq & 0xFFFFFFFF).to_bytes(4, "big")
            )
            if bucket is None:
                bucket = self._rr_bucket
                self._rr_bucket = (self._rr_bucket + 1) % self.num_buckets
            self._outbox.append((bucket % self.num_buckets, col, content))
            if len(self._outbox) >= self.send_batch_size:
                self._flush_wakeup.set()
            if self._send_thread is None:
                self._send_thread = threading.Thread(
                    target=self._send_loop, name=f"log-{self.name}-send",
                    daemon=True,
                )
                self._send_thread.start()

    def add_now(self, content: bytes, bucket: Optional[int] = None) -> None:
        """Append and flush synchronously (WAL markers need durability before
        the commit proceeds)."""
        self.add(content, bucket)
        self.flush()

    def flush(self) -> None:
        with self._lock:
            batch = self._outbox
            self._outbox = []
        if not batch:
            return
        # group per row key
        rows: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        row_of: Dict[bytes, bytes] = {}
        for bucket, col, val in batch:
            ts = int.from_bytes(col[:8], "big")
            row = self._row_key(bucket, ts)
            rows.setdefault(row, []).append((col, val))
            row_of[col] = row
        done_rows = set()
        try:
            stx = self._tx_factory()
            for row, adds in rows.items():
                self.store.mutate(row, adds, [], stx)
                done_rows.add(row)
        except Exception:
            # durable-log promise: unwritten messages go back in the outbox
            # for the next flush instead of being dropped
            with self._lock:
                self._outbox[:0] = [
                    item for item in batch if row_of[item[1]] not in done_rows
                ]
            raise

    def _record_loop_error(self, loop: str, e: Exception) -> None:
        """Bounded observability for the background send/pull loops
        (JG112): the loop keeps running, the failure is on record."""
        from janusgraph_tpu.observability import flight_recorder, registry

        registry.counter("storage.log.loop_errors").inc()
        flight_recorder.record(
            "thread_error", thread=f"log-{self.name}-{loop}",
            error=repr(e),
        )

    def _send_loop(self) -> None:
        while not self._closed.is_set():
            self._flush_wakeup.wait(self.send_interval_ms / 1000.0)
            self._flush_wakeup.clear()
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - sender must not die
                # the batch is re-queued by flush() and retried next
                # tick, but the failure itself must be recorded (JG112):
                # a permanently failing sender is an outbox growing
                # toward the journal bound, invisibly
                self._record_loop_error("send", e)

    # ------------------------------------------------------------------- read
    def register_reader(
        self,
        marker: ReadMarker,
        reader: Callable[[LogMessage], None],
        poll_ms: Optional[float] = None,
    ) -> None:
        """Spawn one puller thread per bucket from the marker position
        (reference: KCVSLog.java:212 MessagePuller per (partition,bucket))."""
        start = marker.start_ns if marker.start_ns is not None else time.time_ns()
        for bucket in range(self.num_buckets):
            t = threading.Thread(
                target=self._pull_loop,
                args=(bucket, start, reader, poll_ms or self.read_interval_ms),
                name=f"log-{self.name}-pull-{bucket}",
                daemon=True,
            )
            t.start()
            self._readers.append(t)

    def _bucket_rows(self, bucket: int, start_ns: int, end_ns: int, stx):
        """Ordered scan of one bucket's rows in [start_ns, end_ns] — a key
        RANGE scan, so sparse logs cost only their actual rows."""
        start_key = bytes([bucket]) + (
            start_ns // self._slice_ns
        ).to_bytes(8, "big")
        end_key = bytes([bucket]) + (
            end_ns // self._slice_ns + 1
        ).to_bytes(8, "big")
        return self.store.get_keys(
            KeyRangeQuery(start_key, end_key, SliceQuery()), stx
        )

    def read_range(
        self, start_ns: int, end_ns: Optional[int] = None
    ) -> List[LogMessage]:
        """Synchronous bounded read across all buckets, time-ordered.
        (Recovery and tests want deterministic pulls without threads.)"""
        end = end_ns if end_ns is not None else time.time_ns()
        out: List[LogMessage] = []
        stx = self._tx_factory()
        for bucket in range(self.num_buckets):
            for _row, entries in self._bucket_rows(bucket, start_ns, end, stx):
                for col, val in entries:
                    ts = int.from_bytes(col[:8], "big")
                    if start_ns <= ts <= end:
                        out.append(LogMessage(val, ts, col[8:16]))
        out.sort(key=lambda m: m.timestamp_ns)
        return out

    def _pull_loop(
        self, bucket: int, start_ns: int, reader, poll_ms: float
    ) -> None:
        # strictly-increasing (row-slice, column) cursor per bucket
        cursor = ((start_ns // self._slice_ns).to_bytes(8, "big"), b"")
        while not self._closed.is_set():
            try:
                stx = self._tx_factory()
                # resume the ranged scan at the cursor's row; stop read-lag
                # behind now so same-tick stragglers still get consumed
                resume_ns = int.from_bytes(cursor[0], "big") * self._slice_ns
                end_ns = time.time_ns() - self._read_lag_ns
                for row, entries in self._bucket_rows(
                    bucket, resume_ns, end_ns, stx
                ):
                    row_slice = row[1:9]
                    for col, val in entries:
                        if (row_slice, col) <= cursor:
                            continue
                        ts = int.from_bytes(col[:8], "big")
                        if ts > end_ns:
                            # inside the lag window: revisit next poll —
                            # cursor must NOT advance past it
                            continue
                        cursor = (row_slice, col)
                        if ts < start_ns:
                            continue
                        try:
                            reader(LogMessage(val, ts, col[8:16]))
                        except Exception as e:  # noqa: BLE001 - a bad consumer must not kill the puller
                            self._record_loop_error("reader", e)
            except Exception as e:  # noqa: BLE001 - puller must not die
                # recorded, not raised (JG112): a puller failing every
                # poll means consumers silently stop seeing messages
                self._record_loop_error("pull", e)
            self._closed.wait(poll_ms / 1000.0)

    def close(self) -> None:
        self._closed.set()
        self._flush_wakeup.set()
        if self._send_thread is not None:
            self._send_thread.join(timeout=2.0)
        for t in self._readers:
            t.join(timeout=2.0)
        self.flush()


class LogManager:
    """Opens named logs over dedicated stores (reference:
    KCVSLogManager.java:244)."""

    def __init__(
        self,
        store_manager,
        sender: bytes,
        num_buckets: int = 4,
        send_batch_size: int = 256,
        read_interval_ms: float = 20.0,
        send_delay_ms: float = 10.0,
        ttl_seconds: float = 0.0,
        timestamps=None,
        read_lag_ms: float = -1.0,
        read_only: bool = False,
        slice_granularity_ms: int = _SLICE_MS,
    ):
        self.slice_granularity_ms = slice_granularity_ms
        self.manager = store_manager
        self.sender = sender
        self.timestamps = timestamps
        self.read_lag_ms = read_lag_ms
        self.read_only = read_only
        self.num_buckets = num_buckets
        self.send_batch_size = send_batch_size
        self.read_interval_ms = read_interval_ms
        self.send_delay_ms = send_delay_ms
        # log.ttl-seconds: expire log rows via a cell-TTL wrapper (the
        # reference's log.[X].ttl on ttl-capable stores)
        self.ttl_seconds = ttl_seconds
        self._logs: Dict[str, KCVSLog] = {}
        self._lock = threading.Lock()

    def open_log(self, name: str) -> KCVSLog:
        with self._lock:
            log = self._logs.get(name)
            if log is None:
                store = self.manager.open_database(name)
                if self.ttl_seconds > 0:
                    from janusgraph_tpu.storage.ttl import TTLKCVStore

                    store = TTLKCVStore(store, self.ttl_seconds)
                log = KCVSLog(
                    name,
                    store,
                    self.manager.begin_transaction,
                    self.sender,
                    num_buckets=self.num_buckets,
                    send_batch_size=self.send_batch_size,
                    send_interval_ms=self.send_delay_ms,
                    read_interval_ms=self.read_interval_ms,
                    timestamps=self.timestamps,
                    read_lag_ms=self.read_lag_ms,
                    read_only=self.read_only,
                    slice_granularity_ms=self.slice_granularity_ms,
                )
                self._logs[name] = log
            return log

    def close(self) -> None:
        with self._lock:
            for log in self._logs.values():
                log.close()
            self._logs.clear()
