"""Pipelined async framing for the remote wire protocols.

The synchronous framing (storage/remote.py PR 1) serializes one op per
round-trip under a per-connection lock: every getSlice pays a full wire
RTT plus two syscalls on each side, and concurrency is capped at the
pool size. This module is the amortize-per-message-cost fix (the same
principle PAPERS.md's propagation-blocking and communication-batching
papers apply to on-chip messages, applied to the wire): many in-flight
ops share few sockets, small ops coalesce into batched wire frames, and
responses complete out of order via per-frame request ids.

Wire format (negotiated — see the `pipeline` feature bit in
storage/remote.py / indexing/remote.py; un-negotiated peers never see a
flagged frame):

  pipelined request:   [u32 len][u8 op|flags|0x10][u32 req_id]
                       [trace?][deadline?][payload]
  batch carrier:       [u32 len][u8 OP_BATCH|0x10][u32 nsub]
                       ([u32 sub_len][u8 op|flags|0x10][u32 req_id]
                        [trace?][deadline?][payload])*
  pipelined response:  [u32 len][u8 status|0x10][u32 req_id]
                       [ledger?][payload]

Request-id lifecycle: ids are per-connection u32 counters assigned at
encode time; the id is registered in the pending table BEFORE the frame
is written, popped when its response arrives (any order), and failed
with a TemporaryBackendError if the connection dies first. The carrier
frame has no id of its own — every reply names the individual op, so
trace contexts, ledger echoes, deadline refusals, breaker accounting,
and injected faults all attribute to the op, never the carrier.

Coalescing rules (client writer):
  * getSlice ops with the same (store, slice, trace context, flags)
    merge into ONE getSliceMulti sub-frame; the response is demuxed per
    key back to each op's future. Merged frames drop the ledger flag —
    each op falls back to counting its own decoded entries client-side,
    so per-op attribution survives the merge.
  * mutate ops with the same (store, trace context, flags) and distinct
    keys merge into ONE mutateMany sub-frame (a duplicate key starts a
    new group, preserving same-key order).
  * everything else rides the carrier as individual sub-frames — still
    one syscall per drained batch on each side.
  * merge groups never mix trace contexts; a merged frame's deadline is
    the MINIMUM of its members' budgets (never extends any op).

Backpressure: the send queue is BOUNDED (`pipeline-depth`); a submit
that blocks on a full queue past `pipeline-stall-ms` is a pipeline
stall (counter + flight event). The queue bound is the overload story —
the JG206 discipline — not a hidden buffer.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from janusgraph_tpu.exceptions import (
    DeadlineExceededError,
    PermanentBackendError,
    TemporaryBackendError,
)

#: fourth flag bit of the op byte: the frame carries pipelined framing
#: ([u32 req_id] leads the body; responses echo it on status|0x10). Sent
#: only after the peer negotiated the `pipeline` capability.
PIPELINE_FLAG = 0x10

_STATUS_OK = 0
_STATUS_TEMP = 1
_STATUS_PERM = 2
#: low nibble of a pipelined status byte (high nibble carries the flag)
_STATUS_MASK = 0x0F



# hot-path module handles: resolved once, then plain global lookups —
# a `from x import y` per op would contend on the import lock across
# every submitting thread (measured at >15% CPU under load)
_R = None
_REG = None


def _remote_mod():
    global _R
    if _R is None:
        from janusgraph_tpu.storage import remote
        _R = remote
    return _R


def _registry():
    global _REG
    if _REG is None:
        from janusgraph_tpu.observability import registry
        _REG = registry
    return _REG


class WireOp:
    """One client op queued for pipelined submission. ``merge`` is the
    coalescing hint: None (unmergeable), ("gs", store, key, slice_bytes)
    for a getSlice, or ("mu", store, key, row_bytes) for a mutate.

    ``prefix`` carries the TRACE header only; the deadline prefix is
    encoded at frame-build time from ``expires_at`` so (a) the budget
    keeps shrinking while the op waits in the send queue, and (b) two
    ops under the same deadline scope still merge — their ambient
    remaining_ms differs by microseconds, which would defeat any
    byte-equality grouping on a pre-encoded prefix."""

    __slots__ = (
        "op", "flags", "prefix", "payload", "want_ledger", "merge",
        "expires_at",
    )

    def __init__(self, op: int, flags: int, prefix: bytes, payload: bytes,
                 want_ledger: bool = False, merge: Optional[tuple] = None,
                 expires_at: Optional[float] = None):
        self.op = op
        self.flags = flags
        self.prefix = prefix
        self.payload = payload
        self.want_ledger = want_ledger
        self.merge = merge
        self.expires_at = expires_at


class OpFuture:
    """Completion slot for one submitted op. First resolution wins
    (teardown and demux may race); ``result()`` re-raises failures.

    There is NO dedicated reader thread: ``result()`` drives the
    connection's leader/follower receive loop — the first waiter to win
    the receive lock becomes the leader, drains response frames (its own
    and every sibling's, completing their futures as they land), and on
    exit NUDGES one still-pending future so its waiter takes over
    leadership immediately (no polling gap). A single sequential caller
    therefore pays the same syscall pattern as the old synchronous path
    — send then recv on its own thread, zero handoffs — while
    concurrent callers get one leader amortizing wakeups for the whole
    in-flight set."""

    __slots__ = ("_cv", "_done", "_value", "_exc", "_nudged", "_conn",
                 "_ep")

    def __init__(self):
        self._cv = threading.Condition()
        self._done = False
        self._value = None
        self._exc: Optional[BaseException] = None
        self._nudged = False
        self._conn = None
        self._ep = None

    def bind(self, conn, ep) -> None:
        self._conn = conn
        self._ep = ep

    def set(self, value) -> None:
        with self._cv:
            if not self._done:
                self._value = value
                self._done = True
                self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cv:
            if not self._done:
                self._exc = exc
                self._done = True
                self._cv.notify_all()

    def nudge(self) -> None:
        """Wake this future's waiter WITHOUT completing it — the
        leadership baton: 'the receive role is vacant, come drive it'."""
        with self._cv:
            self._nudged = True
            self._cv.notify_all()

    def done(self) -> bool:
        return self._done

    def wait_or_nudge(self, timeout: float) -> None:
        """Follower wait: returns on completion, on a leadership nudge
        (consumed), or after ``timeout`` (the safety net when a nudge
        target abandoned its wait)."""
        with self._cv:
            if self._nudged:
                self._nudged = False
                return
            if self._done:
                return
            self._cv.wait(timeout)
            self._nudged = False

    def result(self, timeout: Optional[float] = None):
        if not self._done and self._conn is not None:
            self._conn._await(self._ep, self, timeout)
        with self._cv:
            if not self._done:
                self._cv.wait(timeout)
            if not self._done:
                raise TemporaryBackendError(
                    "pipelined op timed out waiting for its response"
                )
            if self._exc is not None:
                raise self._exc
            return self._value


class _Pending:
    """Server-side-completion bookkeeping for one req_id."""

    __slots__ = ("kind", "future", "members", "want_ledger")

    def __init__(self, kind: str, future: Optional[OpFuture] = None,
                 members: Optional[list] = None, want_ledger: bool = False):
        self.kind = kind  # "single" | "gslice" | "mutate"
        self.future = future
        self.members = members  # [(future, key)] / [future]
        self.want_ledger = want_ledger


def _status_error(status: int, payload: bytes) -> Exception:
    msg = payload.decode("utf-8", "replace")
    if status == _STATUS_TEMP:
        return TemporaryBackendError(msg)
    return PermanentBackendError(msg)


class _Entry:
    """One queued (item, future) pair; ``sent`` flips when a combiner
    drains it onto the wire (the submitter spins on it — see submit)."""

    __slots__ = ("item", "fut", "sent")

    def __init__(self, item: WireOp, fut: OpFuture):
        self.item = item
        self.fut = fut
        self.sent = False


class _Epoch:
    """One connection lifetime: socket + bounded send queue + pending
    table. Teardown fails everything and the owning connection redials
    on the next submit."""

    __slots__ = (
        "sock", "sq", "pending", "lock", "alive", "next_id", "send_lock",
        "recv_lock", "last_frame_at", "last_window_at",
    )

    def __init__(self, sock: socket.socket, depth: int):
        self.sock = sock
        self.sq: "queue.Queue" = queue.Queue(maxsize=depth)
        self.pending: Dict[int, _Pending] = {}
        self.lock = threading.Lock()
        self.alive = True
        self.next_id = 1
        self.last_window_at = 0.0
        #: the combining lock: whoever holds it drains the send queue
        #: into batched wire frames (flat combining — no writer thread,
        #: no handoff when uncontended, amortized syscalls under load)
        self.send_lock = threading.Lock()
        #: the receive-leadership lock: the waiter holding it drains
        #: response frames for everyone (leader/follower — no reader
        #: thread, no handoff for the uncontended sequential caller)
        self.recv_lock = threading.Lock()
        self.last_frame_at = time.monotonic()


class PipelinedConnection:
    """One pipelined socket, flat-combining on the send side: the
    submitting thread that wins the send lock drains the bounded queue
    — its own op plus everything queued by contending threads — into
    coalesced wire frames, so an uncontended op pays zero thread
    handoffs and a contended burst amortizes one syscall over the whole
    batch. A reader thread completes futures by request id, in whatever
    order the server finishes. Restartable: a dead connection redials
    on the next submit, and every in-flight op fails with a
    TemporaryBackendError so the per-op retry guard replays it."""

    def __init__(self, host: str, port: int, index: int,
                 connect_timeout_s: float = 30.0, depth: int = 128,
                 max_batch: int = 64, stall_ms: float = 200.0,
                 coalesce_us: float = 150.0,
                 metric_prefix: str = "storage.remote",
                 batch_op: int = 0,
                 split_ledger: Optional[Callable] = None,
                 encode_entries: Optional[Callable] = None,
                 decode_multi: Optional[Callable] = None):
        self.host, self.port = host, port
        self.index = index
        self.connect_timeout_s = connect_timeout_s
        self.depth = depth
        self.max_batch = max_batch
        self.stall_ms = stall_ms
        #: group-commit window: with ops already in flight, the combiner
        #: yields briefly so sibling threads can enqueue before the
        #: frame seals (closed-loop callers resubmit in convoys — the
        #: window turns the convoy into one coalesced carrier). 0 = off;
        #: a truly idle connection never waits (fast path).
        self.coalesce_s = coalesce_us / 1e6
        self.metric_prefix = metric_prefix
        #: the protocol's batch-carrier opcode (store: 10, index: 11)
        self.batch_op = batch_op
        # protocol hooks (injected so this module stays codec-agnostic)
        self._split_ledger = split_ledger
        self._encode_entries = encode_entries
        self._decode_multi = decode_multi
        self._epoch: Optional[_Epoch] = None
        self._lifecycle = threading.Lock()
        self._last_stall_flight = 0.0
        self._metric_cache: Dict[str, object] = {}
        # hot-path stats accumulate as plain ints (GIL-atomic +=) and
        # flush to the locked registry every _FLUSH_EVERY ops — four
        # contended metric locks per op would serialize the very
        # concurrency this path exists to provide
        self._stat_ops = 0
        self._stat_frames = 0
        self._stat_merged = 0
        self._stat_unflushed = 0
        self._last_batch = 0
        self._last_stat_flush = time.monotonic()

    _FLUSH_EVERY = 64

    # ------------------------------------------------------------- metrics
    def _counter(self, name: str):
        c = self._metric_cache.get(name)
        if c is None:
            # graphlint: disable=JG110 -- prefix is one of two protocol literals and name a fixed counter vocabulary: bounded
            c = _registry().counter(
                f"{self.metric_prefix}.pipeline.{name}"
            )
            self._metric_cache[name] = c
        return c

    def _gauge(self, name: str):
        g = self._metric_cache.get(name)
        if g is None:
            # graphlint: disable=JG110 -- conn index is bounded by storage.remote.connection-pool-size; prefix/name are fixed sets
            g = _registry().gauge(
                f"{self.metric_prefix}.pipeline.conn{self.index}.{name}"
            )
            self._metric_cache[name] = g
        return g

    def _note(self, ops: int = 0, frames: int = 0, merged: int = 0,
              force: bool = False) -> None:
        self._stat_ops += ops
        self._stat_frames += frames
        self._stat_merged += merged
        self._stat_unflushed += ops + frames + merged
        if self._stat_unflushed >= self._FLUSH_EVERY or force or (
            self._stat_unflushed
            and time.monotonic() - self._last_stat_flush > 0.05
        ):
            self._flush_stats()

    def _flush_stats(self) -> None:
        self._stat_unflushed = 0
        self._last_stat_flush = time.monotonic()
        if self._stat_ops:
            self._counter("ops").inc(self._stat_ops)
            self._stat_ops = 0
        if self._stat_frames:
            self._counter("wire_frames").inc(self._stat_frames)
            self._stat_frames = 0
        if self._stat_merged:
            self._counter("merged_ops").inc(self._stat_merged)
            self._stat_merged = 0
        ep = self._epoch
        if ep is not None:
            self._gauge("in_flight").set(float(len(ep.pending)))
        if self._last_batch:
            self._gauge("ops_per_frame").set(float(self._last_batch))
            self._last_batch = 0

    def _set_gauges(self, in_flight: int,
                    ops_per_frame: Optional[int] = None) -> None:
        self._gauge("in_flight").set(float(in_flight))
        if ops_per_frame is not None:
            self._gauge("ops_per_frame").set(float(ops_per_frame))

    # ------------------------------------------------------------ lifecycle
    def load(self) -> int:
        ep = self._epoch
        if ep is None or not ep.alive:
            return 0
        return len(ep.pending) + ep.sq.qsize()

    def _start_epoch(self) -> _Epoch:
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as e:
            raise TemporaryBackendError(f"connect failed: {e}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # short recv timeout: the receive LEADER must periodically
        # re-check its own deadline and epoch health; sustained silence
        # with ops pending past connect_timeout_s tears the epoch down
        sock.settimeout(0.5)
        ep = _Epoch(sock, self.depth)
        self._epoch = ep
        return ep

    def _teardown(self, ep: _Epoch, exc: Exception) -> None:
        with ep.lock:
            was_alive = ep.alive
            ep.alive = False
            pending = list(ep.pending.values())
            ep.pending.clear()
        if not was_alive:
            pending = []
        try:
            ep.sock.close()
        except OSError:
            pass
        for p in pending:
            self._fail_pending(p, exc)
        self._drain_queue(ep, exc)
        with self._lifecycle:
            if self._epoch is ep:
                self._epoch = None
        self._set_gauges(0)

    def _drain_queue(self, ep: _Epoch, exc: Exception) -> None:
        while True:
            try:
                entry = ep.sq.get_nowait()
            except queue.Empty:
                return
            entry.sent = True
            entry.fut.fail(exc)

    @staticmethod
    def _fail_pending(p: _Pending, exc: Exception) -> None:
        if p.future is not None:
            p.future.fail(exc)
        for m in p.members or ():
            fut = m[0] if isinstance(m, tuple) else m
            fut.fail(exc)

    def close(self) -> None:
        self._flush_stats()
        ep = self._epoch
        if ep is not None:
            self._teardown(
                ep, TemporaryBackendError("pipelined connection closed")
            )

    # --------------------------------------------------------------- submit
    def submit(self, item: WireOp) -> OpFuture:
        fut = OpFuture()
        ep = self._epoch
        if ep is None or not ep.alive:
            with self._lifecycle:
                ep = self._epoch
                if ep is None or not ep.alive:
                    ep = self._start_epoch()
        fut.bind(self, ep)
        self._note(ops=1)
        # fast path: a truly idle connection (nothing in flight, nothing
        # queued) skips the queue/batch machinery — encode and send on
        # the caller thread. With ops IN FLIGHT we take the queue path
        # instead: in-flight siblings mean sibling submits are imminent
        # (closed-loop convoy), and the combiner's group-commit window
        # below coalesces them into one carrier.
        # graphlint: disable=JG201 -- try-acquire fast path: the immediately following try/finally releases on every path
        if not ep.pending and ep.send_lock.acquire(blocking=False):
            direct = False
            try:
                if ep.sq.empty():
                    self._send_direct(ep, item, fut)
                    direct = True
            finally:
                ep.send_lock.release()
            if direct:
                return fut
        entry = _Entry(item, fut)
        try:
            ep.sq.put_nowait(entry)
        except queue.Full:
            # backpressure: the bounded queue is full — block, count the
            # stall, and surface it as a flight event (rate-limited)
            t0 = time.monotonic()
            try:
                ep.sq.put(entry, timeout=self.connect_timeout_s)
            except queue.Full:
                fut.fail(TemporaryBackendError(
                    "pipeline send queue full past the connect timeout"
                ))
                return fut
            waited_ms = (time.monotonic() - t0) * 1000.0
            if waited_ms >= self.stall_ms:
                self._counter("stalls").inc()
                now = time.monotonic()
                if now - self._last_stall_flight >= 1.0:
                    self._last_stall_flight = now
                    from janusgraph_tpu.observability import flight_recorder

                    flight_recorder.record(
                        "pipeline_stall",
                        endpoint=f"{self.host}:{self.port}",
                        protocol=self.metric_prefix,
                        waited_ms=round(waited_ms, 1),
                        depth=self.depth,
                    )
        # flat combining: spin until OUR entry hits the wire — either we
        # win the send lock and drain the queue (ours plus every
        # contending thread's), or a concurrent combiner drains it for
        # us. Uncontended this is acquire/encode/sendall on the caller
        # thread; contended, one combiner amortizes one syscall over the
        # whole burst.
        while not entry.sent and not fut.done():
            # graphlint: disable=JG201 -- combining-loop try-acquire: the immediately following try/finally releases on every path
            if not ep.send_lock.acquire(timeout=0.02):
                continue
            try:
                self._coalesce_window(ep)
                self._drain_and_send(ep)
            finally:
                ep.send_lock.release()
        if not ep.alive:
            # teardown raced the enqueue: make sure nothing is stranded
            self._drain_queue(
                ep, TemporaryBackendError("pipelined connection lost")
            )
        return fut

    def _send_direct(self, ep: _Epoch, item: WireOp, fut: OpFuture) -> None:
        """Encode and send ONE op as its own pipelined frame. Caller
        holds ep.send_lock."""
        _r = _remote_mod()
        now = time.monotonic()
        if item.expires_at is not None and now >= item.expires_at:
            _registry().counter(
                "storage.backend_op.deadline_expired"
            ).inc()
            self._counter("expired_in_queue").inc()
            fut.fail(DeadlineExceededError(
                "op deadline spent before the pipelined send"
            ))
            return
        prefix = item.prefix
        if item.flags & _r._DEADLINE_FLAG and item.expires_at is not None:
            prefix = prefix + _r.encode_deadline_prefix(
                max(0.0, (item.expires_at - now) * 1000.0)
            )
        pending = _Pending(
            "single", future=fut, want_ledger=item.want_ledger
        )
        req_id = self._register(ep, pending)
        body = struct.pack(">I", req_id) + prefix + item.payload
        frame = (
            struct.pack(
                ">IB", len(body), item.op | item.flags | PIPELINE_FLAG
            ) + body
        )
        try:
            # graphlint: disable=JG203 -- intentional: send half only under the combining lock (see _drain_and_send)
            ep.sock.sendall(frame)
        except (OSError, ConnectionError) as e:
            self._teardown(ep, TemporaryBackendError(
                f"pipelined send failed: {e}"
            ))
            return
        self._note(frames=1)

    def _coalesce_window(self, ep: _Epoch) -> None:
        """Group commit: with several ops in flight, their callers will
        resubmit as a convoy the moment the responses land — hold the
        frame open briefly so the convoy seals into ONE carrier (merged
        multi-gets, batched mutates) instead of trickling out as
        singles. ONE window per response burst: the first submitter
        after a quiet period opens it and collects the convoy;
        latecomers inside the same burst send immediately (a chain of
        back-to-back windows would serialize sends instead of batching
        them). Light concurrency (< 3 in flight) never waits."""
        if not self.coalesce_s:
            return
        in_flight = len(ep.pending)
        if in_flight < 3:
            return
        now = time.monotonic()
        if now - ep.last_window_at < 4 * self.coalesce_s:
            return
        ep.last_window_at = now
        target = min(self.max_batch, max(2, in_flight // 2))
        give_up = now + self.coalesce_s
        while ep.sq.qsize() < target and time.monotonic() < give_up:
            time.sleep(0.00005)  # park briefly; submitters fill the queue

    # ------------------------------------------------------------- combiner
    def _drain_and_send(self, ep: _Epoch) -> None:
        """Drain the send queue into coalesced wire frames (up to
        max_batch ops per frame) until empty. Caller holds ep.send_lock;
        the sendall under it is the SEND half only — never a round-trip
        — which is what retires the old one-lock-one-op design."""
        while True:
            batch: List[_Entry] = []
            while len(batch) < self.max_batch:
                try:
                    batch.append(ep.sq.get_nowait())
                except queue.Empty:
                    break
            if not batch:
                return
            for e in batch:
                e.sent = True
            buf, nops = self._encode_batch(ep, batch)
            if buf is None:
                continue
            try:
                # graphlint: disable=JG203 -- intentional: the combining lock serializes frame WRITES on this socket (send half only, responses complete via the reader); holding it across sendall is the flat-combining design
                ep.sock.sendall(buf)
            except (OSError, ConnectionError) as e2:
                self._teardown(ep, TemporaryBackendError(
                    f"pipelined send failed: {e2}"
                ))
                return
            self._last_batch = nops
            self._note(frames=1)

    def _register(self, ep: _Epoch, pending: _Pending) -> int:
        with ep.lock:
            req_id = ep.next_id
            ep.next_id = (ep.next_id + 1) & 0xFFFFFFFF or 1
            ep.pending[req_id] = pending
        return req_id

    def _encode_batch(
        self, ep: _Epoch, batch: List[_Entry]
    ) -> Tuple[Optional[bytes], int]:
        """Coalesce one drained batch into wire sub-frames, register the
        pending completions, and return (encoded buffer, op count)."""
        _r = _remote_mod()
        now = time.monotonic()
        singles: List[Tuple[WireOp, OpFuture]] = []
        groups: Dict[tuple, list] = {}
        nops = 0
        for e in batch:
            item, fut = e.item, e.fut
            if fut.done():
                continue  # failed while queued (teardown race)
            if item.expires_at is not None and now >= item.expires_at:
                # per-op deadline spent while queued: refuse client-side,
                # exactly like backend_op's pre-dispatch check — the op
                # never touches the wire
                _registry().counter(
                    "storage.backend_op.deadline_expired"
                ).inc()
                self._counter("expired_in_queue").inc()
                fut.fail(DeadlineExceededError(
                    "op deadline spent while queued in the pipeline"
                ))
                continue
            nops += 1
            if item.merge is not None:
                key = (item.merge[0], item.merge[1],
                       item.merge[3] if item.merge[0] == "gs" else b"",
                       item.prefix, item.flags & ~_r._LEDGER_FLAG)
                groups.setdefault(key, []).append((item, fut))
            else:
                singles.append((item, fut))
        subframes: List[bytes] = []

        def _budget_ms(item: WireOp) -> Optional[float]:
            if not item.flags & _r._DEADLINE_FLAG or item.expires_at is None:
                return None
            return max(0.0, (item.expires_at - now) * 1000.0)

        def _sub(raw_op: int, req_id: int, item_prefix: bytes,
                 payload: bytes, budget: Optional[float]) -> bytes:
            # the deadline prefix is encoded NOW, from the remaining
            # budget at send time: queue dwell is charged to the op
            prefix = item_prefix
            if budget is not None:
                prefix = prefix + _r.encode_deadline_prefix(budget)
            body = struct.pack(">I", req_id) + prefix + payload
            return struct.pack(">IB", len(body), raw_op | PIPELINE_FLAG) + body

        for (kind, store, _sl, prefix, gflags), members in groups.items():
            if len(members) == 1:
                singles.append(members[0])
                continue
            self._note(merged=len(members))
            budgets = [
                b for b in (_budget_ms(it) for it, _f in members)
                if b is not None
            ]
            # a merged frame's deadline is the MINIMUM of its members'
            # remaining budgets — it never extends any op's deadline
            budget = min(budgets) if budgets else None
            if kind == "gs":
                subframes.append(self._merge_gslice(
                    ep, store, prefix, gflags, members, _sub, budget
                ))
            else:
                subframes.extend(self._merge_mutate(
                    ep, store, prefix, gflags, members, _sub, budget
                ))
        for item, fut in singles:
            pending = _Pending(
                "single", future=fut, want_ledger=item.want_ledger
            )
            req_id = self._register(ep, pending)
            subframes.append(_sub(
                item.op | item.flags, req_id, item.prefix, item.payload,
                _budget_ms(item),
            ))
        if not subframes:
            return None, 0
        if len(subframes) == 1:
            return subframes[0], nops
        head = struct.pack(">I", len(subframes))
        body = head + b"".join(subframes)
        return (
            struct.pack(">IB", len(body), self.batch_op | PIPELINE_FLAG)
            + body,
            nops,
        )

    def _merge_gslice(self, ep, store, prefix, flags, members, _sub,
                      budget) -> bytes:
        """k getSlice ops, same (store, slice, context) -> one
        getSliceMulti sub-frame over the distinct keys."""
        _r = _remote_mod()
        slice_bytes = members[0][0].merge[3]
        keys: List[bytes] = []
        seen = set()
        futs: List[Tuple[OpFuture, bytes]] = []
        for item, fut in members:
            k = item.merge[2]
            if k not in seen:
                seen.add(k)
                keys.append(k)
            futs.append((fut, k))
        out: List[bytes] = []
        sb = store.encode()
        out.append(struct.pack(">I", len(sb)))
        out.append(sb)
        out.append(struct.pack(">I", len(keys)))
        for k in keys:
            out.append(struct.pack(">I", len(k)))
            out.append(k)
        out.append(slice_bytes)
        pending = _Pending("gslice", members=futs)
        req_id = self._register(ep, pending)
        # merged frames never carry the ledger flag: the echo could not
        # attribute to one op, so each member counts its own decoded
        # entries client-side instead (the documented fallback path)
        return _sub(
            _r._OP_GET_SLICE_MULTI | flags, req_id, prefix,
            b"".join(out), budget,
        )

    def _merge_mutate(self, ep, store, prefix, flags, members, _sub, budget):
        """k mutate ops, same (store, context), distinct keys -> one
        mutateMany sub-frame; a duplicate key starts a new group so
        same-key ordering is preserved."""
        from janusgraph_tpu.storage import remote as _r

        frames: List[bytes] = []
        group: List[Tuple[WireOp, OpFuture]] = []
        seen: set = set()

        def _flush():
            if not group:
                return
            sb = store.encode()
            out = [struct.pack(">I", 1), struct.pack(">I", len(sb)), sb,
                   struct.pack(">I", len(group))]
            futs = []
            for item, fut in group:
                out.append(item.merge[3])  # [key][adds][ndels][dels]
                futs.append(fut)
            pending = _Pending("mutate", members=futs)
            req_id = self._register(ep, pending)
            frames.append(_sub(
                _r._OP_MUTATE_MANY | flags, req_id, prefix,
                b"".join(out), budget,
            ))
            group.clear()
            seen.clear()

        for item, fut in members:
            k = item.merge[2]
            if k in seen:
                _flush()
            seen.add(k)
            group.append((item, fut))
        _flush()
        return frames

    # ------------------------------------------------- leader/follower recv
    def _await(self, ep: _Epoch, fut: OpFuture,
               timeout: Optional[float]) -> None:
        """Drive completion of ``fut``: become the receive leader when
        the role is free (drain frames for EVERY waiter), otherwise
        follow — wait for completion or a leadership nudge. A leader
        that finishes with siblings still pending nudges one of them on
        the way out, so the receive role never sits vacant behind a
        polling interval."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while not fut.done():
            # graphlint: disable=JG201 -- leader/follower try-acquire: the immediately following try/finally releases (and hands leadership off) on every path
            if ep.recv_lock.acquire(blocking=False):
                try:
                    while not fut.done() and ep.alive:
                        if not self._recv_one(ep):
                            break
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            break
                    # greedy drain: responses already buffered on the
                    # socket are FREE to demux now — without this, each
                    # buffered frame would cost the next leader a full
                    # thread wakeup (leadership churn serializes the
                    # response burst at one wake per op)
                    if fut.done() and ep.alive:
                        self._drain_buffered(ep)
                finally:
                    ep.recv_lock.release()
                    self._handoff(ep)
                if fut.done():
                    return
            else:
                # follower: our future completes the instant the leader
                # demuxes our frame; the timeout is only the safety net
                # for a dropped baton (nudge target stopped waiting)
                fut.wait_or_nudge(0.05)
            if not ep.alive:
                fut.fail(TemporaryBackendError("pipelined connection lost"))
                return
            if deadline is not None and time.monotonic() >= deadline:
                return  # result() raises the timeout

    def _drain_buffered(self, ep: _Epoch) -> None:
        """Demux every response frame already sitting in the socket
        buffer (bounded). Caller holds ep.recv_lock."""
        import select

        for _ in range(256):
            try:
                r, _w, _x = select.select([ep.sock], [], [], 0)
            except (OSError, ValueError):
                return
            if not r:
                return
            if not self._recv_one(ep):
                return

    def _handoff(self, ep: _Epoch) -> None:
        """Pass receive leadership: nudge one pending future's waiter so
        it contends for the (now free) recv lock immediately."""
        nxt: Optional[OpFuture] = None
        with ep.lock:
            for p in ep.pending.values():
                if p.future is not None:
                    nxt = p.future
                elif p.members:
                    m = p.members[0]
                    nxt = m[0] if isinstance(m, tuple) else m
                if nxt is not None:
                    break
        if nxt is not None:
            nxt.nudge()

    @staticmethod
    def _recv_rest(sock: socket.socket, buf: bytes, n: int,
                   budget_s: float) -> bytes:
        """Finish reading an n-byte chunk we are already committed to
        (mid-frame): short recv timeouts retry until the silence budget
        is spent — abandoning a partial frame would desync the stream,
        so past the budget the connection is torn down instead."""
        out = bytearray(buf)
        give_up = time.monotonic() + budget_s
        while len(out) < n:
            try:
                chunk = sock.recv(n - len(out))
            except socket.timeout:
                if time.monotonic() >= give_up:
                    raise ConnectionError(
                        "pipelined response stalled mid-frame"
                    ) from None
                continue
            if not chunk:
                raise ConnectionError("connection closed mid-frame")
            out += chunk
        return bytes(out)

    def _recv_one(self, ep: _Epoch) -> bool:
        """Receive and demux ONE response frame. Returns False when the
        caller should re-evaluate (clean timeout tick with no frame byte
        consumed); tears the epoch down on connection failure or
        sustained silence with ops pending."""
        sock = ep.sock
        try:
            try:
                first = sock.recv(5)
            except socket.timeout:
                # clean tick (no bytes consumed): fatal only when the
                # silence with ops pending outlives the connect timeout
                with ep.lock:
                    waiting = bool(ep.pending)
                if waiting and (
                    time.monotonic() - ep.last_frame_at
                    > self.connect_timeout_s
                ):
                    self._teardown(ep, TemporaryBackendError(
                        "pipelined response timed out"
                    ))
                return False
            if not first:
                raise ConnectionError("connection closed")
            head = self._recv_rest(sock, first, 5, self.connect_timeout_s)
            (blen,) = struct.unpack(">I", head[:4])
            status_raw = head[4]
            payload = (
                self._recv_rest(sock, b"", blen, self.connect_timeout_s)
                if blen else b""
            )
            if len(payload) < 4 or not status_raw & PIPELINE_FLAG:
                raise ConnectionError(
                    "non-pipelined frame on a pipelined connection"
                )
            (req_id,) = struct.unpack_from(">I", payload, 0)
            rest = payload[4:]
        except (OSError, ConnectionError, struct.error, ValueError) as e:
            self._teardown(ep, TemporaryBackendError(
                f"pipelined receive failed: {e}"
            ))
            return False
        ep.last_frame_at = time.monotonic()
        with ep.lock:
            pending = ep.pending.pop(req_id, None)
        if pending is not None:
            self._complete(pending, status_raw & _STATUS_MASK, rest)
        return True

    def _complete(self, p: _Pending, status: int, rest: bytes) -> None:
        if status != _STATUS_OK:
            self._fail_pending(p, _status_error(status, rest))
            return
        if p.kind == "single":
            fields = None
            if p.want_ledger and self._split_ledger is not None:
                fields, rest = self._split_ledger(rest)
            p.future.set((rest, fields))
            return
        if p.kind == "mutate":
            for fut in p.members:
                fut.set((b"", None))
            return
        # gslice: decode the multi payload once, hand each member its
        # own key's entries re-encoded as a single-slice payload — the
        # callers' decode path (and per-op fallback accounting) is
        # byte-identical to an unmerged response
        try:
            res = self._decode_multi(rest)
        except Exception as e:  # noqa: BLE001 - torn payload
            self._fail_pending(p, TemporaryBackendError(
                f"merged multi-slice payload undecodable: {e}"
            ))
            return
        for fut, key in p.members:
            fut.set((self._encode_entries(res.get(key, [])), None))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


class PipelineMux:
    """Connection multiplexer: many in-flight ops share few pipelined
    sockets. submit() routes to the least-loaded connection."""

    def __init__(self, host: str, port: int, connections: int = 2,
                 **conn_kwargs):
        self._conns = [
            PipelinedConnection(host, port, i, **conn_kwargs)
            for i in range(max(1, connections))
        ]
        self._rr = 0

    def submit(self, item: WireOp) -> OpFuture:
        # lock-free round robin (the GIL makes the increment atomic
        # enough: a rare duplicate index is harmless): a least-loaded
        # scan would take every connection's queue lock on every op
        self._rr = (self._rr + 1) % len(self._conns)
        return self._conns[self._rr].submit(item)

    def close(self) -> None:
        for c in self._conns:
            c.close()

    def flush_stats(self) -> None:
        """Push every connection's locally-batched counters/gauges into
        the registry NOW (they otherwise flush every 64 ops / 50 ms of
        activity / on close)."""
        for c in self._conns:
            c._flush_stats()

    def in_flight(self) -> int:
        return sum(c.load() for c in self._conns)

    def busy(self) -> bool:
        """Cheap concurrency probe (no locks): True when any connection
        has ops in flight."""
        for c in self._conns:
            ep = c._epoch
            if ep is not None and ep.pending:
                return True
        return False


# ---------------------------------------------------------------- server side
class _InlineReply:
    """Immediate reply writer for inline-served (sequential) frames."""

    __slots__ = ("_pipe",)

    def __init__(self, pipe: "ServerPipeline"):
        self._pipe = pipe

    def reply(self, req_id: int, status: int, body: bytes) -> None:
        self._pipe.write(
            struct.pack(">IB", len(body) + 4, status | PIPELINE_FLAG)
            + struct.pack(">I", req_id) + body
        )


class _ReplyBuffer:
    """Accumulates one carrier's pipelined response frames and flushes
    them in ONE write under the connection's write lock — the receive
    syscall amortization, mirrored on the reply side."""

    __slots__ = ("_pipe", "_parts", "_size")

    _FLUSH_BYTES = 1 << 16

    def __init__(self, pipe: "ServerPipeline"):
        self._pipe = pipe
        self._parts: List[bytes] = []
        self._size = 0

    def reply(self, req_id: int, status: int, body: bytes) -> None:
        frame = (
            struct.pack(">IB", len(body) + 4, status | PIPELINE_FLAG)
            + struct.pack(">I", req_id) + body
        )
        self._parts.append(frame)
        self._size += len(frame)
        if self._size >= self._FLUSH_BYTES:
            self.flush()

    def flush(self) -> None:
        if not self._parts:
            return
        buf = b"".join(self._parts)
        self._parts = []
        self._size = 0
        self._pipe.write(buf)


class ServerPipeline:
    """Per-connection server state for pipelined frames.

    Dispatch policy, tuned for the two traffic shapes:

    * **sequential** (one op in flight): serve the frame INLINE on the
      connection thread — out-of-order machinery buys nothing with a
      single outstanding op, and the worker-pool handoff would just tax
      every op with a thread wakeup. Inline is taken only when no pool
      task is active AND no further frame is already buffered on the
      socket, so a concurrent stream never lands behind an inline op it
      could have overtaken.
    * **concurrent** (frames/batches in flight): every sub-op becomes
      its own worker-pool task — ops complete out of order, a slow or
      fault-stalled op never blocks its siblings, and each reply is
      written under the connection's write lock addressed by request
      id.
    """

    #: inline-serve only while the EWMA op duration stays below this —
    #: an op that blocks the connection's read loop for longer than a
    #: pool handoff costs would serialize the stream behind it
    _INLINE_EWMA_S = 0.0001

    def __init__(self, sock: socket.socket, workers: int = 4):
        from concurrent.futures import ThreadPoolExecutor

        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._wlock = threading.Lock()
        self._alock = threading.Lock()
        self._active = 0
        #: EWMA of recent op service time (seconds); starts optimistic
        #: so a fast sequential stream takes the inline path immediately
        self._ewma_s = 0.0
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers), thread_name_prefix="pipe-serve"
        )

    def note_duration(self, dt_s: float) -> None:
        # GIL-atomic enough for a heuristic
        self._ewma_s = 0.8 * self._ewma_s + 0.2 * dt_s

    def serve_inline_ok(self) -> bool:
        """True when the sequential fast path applies: nothing running
        on the pool, nothing more buffered to read, and recent ops have
        been FAST — a slow op served inline would hold up the read loop
        for its whole duration (the one thing pipelining must never
        do), so slow traffic always rides the pool."""
        if self._ewma_s > self._INLINE_EWMA_S:
            return False
        with self._alock:
            if self._active:
                return False
        import select

        r, _w, _x = select.select([self._sock], [], [], 0)
        return not r

    def submit_op(self, serve: Callable, mgr, sub_raw: int,
                  sub_body: bytes, t_arrival: float) -> None:
        """Schedule one sub-op as its own pool task (out-of-order
        completion unit)."""
        with self._alock:
            self._active += 1
        self._pool.submit(self._run_op, serve, mgr, sub_raw, sub_body,
                          t_arrival)

    def _run_op(self, serve, mgr, sub_raw, sub_body, t_arrival) -> None:
        out = _ReplyBuffer(self)
        t0 = time.monotonic()
        try:
            serve(mgr, out, sub_raw, sub_body, t_arrival)
            out.flush()
        except (OSError, ConnectionError):
            pass  # connection died mid-reply; the handler loop notices
        finally:
            self.note_duration(time.monotonic() - t0)
            with self._alock:
                self._active -= 1

    def write(self, buf: bytes) -> None:
        with self._wlock:
            # graphlint: disable=JG203 -- intentional: the write lock serializes response frames onto the shared socket; it guards the send half only, never a round-trip
            self._sock.sendall(buf)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


def iter_batch(body: bytes):
    """Yield (raw_op, sub_body) for each sub-frame of a batch carrier.
    A sub-frame is [u32 sub_len][u8 op|flags][sub_body]; sub_len counts
    the sub_body only (the op byte rides the 5-byte header, exactly like
    a top-level frame)."""
    (n,) = struct.unpack_from(">I", body, 0)
    off = 4
    for _ in range(n):
        (sub_len,) = struct.unpack_from(">I", body, off)
        raw = body[off + 4]
        yield raw, body[off + 5 : off + 5 + sub_len]
        off += 5 + sub_len


def pipeline_health_block(snapshot: dict) -> dict:
    """The /healthz ``pipeline`` block: per-protocol in-flight depth and
    coalescing ratios aggregated from the remote clients' gauges and
    counters in a registry snapshot."""
    block: Dict[str, dict] = {}
    for proto in ("storage.remote", "index.remote"):
        prefix = f"{proto}.pipeline."
        in_flight = sum(
            m.get("value", 0)
            for name, m in snapshot.items()
            if name.startswith(prefix) and name.endswith(".in_flight")
            and m.get("type") == "gauge"
        )
        counters = {
            name[len(prefix):]: m["count"]
            for name, m in snapshot.items()
            if name.startswith(prefix) and m.get("type") == "counter"
        }
        if not counters and not in_flight:
            continue
        ops = counters.get("ops", 0)
        frames = counters.get("wire_frames", 0)
        block[proto] = {
            "in_flight": in_flight,
            "ops": ops,
            "wire_frames": frames,
            "merged_ops": counters.get("merged_ops", 0),
            "coalesce_ratio": round(ops / frames, 3) if frames else None,
            "stalls": counters.get("stalls", 0),
            "expired_in_queue": counters.get("expired_in_queue", 0),
            "negotiation_fallbacks": counters.get("fallbacks", 0),
        }
    return block
