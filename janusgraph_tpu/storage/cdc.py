"""Durable segmented change-data-capture log.

PR 14's :class:`~janusgraph_tpu.olap.delta.ChangeCapture` is a
per-process ring: it dies with its replica and overflow re-anchors
consumers to a full rescan. This module gives the capture a durable
spine — every committed batch the capture decodes is also appended to an
on-disk, cursor-addressable log that survives restarts and feeds
follower replicas over the fleet plane (server/fleet.py CDCFollower).

Disk layout (all under one directory, ``storage.cdc.dir``):

``cdc-tail.tmp``
    The active tail: crc-framed batch records appended in epoch order.
    The ``.tmp`` name is honest — the tail IS the uncommitted
    intermediate of the next sealed segment, and sealing commits it
    atomically. A crash mid-append tears at most the last frame; the
    recovery scan drops exactly the torn suffix and nothing else.

``cdc-%06d.segment``
    Sealed segments: a digest-embedded header over the same framed
    payload, written with the checkpoint discipline (mkstemp in the
    target directory + ``os.replace``), so a sealed segment is either
    complete-and-verifiable or absent — never torn.

``manifest.cdc.json``
    The digest-embedded manifest (sha256 over canonical JSON, ``.prev``
    demotion on rewrite — olap/sharded_checkpoint.py discipline) listing
    sealed segments with their cursor/epoch ranges and digests. Tail
    appends never touch the manifest; it only rewrites on seal/prune.

Record encoding rides the fixed-width bulk edge codec
(core/codecs.py ``EDGE_COL_FIXED``): each edge-lane row is the owning
vertex id (8 bytes big-endian) followed by the exact 27-byte fixed
column layout — category, type id, direction, sklen=0, other vid,
relation id — so encode and decode are single vectorized numpy passes,
the same hot-loop replacement as ``EdgeSerializer.bulk_decode_edges``.

Cursor semantics: a cursor is the global batch ordinal (0-based).
``replay_from(cursor)`` returns every surviving record at or past the
cursor plus the next cursor; it returns ``None`` — honestly, never a
partial answer — when the range is unservable: pruned past (retention),
a poison record (a commit the capture could not decode) inside the
range, or a corrupt/missing sealed segment. ``None`` means the consumer
must re-bootstrap from a checkpoint whose epoch clears the gap.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import tempfile
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from janusgraph_tpu.core.codecs import Direction, EDGE_COL_FIXED

MANIFEST_NAME = "manifest.cdc.json"
TAIL_NAME = "cdc-tail.tmp"
_LOG_KIND = "cdc-log"
_VERSION = 1

#: frame = length + crc32 over the payload, then the payload
_FRAME = struct.Struct(">II")
#: batch payload header: epoch, flags, n_add, n_del, n_vadd, n_vdel
_BHEAD = struct.Struct(">qBIIII")
_FLAG_POISON = 0x01
#: edge-lane row: owning vid (8B big-endian) + the fixed-width column
_EDGE_ROW = 8 + EDGE_COL_FIXED
#: sealed-segment header: magic, records, first_cursor, first_epoch,
#: last_epoch, sha256(payload)
_SEG_HEAD = struct.Struct(">8sIqqq32s")
_SEG_MAGIC = b"JGCDCSG1"


class CDCTornWrite(RuntimeError):
    """Raised by the seeded torn-write fault: the process 'died' with a
    partial frame on the tail. Reopen the log to recover."""


def _segment_name(seq: int) -> str:
    return "cdc-%06d.segment" % seq


def _manifest_digest(body: dict) -> str:
    canon = json.dumps(
        {k: v for k, v in sorted(body.items()) if k != "digest"},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()


# ---------------------------------------------------------------------------
# batch <-> bytes (the fixed-width codec lanes)
# ---------------------------------------------------------------------------

def _encode_edge_lane(src, dst, et) -> bytes:
    """(src, dst, type) int64 arrays -> rows of vid + fixed-width column
    (the exact byte layout EdgeSerializer.bulk_decode_edges consumes)."""
    m = len(src)
    if not m:
        return b""

    def _be(a):
        return (
            np.ascontiguousarray(np.asarray(a, np.int64).astype(">u8"))
            .view(np.uint8).reshape(m, 8)
        )

    rows = np.zeros((m, _EDGE_ROW), dtype=np.uint8)
    rows[:, 0:8] = _be(src)
    rows[:, 8] = 3  # edge category byte
    rows[:, 9:17] = _be(et)  # type id lane
    rows[:, 17] = int(Direction.OUT)
    rows[:, 18] = 0  # sklen = 0: the fixed-width fast path
    rows[:, 19:27] = _be(dst)  # other-vid lane
    # bytes 27:35 stay zero: relation ids do not survive netting
    return rows.tobytes()


def _decode_edge_lane(data: bytes, m: int):
    if not m:
        z = np.empty(0, dtype=np.int64)
        return z, z.copy(), z.copy()
    buf = np.frombuffer(data, dtype=np.uint8).reshape(m, _EDGE_ROW)

    def _i64(lo, hi):
        return buf[:, lo:hi].copy().view(">u8").astype(np.int64).ravel()

    return _i64(0, 8), _i64(19, 27), _i64(9, 17)  # src, dst, type


def encode_batch(epoch: int, batch: Optional[dict]) -> bytes:
    """One capture batch (or ``None`` = poison) -> payload bytes."""
    if batch is None:
        return _BHEAD.pack(int(epoch), _FLAG_POISON, 0, 0, 0, 0)
    a_src, a_dst, a_et = batch["add"]
    d_src, d_dst, d_et = batch["del"]
    v_add = batch.get("v_add") or {}
    v_del = batch.get("v_del") or []
    parts = [
        _BHEAD.pack(
            int(epoch), 0, len(a_src), len(d_src), len(v_add), len(v_del)
        ),
        _encode_edge_lane(a_src, a_dst, a_et),
        _encode_edge_lane(d_src, d_dst, d_et),
    ]
    if v_add:
        va = np.asarray(
            [[int(k), int(v)] for k, v in v_add.items()], dtype=np.int64
        )
        parts.append(np.ascontiguousarray(va.astype(">i8")).tobytes())
    if v_del:
        vd = np.asarray([int(v) for v in v_del], dtype=np.int64)
        parts.append(np.ascontiguousarray(vd.astype(">i8")).tobytes())
    return b"".join(parts)


def decode_batch(payload: bytes) -> Tuple[int, Optional[dict]]:
    """Payload bytes -> (epoch, batch-or-None-for-poison). Raises on any
    structural mismatch (the caller treats that as a torn frame)."""
    epoch, flags, n_add, n_del, n_vadd, n_vdel = _BHEAD.unpack_from(
        payload
    )
    if flags & _FLAG_POISON:
        return epoch, None
    off = _BHEAD.size
    end = off + n_add * _EDGE_ROW
    a_src, a_dst, a_et = _decode_edge_lane(payload[off:end], n_add)
    off = end
    end = off + n_del * _EDGE_ROW
    d_src, d_dst, d_et = _decode_edge_lane(payload[off:end], n_del)
    off = end
    v_add: Dict[int, int] = {}
    if n_vadd:
        end = off + n_vadd * 16
        va = (
            np.frombuffer(payload[off:end], dtype=">i8")
            .astype(np.int64).reshape(n_vadd, 2)
        )
        v_add = {int(r[0]): int(r[1]) for r in va}
        off = end
    v_del: List[int] = []
    if n_vdel:
        end = off + n_vdel * 8
        v_del = [
            int(v)
            for v in np.frombuffer(payload[off:end], dtype=">i8")
        ]
        off = end
    if off != len(payload):
        raise ValueError("cdc batch payload length mismatch")
    return epoch, {
        "n": n_add + n_del + len(v_add) + len(v_del),
        "add": (a_src, a_dst, a_et),
        "del": (d_src, d_dst, d_et),
        "v_add": v_add,
        "v_del": v_del,
    }


def _frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def _iter_frames(data: bytes):
    """Yield (payload, end_offset) for every intact frame; stop silently
    at the first torn/corrupt one (crc or length mismatch)."""
    off = 0
    while off + _FRAME.size <= len(data):
        length, crc = _FRAME.unpack_from(data, off)
        end = off + _FRAME.size + length
        if end > len(data):
            return
        payload = data[off + _FRAME.size:end]
        if zlib.crc32(payload) != crc:
            return
        yield payload, end
        off = end


# ---------------------------------------------------------------------------
# the log
# ---------------------------------------------------------------------------

class CDCLog:
    """Durable, segmented, cursor-addressable change log.

    Thread-safe; ``append`` is cheap enough to sit on the commit path as
    a :meth:`ChangeCapture.add_sink` sink (one vectorized encode + one
    buffered write + flush). Segment size must be a power of two
    (``storage.cdc.segment-records``) so cursor->segment arithmetic is a
    shift, the pow2 discipline of the sharded planner.
    """

    def __init__(
        self,
        dir_path: str,
        segment_records: int = 1024,
        retention_segments: int = 64,
        fault_plan=None,
    ):
        if segment_records <= 0 or segment_records & (segment_records - 1):
            raise ValueError("segment_records must be a power of two")
        self.dir = str(dir_path)
        self.segment_records = int(segment_records)
        self.retention_segments = max(1, int(retention_segments))
        self.fault_plan = fault_plan
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)

        #: sealed-segment metadata rows (manifest mirror)
        self._segments: List[dict] = []
        #: cursors below this are unservable (pruned or lost)
        self._gap_through = 0
        #: max epoch among unservable records (a bootstrap checkpoint
        #: must clear this epoch before replay can take over)
        self._gap_epoch = -1
        #: first cursor of the tail (== end of the sealed range)
        self._sealed_through = 0
        #: in-memory tail: (cursor, epoch, batch-or-None) + raw frames
        self._tail: List[Tuple[int, int, Optional[dict]]] = []
        self._tail_raw: List[bytes] = []
        self._tail_file = None
        self._crashed = False
        self._recover()

    # ------------------------------------------------------------- recovery
    def _recover(self) -> None:
        from janusgraph_tpu.observability import registry

        body = self._read_manifest()
        if body is not None:
            self._gap_through = int(body.get("pruned_through_cursor", 0))
            self._gap_epoch = int(body.get("pruned_last_epoch", -1))
            self._sealed_through = self._gap_through
            for row in body.get("segments", []):
                path = os.path.join(self.dir, row["name"])
                if not os.path.exists(path):
                    # a listed segment is gone: everything through its
                    # end is lost — honest gap, never a silent skip
                    self._segments = []
                    self._gap_through = (
                        int(row["first_cursor"]) + int(row["records"])
                    )
                    self._gap_epoch = max(
                        self._gap_epoch, int(row["last_epoch"])
                    )
                    self._sealed_through = self._gap_through
                    registry.counter("cdc.segments_lost").inc()
                    continue
                self._segments.append(dict(row))
                self._sealed_through = (
                    int(row["first_cursor"]) + int(row["records"])
                )
        # tail scan: keep the intact prefix, drop the torn suffix
        tail_path = os.path.join(self.dir, TAIL_NAME)
        good_end = 0
        if os.path.exists(tail_path):
            with open(tail_path, "rb") as f:
                data = f.read()
            cursor = self._sealed_through
            for payload, end in _iter_frames(data):
                try:
                    epoch, batch = decode_batch(payload)
                except Exception:  # torn mid-frame body
                    break
                self._tail.append((cursor, epoch, batch))
                self._tail_raw.append(_frame(payload))
                cursor += 1
                good_end = end
            if good_end < len(data):
                registry.counter("cdc.torn_frames_dropped").inc()
                with open(tail_path, "r+b") as f:
                    f.truncate(good_end)
        self._tail_file = open(tail_path, "ab")

    def _read_manifest(self) -> Optional[dict]:
        mpath = os.path.join(self.dir, MANIFEST_NAME)
        for candidate in (mpath, mpath + ".prev"):
            try:
                with open(candidate, "r", encoding="utf-8") as f:
                    body = json.load(f)
            except (OSError, ValueError):
                continue
            if body.get("kind") != _LOG_KIND:
                continue
            if body.get("digest") != _manifest_digest(body):
                continue
            return body
        return None

    def _write_manifest(self) -> None:
        body = {
            "kind": _LOG_KIND,
            "version": _VERSION,
            "segments": [dict(s) for s in self._segments],
            "pruned_through_cursor": self._gap_through,
            "pruned_last_epoch": self._gap_epoch,
        }
        body["digest"] = _manifest_digest(body)
        path = os.path.join(self.dir, MANIFEST_NAME)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".json.tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(body, f)
            if os.path.exists(path):
                os.replace(path, path + ".prev")
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # ------------------------------------------------------------- write side
    @property
    def cursor(self) -> int:
        """Next cursor to be assigned (== records ever appended)."""
        with self._lock:
            return self._sealed_through + len(self._tail)

    @property
    def base_cursor(self) -> int:
        """Smallest replayable cursor."""
        with self._lock:
            return self._gap_through

    def head_cursor(self) -> int:
        """Alias of :attr:`cursor` under the pull-source interface
        (CDCReader implements the same trio: replay_from /
        cursor_for_epoch / head_cursor)."""
        return self.cursor

    def append(self, epoch: int, batch: Optional[dict]) -> int:
        """Durably append one capture batch (``None`` = poison marker).
        Returns the record's cursor. The ChangeCapture sink signature."""
        from janusgraph_tpu.observability import registry

        payload = encode_batch(epoch, batch)
        frame = _frame(payload)
        with self._lock:
            if self._crashed:
                raise CDCTornWrite("cdc log crashed; reopen to recover")
            plan = self.fault_plan
            if plan is not None and plan.cdc_torn_write():
                # seeded torn write: a partial frame hits the platter and
                # the process 'dies' — recovery must drop exactly this
                self._tail_file.write(frame[: max(1, len(frame) // 2)])
                self._tail_file.flush()
                self._crashed = True
                raise CDCTornWrite("injected torn cdc tail write")
            cur = self._sealed_through + len(self._tail)
            self._tail_file.write(frame)
            self._tail_file.flush()
            self._tail.append((cur, int(epoch), batch))
            self._tail_raw.append(frame)
            registry.counter("cdc.appends").inc()
            if len(self._tail) >= self.segment_records:
                self._seal_locked()
            return cur

    def seal(self) -> None:
        """Seal the current tail into a durable segment (no-op when the
        tail is empty). Normally automatic at the pow2 boundary."""
        with self._lock:
            if self._tail:
                self._seal_locked()

    def _seal_locked(self) -> None:
        from janusgraph_tpu.observability import flight_recorder, registry

        payload = b"".join(self._tail_raw)
        epochs = [e for _, e, _ in self._tail]
        seq = (
            int(self._segments[-1]["seq"]) + 1 if self._segments else 0
        )
        name = _segment_name(seq)
        row = {
            "seq": seq,
            "name": name,
            "records": len(self._tail),
            "first_cursor": self._sealed_through,
            "first_epoch": min(epochs),
            "last_epoch": max(epochs),
            "digest": hashlib.sha256(payload).hexdigest(),
        }
        head = _SEG_HEAD.pack(
            _SEG_MAGIC,
            row["records"],
            row["first_cursor"],
            row["first_epoch"],
            row["last_epoch"],
            hashlib.sha256(payload).digest(),
        )
        path = os.path.join(self.dir, name)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".segment.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(head)
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self._segments.append(row)
        self._sealed_through += len(self._tail)
        # retention: prune oldest sealed segments past the budget; the
        # pruned range becomes an honest cursor gap
        while len(self._segments) > self.retention_segments:
            old = self._segments.pop(0)
            self._gap_through = (
                int(old["first_cursor"]) + int(old["records"])
            )
            self._gap_epoch = max(self._gap_epoch, int(old["last_epoch"]))
            try:
                os.unlink(os.path.join(self.dir, old["name"]))
            except OSError:
                pass
            registry.counter("cdc.segments_pruned").inc()
        self._write_manifest()
        # truncate the tail: its frames now live in the sealed segment
        self._tail_file.close()
        tail_path = os.path.join(self.dir, TAIL_NAME)
        with open(tail_path, "wb"):
            pass
        self._tail_file = open(tail_path, "ab")
        self._tail = []
        self._tail_raw = []
        registry.counter("cdc.seals").inc()
        flight_recorder.record(
            "cdc_seal",
            seq=seq,
            records=row["records"],
            first_cursor=row["first_cursor"],
            first_epoch=row["first_epoch"],
            last_epoch=row["last_epoch"],
        )

    # ------------------------------------------------------------- read side
    def _read_segment(self, row: dict) -> Optional[List[Tuple[int, int, Optional[dict]]]]:
        """Decode one sealed segment (digest-verified). None = corrupt."""
        path = os.path.join(self.dir, row["name"])
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if len(data) < _SEG_HEAD.size:
            return None
        magic, records, first_cursor, _fe, _le, digest = (
            _SEG_HEAD.unpack_from(data)
        )
        payload = data[_SEG_HEAD.size:]
        if (
            magic != _SEG_MAGIC
            or hashlib.sha256(payload).digest() != digest
        ):
            return None
        out: List[Tuple[int, int, Optional[dict]]] = []
        cursor = int(first_cursor)
        for frame_payload, _end in _iter_frames(payload):
            try:
                epoch, batch = decode_batch(frame_payload)
            except Exception:
                return None
            out.append((cursor, epoch, batch))
            cursor += 1
        if len(out) != int(records):
            return None
        return out

    def replay_from(
        self, cursor: int
    ) -> Optional[Tuple[List[Tuple[int, dict]], int]]:
        """Every (epoch, batch) at or past ``cursor`` in append order,
        plus the next cursor. ``None`` = unservable (pruned gap, poison
        in range, corrupt segment, or a future cursor): the caller must
        re-bootstrap from a checkpoint past :attr:`gap_epoch`.

        Replay is idempotent — the same cursor always yields the same
        records — and folding the records through ``DeltaOverlay.
        from_batches`` + ``materialize`` is bitwise-equivalent to a
        fresh scan at the final epoch (tests/test_cdc.py)."""
        from janusgraph_tpu.observability import flight_recorder, registry

        cursor = int(cursor)
        with self._lock:
            next_cursor = self._sealed_through + len(self._tail)
            if cursor < self._gap_through or cursor > next_cursor:
                registry.counter("cdc.replay_gaps").inc()
                flight_recorder.record(
                    "cdc_replay", action="gap", cursor=cursor,
                    base=self._gap_through, next=next_cursor,
                )
                return None
            out: List[Tuple[int, dict]] = []
            for row in self._segments:
                end = int(row["first_cursor"]) + int(row["records"])
                if end <= cursor:
                    continue
                frames = self._read_segment(row)
                if frames is None:
                    registry.counter("cdc.replay_gaps").inc()
                    flight_recorder.record(
                        "cdc_replay", action="corrupt",
                        cursor=cursor, seq=row["seq"],
                    )
                    return None
                for c, epoch, batch in frames:
                    if c < cursor:
                        continue
                    if batch is None:
                        registry.counter("cdc.replay_poisoned").inc()
                        flight_recorder.record(
                            "cdc_replay", action="poison",
                            cursor=c, epoch=epoch,
                        )
                        return None
                    out.append((epoch, batch))
            for c, epoch, batch in self._tail:
                if c < cursor:
                    continue
                if batch is None:
                    registry.counter("cdc.replay_poisoned").inc()
                    flight_recorder.record(
                        "cdc_replay", action="poison",
                        cursor=c, epoch=epoch,
                    )
                    return None
                out.append((epoch, batch))
            registry.counter("cdc.replays").inc()
            flight_recorder.record(
                "cdc_replay", action="serve", cursor=cursor,
                records=len(out), next=next_cursor,
            )
            return out, next_cursor

    def cursor_for_epoch(self, epoch: int) -> Optional[int]:
        """Smallest cursor whose replay covers every record with epoch
        past ``epoch`` — the bootstrap anchor for a follower joining
        from a checkpoint at that epoch. ``None`` when records past the
        epoch were pruned/poisoned away (bootstrap checkpoint too old)."""
        epoch = int(epoch)
        with self._lock:
            if epoch < self._gap_epoch:
                return None
            cursor = self._gap_through
            for row in self._segments:
                if int(row["last_epoch"]) <= epoch:
                    cursor = int(row["first_cursor"]) + int(row["records"])
                    continue
                frames = self._read_segment(row)
                if frames is None:
                    return None
                for c, e, _b in frames:
                    if e <= epoch:
                        cursor = c + 1
                return cursor
            for c, e, _b in self._tail:
                if e <= epoch:
                    cursor = c + 1
            return cursor

    @property
    def gap_epoch(self) -> int:
        """Max epoch among unservable (pruned/lost) records; a bootstrap
        checkpoint must be at an epoch >= this to hand off to replay."""
        with self._lock:
            return self._gap_epoch

    def last_epoch(self) -> int:
        """Epoch of the newest durable record (-1 when empty)."""
        with self._lock:
            if self._tail:
                return self._tail[-1][1]
            if self._segments:
                return int(self._segments[-1]["last_epoch"])
            return self._gap_epoch

    def stats(self) -> dict:
        with self._lock:
            return {
                "cursor": self._sealed_through + len(self._tail),
                "base_cursor": self._gap_through,
                "sealed_segments": len(self._segments),
                "tail_records": len(self._tail),
                "last_epoch": (
                    self._tail[-1][1] if self._tail
                    else int(self._segments[-1]["last_epoch"])
                    if self._segments else self._gap_epoch
                ),
            }

    def close(self) -> None:
        with self._lock:
            if self._tail_file is not None:
                self._tail_file.close()
                self._tail_file = None
                self._crashed = True


# ---------------------------------------------------------------------------
# read-only view (the follower pull plane)
# ---------------------------------------------------------------------------

class CDCReader:
    """Read-only view of a (possibly live) CDC directory — the follower
    pull plane when replicas share a filesystem. Never mutates: no tail
    truncation, no file handles held; a torn tail frame simply ends the
    scan. Every call re-reads the manifest, and re-checks it after the
    tail read — if a seal landed in between (the manifest moved), the
    read retries so tail cursors never bind to a stale sealed range.

    Implements the same pull-source trio as :class:`CDCLog`
    (``replay_from`` / ``cursor_for_epoch`` / ``head_cursor``), so
    :class:`~janusgraph_tpu.server.fleet.CDCFollower` takes either."""

    _RETRIES = 3

    def __init__(self, dir_path: str):
        self.dir = str(dir_path)

    def _manifest_body(self) -> Optional[dict]:
        mpath = os.path.join(self.dir, MANIFEST_NAME)
        for candidate in (mpath, mpath + ".prev"):
            try:
                with open(candidate, "r", encoding="utf-8") as f:
                    body = json.load(f)
            except (OSError, ValueError):
                continue
            if body.get("kind") != _LOG_KIND:
                continue
            if body.get("digest") != _manifest_digest(body):
                continue
            return body
        return None

    def _snapshot(self):
        """One consistent (segments, gap_through, gap_epoch, tail
        records) view, retried across concurrent seals. Tail records are
        (cursor, epoch, batch-or-None) like the writer's."""
        for _ in range(self._RETRIES):
            body = self._manifest_body() or {}
            segments = list(body.get("segments", []))
            gap_through = int(body.get("pruned_through_cursor", 0))
            gap_epoch = int(body.get("pruned_last_epoch", -1))
            sealed_through = gap_through
            for row in segments:
                sealed_through = (
                    int(row["first_cursor"]) + int(row["records"])
                )
            tail: List[Tuple[int, int, Optional[dict]]] = []
            tail_path = os.path.join(self.dir, TAIL_NAME)
            data = b""
            try:
                with open(tail_path, "rb") as f:
                    data = f.read()
            except OSError:
                pass
            cursor = sealed_through
            torn = False
            for payload, _end in _iter_frames(data):
                try:
                    epoch, batch = decode_batch(payload)
                except Exception:
                    torn = True
                    break
                tail.append((cursor, epoch, batch))
                cursor += 1
            # a seal between the manifest read and the tail read would
            # re-base the tail: verify the manifest did not move
            body2 = self._manifest_body() or {}
            if len(body2.get("segments", [])) == len(segments) and int(
                body2.get("pruned_through_cursor", 0)
            ) == gap_through:
                _ = torn  # a torn suffix just ends the durable range
                return segments, gap_through, gap_epoch, tail
        return segments, gap_through, gap_epoch, tail

    def head_cursor(self) -> int:
        segments, gap_through, _ge, tail = self._snapshot()
        if tail:
            return tail[-1][0] + 1
        if segments:
            last = segments[-1]
            return int(last["first_cursor"]) + int(last["records"])
        return gap_through

    def _read_segment(self, row: dict):
        path = os.path.join(self.dir, row["name"])
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if len(data) < _SEG_HEAD.size:
            return None
        magic, records, first_cursor, _fe, _le, digest = (
            _SEG_HEAD.unpack_from(data)
        )
        payload = data[_SEG_HEAD.size:]
        if (
            magic != _SEG_MAGIC
            or hashlib.sha256(payload).digest() != digest
        ):
            return None
        out = []
        cursor = int(first_cursor)
        for frame_payload, _end in _iter_frames(payload):
            try:
                epoch, batch = decode_batch(frame_payload)
            except Exception:
                return None
            out.append((cursor, epoch, batch))
            cursor += 1
        return out if len(out) == int(records) else None

    def replay_from(
        self, cursor: int
    ) -> Optional[Tuple[List[Tuple[int, dict]], int]]:
        """Same contract as :meth:`CDCLog.replay_from`."""
        from janusgraph_tpu.observability import registry

        cursor = int(cursor)
        segments, gap_through, _gap_epoch, tail = self._snapshot()
        next_cursor = (
            tail[-1][0] + 1 if tail
            else (
                int(segments[-1]["first_cursor"])
                + int(segments[-1]["records"])
            ) if segments else gap_through
        )
        if cursor < gap_through or cursor > next_cursor:
            registry.counter("cdc.replay_gaps").inc()
            return None
        out: List[Tuple[int, dict]] = []
        for row in segments:
            end = int(row["first_cursor"]) + int(row["records"])
            if end <= cursor:
                continue
            frames = self._read_segment(row)
            if frames is None:
                registry.counter("cdc.replay_gaps").inc()
                return None
            for c, epoch, batch in frames:
                if c < cursor:
                    continue
                if batch is None:
                    registry.counter("cdc.replay_poisoned").inc()
                    return None
                out.append((epoch, batch))
        for c, epoch, batch in tail:
            if c < cursor:
                continue
            if batch is None:
                registry.counter("cdc.replay_poisoned").inc()
                return None
            out.append((epoch, batch))
        registry.counter("cdc.replays").inc()
        return out, next_cursor

    def cursor_for_epoch(self, epoch: int) -> Optional[int]:
        """Same contract as :meth:`CDCLog.cursor_for_epoch`."""
        epoch = int(epoch)
        segments, gap_through, gap_epoch, tail = self._snapshot()
        if epoch < gap_epoch:
            return None
        cursor = gap_through
        for row in segments:
            if int(row["last_epoch"]) <= epoch:
                cursor = int(row["first_cursor"]) + int(row["records"])
                continue
            frames = self._read_segment(row)
            if frames is None:
                return None
            for c, e, _b in frames:
                if e <= epoch:
                    cursor = c + 1
            return cursor
        for c, e, _b in tail:
            if e <= epoch:
                cursor = c + 1
        return cursor


class LeaderCDCState:
    """The leader-side /healthz ``cdc`` block: role + durable cursor
    frontier (a leader is never stale relative to itself)."""

    role = "leader"

    def __init__(self, log: CDCLog):
        self.log = log

    def healthz_block(self) -> dict:
        s = self.log.stats()
        return {
            "role": "leader",
            "cursor": s["cursor"],
            "lag_records": 0,
            "last_applied_epoch": s["last_epoch"],
            "staleness_s": 0.0,
            "sealed_segments": s["sealed_segments"],
            "degraded": False,
        }
