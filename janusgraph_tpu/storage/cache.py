"""Store-level slice cache with expiration and write-through invalidation.

Capability parity with the reference's two-level caching
(reference: diskstorage/keycolumnvalue/cache/ExpirationKCVSCache.java:225,
KCVSCache.java:82): an LRU of slice results keyed by (row key, slice),
invalidated per row on mutation, with a TTL for cross-instance staleness
bounds. Wraps any KeyColumnValueStore transparently.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, Optional, Sequence, Tuple

from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KeyColumnValueStore,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)


class CacheMetrics:
    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.invalidations = 0


class ExpirationCacheStore(KeyColumnValueStore):
    """LRU slice cache wrapper. Thread-safe; snapshot semantics inherited
    from the underlying store."""

    def __init__(
        self,
        store: KeyColumnValueStore,
        max_entries: int = 65536,
        ttl_seconds: Optional[float] = None,
        clean_wait_seconds: float = 0.0,
    ):
        self._store = store
        self._max = max_entries
        self._ttl = ttl_seconds
        # cache.db-cache-clean-wait-ms: after a row invalidation, refuse to
        # re-admit that row for this long — an eventually-consistent backend
        # may still be propagating the write that invalidated it
        # (reference: ExpirationKCVSCache.java penaltyCountdown)
        self._clean_wait = clean_wait_seconds
        self._dirty_rows: Dict[bytes, float] = {}
        self._lock = threading.Lock()
        # (key, slice) -> (entries, inserted_at)
        self._cache: "OrderedDict[Tuple[bytes, SliceQuery], Tuple[EntryList, float]]" = (
            OrderedDict()
        )
        # row key -> set of cached slice keys, for O(row) invalidation
        self._by_row: Dict[bytes, set] = {}
        # bumped on every invalidation; a fetch started before a concurrent
        # invalidation must not populate the cache with its (stale) result
        self._generation = 0
        self.metrics = CacheMetrics()

    @property
    def name(self) -> str:
        return self._store.name

    @property
    def wrapped(self) -> KeyColumnValueStore:
        return self._store

    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        ck = (query.key, query.slice)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(ck)
            if hit is not None:
                entries, at = hit
                if self._ttl is None or now - at < self._ttl:
                    self._cache.move_to_end(ck)
                    self.metrics.hits += 1
                    return list(entries)
                self._evict(ck)
            self.metrics.misses += 1
            gen = self._generation
        entries = self._store.get_slice(query, txh)
        with self._lock:
            if self._generation != gen:
                # a row was invalidated during the unlocked fetch; our result
                # may predate the write — serve it but don't cache it
                return list(entries)
            if self._clean_wait > 0:
                dirty_at = self._dirty_rows.get(query.key)
                if dirty_at is not None:
                    if time.monotonic() - dirty_at < self._clean_wait:
                        return list(entries)  # within the clean-wait window
                    del self._dirty_rows[query.key]
            self._cache[ck] = (entries, now)
            self._by_row.setdefault(query.key, set()).add(ck)
            while len(self._cache) > self._max:
                old, _ = self._cache.popitem(last=False)
                rowset = self._by_row.get(old[0])
                if rowset is not None:
                    rowset.discard(old)
                    if not rowset:
                        del self._by_row[old[0]]
        return list(entries)

    def get_slice_multi(self, keys, slice_query, txh):
        return {k: self.get_slice(KeySliceQuery(k, slice_query), txh) for k in keys}

    def mutate(
        self,
        key: bytes,
        additions: EntryList,
        deletions: Sequence[bytes],
        txh: StoreTransaction,
    ) -> None:
        self._store.mutate(key, additions, deletions, txh)
        self.invalidate(key)

    def invalidate(self, key: bytes) -> None:
        with self._lock:
            self._generation += 1
            if self._clean_wait > 0:
                now = time.monotonic()
                self._dirty_rows[key] = now
                # amortized prune: rows written but never re-read would
                # otherwise accumulate for the process lifetime
                if len(self._dirty_rows) > max(1024, 2 * self._max):
                    self._dirty_rows = {
                        k: at for k, at in self._dirty_rows.items()
                        if now - at < self._clean_wait
                    }
            for ck in self._by_row.pop(key, ()):  # all slices of this row
                self._cache.pop(ck, None)
                self.metrics.invalidations += 1

    def invalidate_all(self) -> None:
        """Drop every cached slice (cross-instance schema changes)."""
        with self._lock:
            self._generation += 1
            self.metrics.invalidations += len(self._cache)
            self._cache.clear()
            self._by_row.clear()

    def _evict(self, ck) -> None:
        self._cache.pop(ck, None)
        rowset = self._by_row.get(ck[0])
        if rowset is not None:
            rowset.discard(ck)
            if not rowset:
                del self._by_row[ck[0]]

    def get_keys(self, query, txh: StoreTransaction) -> Iterator[Tuple[bytes, EntryList]]:
        # scans bypass the cache (reference does the same: scans are OLAP)
        return self._store.get_keys(query, txh)

    def acquire_lock(self, key, column, expected_value, txh):
        return self._store.acquire_lock(key, column, expected_value, txh)

    def close(self) -> None:
        with self._lock:
            self._cache.clear()
            self._by_row.clear()
        self._store.close()
