"""Retrying backend-operation wrapper.

Capability parity with the reference's universal backend-call guard
(reference: diskstorage/util/BackendOperation.java — every storage call is
wrapped in execute(), which retries TemporaryBackendExceptions with
exponential backoff up to a time budget and lets PermanentBackendExceptions
fail fast). Used by the remote store client; available to any caller
touching a backend that can flake (network partitions, failing shards).
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

from janusgraph_tpu.exceptions import (
    PermanentBackendError,
    TemporaryBackendError,
)

T = TypeVar("T")


def execute(
    op: Callable[[], T],
    max_time_s: float = 10.0,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
) -> T:
    """Run `op`, replaying temporary failures with exponential backoff until
    the time budget is spent; the last temporary error is then re-raised.
    Permanent failures propagate immediately (reference:
    BackendOperation.executeDirect semantics)."""
    deadline = time.monotonic() + max_time_s
    delay = base_delay_s
    attempt = 0
    while True:
        try:
            return op()
        except PermanentBackendError:
            raise
        except TemporaryBackendError:
            attempt += 1
            now = time.monotonic()
            if now >= deadline:
                raise
            time.sleep(min(delay, max_delay_s, max(0.0, deadline - now)))
            delay *= 2
