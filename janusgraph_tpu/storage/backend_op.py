"""Retrying backend-operation wrapper.

Capability parity with the reference's universal backend-call guard
(reference: diskstorage/util/BackendOperation.java — every storage call is
wrapped in execute(), which retries TemporaryBackendExceptions with
exponential backoff up to a time budget and lets PermanentBackendExceptions
fail fast). Used by the remote store client, the remote index provider, and
the buffered backend transaction's read/flush paths; available to any
caller touching a backend that can flake (network partitions, failing
shards, injected chaos).

Backoff shape: exponential base with DECORRELATED JITTER — each delay is
drawn uniformly from [base, prev * 3], capped at the ceiling. Pure
exponential backoff synchronizes every client that failed at the same
instant into retry convoys that re-stampede the recovering backend on the
same schedule (the thundering herd); decorrelated jitter spreads them.

Telemetry: ``storage.backend_op.retries`` counts every replayed attempt,
``storage.backend_op.exhausted`` every guard that gave up (budget or
attempt cap spent) — the recovered-vs-lost split the chaos engine asserts
on.

Deadline awareness (core/deadline.py): when the ambient request deadline
is spent, the guard raises ``DeadlineExceededError`` BEFORE dispatching
the operation (zero attempts, zero retries, and the circuit breaker —
which wraps the op inside this guard — never sees the aborted call), and
it stops replaying temporary failures the moment the deadline expires
mid-backoff. This is what keeps a saturated serving path from turning
client timeouts into storage-layer retry storms: the caller gave up, so
every layer below gives up too. ``storage.backend_op.deadline_expired``
counts the refusals.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from janusgraph_tpu.exceptions import (
    DeadlineExceededError,
    PermanentBackendError,
    TemporaryBackendError,
)

T = TypeVar("T")

#: default backoff shape; per-client overrides come in as execute()
#: arguments (storage.backoff-base-ms / storage.backoff-max-ms are wired
#: per CLIENT — RemoteStoreManager/RemoteIndexProvider — not process-wide:
#: two graphs in one process must not clobber each other's tuning)
BASE_DELAY_S = 0.05
MAX_DELAY_S = 2.0


def check_deadline(stage: str = "dispatch") -> None:
    """Refuse NOW when the ambient caller deadline is already spent:
    counts ``storage.backend_op.deadline_expired`` and raises
    ``DeadlineExceededError`` (permanent — never replayed). Shared by
    ``execute``'s pre-dispatch check and the pipelined wire path's
    pre-send check (storage/pipeline.py), so every layer refuses dead
    work with the same counter and the same taxonomy."""
    import time as _time

    from janusgraph_tpu.core import deadline as _deadline
    from janusgraph_tpu.observability import registry

    caller_dl = _deadline.current_deadline()
    if caller_dl is not None and _time.monotonic() >= caller_dl:
        registry.counter("storage.backend_op.deadline_expired").inc()
        raise DeadlineExceededError(
            f"caller deadline spent before {stage} "
            "(no storage dispatch performed)"
        )


def execute(
    op: Callable[[], T],
    max_time_s: float = 10.0,
    base_delay_s: Optional[float] = None,
    max_delay_s: Optional[float] = None,
    max_attempts: int = 0,
) -> T:
    """Run `op`, replaying temporary failures with jittered exponential
    backoff until the time budget is spent; the last temporary error is
    then re-raised. Permanent failures propagate immediately (reference:
    BackendOperation.executeDirect semantics). `max_attempts` (> 0) caps
    the replay COUNT as well as the time budget — whichever trips first
    (reference: storage.write-attempts / read-attempts)."""
    from janusgraph_tpu.core import deadline as _deadline
    from janusgraph_tpu.observability import registry

    deadline = time.monotonic() + max_time_s
    # the ambient request deadline (propagated from the caller, possibly
    # across the wire) caps the retry budget too: whichever is tighter
    caller_dl = _deadline.current_deadline()
    if caller_dl is not None:
        deadline = min(deadline, caller_dl)
    base = BASE_DELAY_S if base_delay_s is None else base_delay_s
    if max_delay_s is None:
        max_delay_s = MAX_DELAY_S
    delay = base
    attempt = 0
    while True:
        # refuse BEFORE dispatch: no attempt, no retry, and the breaker
        # (wrapped inside `op`) never counts the abort
        check_deadline(stage=f"attempt {attempt + 1}")
        try:
            return op()
        except PermanentBackendError:
            raise
        except TemporaryBackendError as e:
            attempt += 1
            now = time.monotonic()
            if caller_dl is not None and now >= caller_dl:
                # the deadline (not the retry budget) ran out mid-replay:
                # surface THAT, permanently — more backoff cannot help a
                # caller who already gave up
                registry.counter(
                    "storage.backend_op.deadline_expired"
                ).inc()
                raise DeadlineExceededError(
                    f"caller deadline spent after {attempt} attempt(s); "
                    f"last temporary error: {e}"
                ) from e
            if now >= deadline or (max_attempts and attempt >= max_attempts):
                registry.counter("storage.backend_op.exhausted").inc()
                from janusgraph_tpu.observability import (
                    flight_recorder,
                    get_logger,
                )

                # a guard giving up is a salient incident event (absorbed
                # retries are just counters; exhaustion loses work)
                flight_recorder.record(
                    "retry_exhausted",
                    attempts=attempt, error=type(e).__name__,
                    message=str(e)[:200],
                )
                get_logger("storage.backend_op").warning(
                    "retry-exhausted",
                    attempts=attempt, error=type(e).__name__,
                    message=str(e)[:200],
                )
                raise
            registry.counter("storage.backend_op.retries").inc()
            from janusgraph_tpu.observability.profiler import accrue

            # replayed attempts are a per-query cost too: the ledger
            # attributes retry burn to the query that paid it
            accrue(retries=1)
            time.sleep(min(delay, max_delay_s, max(0.0, deadline - now)))
            # decorrelated jitter (not part of the fault-plan determinism
            # contract: fault DECISIONS are hash-scheduled, only the retry
            # pacing is randomized)
            delay = min(max_delay_s, random.uniform(base, delay * 3))
