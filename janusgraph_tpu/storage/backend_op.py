"""Retrying backend-operation wrapper.

Capability parity with the reference's universal backend-call guard
(reference: diskstorage/util/BackendOperation.java — every storage call is
wrapped in execute(), which retries TemporaryBackendExceptions with
exponential backoff up to a time budget and lets PermanentBackendExceptions
fail fast). Used by the remote store client; available to any caller
touching a backend that can flake (network partitions, failing shards).
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

from janusgraph_tpu.exceptions import (
    PermanentBackendError,
    TemporaryBackendError,
)

T = TypeVar("T")

#: default backoff shape; per-client overrides come in as execute()
#: arguments (storage.backoff-base-ms / storage.backoff-max-ms are wired
#: per CLIENT — RemoteStoreManager/RemoteIndexProvider — not process-wide:
#: two graphs in one process must not clobber each other's tuning)
BASE_DELAY_S = 0.05
MAX_DELAY_S = 2.0


def execute(
    op: Callable[[], T],
    max_time_s: float = 10.0,
    base_delay_s: float = None,
    max_delay_s: float = None,
    max_attempts: int = 0,
) -> T:
    """Run `op`, replaying temporary failures with exponential backoff until
    the time budget is spent; the last temporary error is then re-raised.
    Permanent failures propagate immediately (reference:
    BackendOperation.executeDirect semantics). `max_attempts` (> 0) caps
    the replay COUNT as well as the time budget — whichever trips first
    (reference: storage.write-attempts / read-attempts)."""
    deadline = time.monotonic() + max_time_s
    delay = BASE_DELAY_S if base_delay_s is None else base_delay_s
    if max_delay_s is None:
        max_delay_s = MAX_DELAY_S
    attempt = 0
    while True:
        try:
            return op()
        except PermanentBackendError:
            raise
        except TemporaryBackendError:
            attempt += 1
            now = time.monotonic()
            if now >= deadline or (max_attempts and attempt >= max_attempts):
                raise
            time.sleep(min(delay, max_delay_s, max(0.0, deadline - now)))
            delay *= 2
