"""Backend orchestration: opens the named stores, wires caches and the ID
authority, and builds buffered backend transactions.

Capability parity with the reference's orchestrator
(reference: diskstorage/Backend.java:80 — opens edgestore/graphindex/
janusgraph_ids/system_properties and wraps caches; BackendTransaction.java —
multiplexes per-store operations; CacheTransaction.java:217 — buffers
mutations and flushes in batches).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional, Sequence

from janusgraph_tpu.exceptions import PermanentBackendError
from janusgraph_tpu.storage.cache import ExpirationCacheStore
from janusgraph_tpu.storage.idauthority import (
    ConflictAvoidanceMode,
    ConsistentKeyIDAuthority,
    ID_STORE_NAME,
)
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStoreManager,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)

EDGESTORE_NAME = "edgestore"
INDEXSTORE_NAME = "graphindex"
SYSTEM_PROPERTIES_NAME = "system_properties"
TXLOG_NAME = "txlog"
SYSTEMLOG_NAME = "systemlog"
LOCK_STORE_SUFFIX = "_lock_"


class GlobalConfigStore:
    """Cluster-global config access over the ``system_properties`` store,
    usable BEFORE the full Backend is built — the reference likewise opens
    the backend temporarily to merge KCVS-stored global config at open
    (reference: GraphDatabaseConfigurationBuilder.java:41,
    KCVSConfiguration)."""

    _CONFIG_KEY = b"\x00config"

    def __init__(
        self, manager: KeyColumnValueStoreManager, read_only: bool = False,
    ):
        self._store = manager.open_database(SYSTEM_PROPERTIES_NAME)
        self._tx = manager.begin_transaction()
        #: storage.read-only: global-config/instance-registry writes refuse
        self.read_only = read_only

    def _check_writable(self) -> None:
        if self.read_only:
            raise PermanentBackendError(
                "storage.read-only: global config writes refused"
            )

    def set_global_config(self, name: str, value: bytes) -> None:
        self._check_writable()
        self._store.mutate(
            self._CONFIG_KEY, [(name.encode(), value)], [], self._tx
        )

    def get_global_config(self, name: str) -> Optional[bytes]:
        col = name.encode()
        entries = self._store.get_slice(
            KeySliceQuery(self._CONFIG_KEY, SliceQuery(col, col + b"\x00")),
            self._tx,
        )
        return entries[0][1] if entries else None

    def del_global_config(self, name: str) -> None:
        self._check_writable()
        self._store.mutate(self._CONFIG_KEY, [], [name.encode()], self._tx)

    def list_global_config(self, prefix: str = "") -> List[str]:
        p = prefix.encode()
        end = (p + b"\xff") if p else None
        entries = self._store.get_slice(
            KeySliceQuery(self._CONFIG_KEY, SliceQuery(p or None, end)),
            self._tx,
        )
        return [col.decode() for col, _ in entries]


class Backend:
    """Owns the store manager and the named stores of one graph."""

    def __init__(
        self,
        manager: KeyColumnValueStoreManager,
        cache_enabled: bool = True,
        cache_size: int = 65536,
        id_block_size: int = 10_000,
        id_conflict_mode: str = "none",
        id_conflict_tag: int = 0,
        id_conflict_tag_bits: int = 4,
        id_max_retries: int = 20,
        cache_ttl_seconds: Optional[float] = 10.0,
        cache_clean_wait_seconds: float = 0.0,
        metrics_enabled: bool = False,
        metrics_merge_stores: bool = False,
        edgestore_cache_fraction: float = 0.8,
        read_only: bool = False,
        retry_time_s: float = 10.0,
        backoff_base_s: Optional[float] = None,
        backoff_max_s: Optional[float] = None,
        retry_attempts: int = 0,
    ):
        self.manager = manager
        self.metrics_enabled = metrics_enabled
        #: storage.read-only: every mutation through this backend raises
        self.read_only = read_only
        #: universal retry-guard shape for this backend's read/flush paths
        #: (storage.retry-time-ms / backoff-base-ms / backoff-max-ms /
        #: write-attempts) — every BackendTransaction operation replays
        #: TemporaryBackendErrors through backend_op.execute, so a flaking
        #: store (or the chaos injector) is absorbed below the tx layer
        self.retry_time_s = retry_time_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.retry_attempts = retry_attempts
        self._base_tx = manager.begin_transaction()
        edgestore = manager.open_database(EDGESTORE_NAME)
        indexstore = manager.open_database(INDEXSTORE_NAME)
        if metrics_enabled:
            # instrument BEFORE the cache layer so cache hits show up as the
            # gap between tx-level and store-level counts (reference:
            # Backend.java:184-188 MetricInstrumentedStore wrapping)
            from janusgraph_tpu.util.metrics import MetricInstrumentedStore

            edgestore = MetricInstrumentedStore(
                edgestore, merge_stores=metrics_merge_stores
            )
            indexstore = MetricInstrumentedStore(
                indexstore, merge_stores=metrics_merge_stores
            )
        if cache_enabled:
            # edge/index cache split like the reference's 80/20
            # (Backend.java:107; cache.edgestore-fraction); the TTL bounds
            # cross-instance staleness (cache.db-cache-time default 10s)
            f = edgestore_cache_fraction
            edgestore = ExpirationCacheStore(
                edgestore, max(1, int(cache_size * f)),
                ttl_seconds=cache_ttl_seconds,
                clean_wait_seconds=cache_clean_wait_seconds,
            )
            indexstore = ExpirationCacheStore(
                indexstore, max(1, int(cache_size * (1.0 - f))),
                ttl_seconds=cache_ttl_seconds,
                clean_wait_seconds=cache_clean_wait_seconds,
            )
        self.edgestore = edgestore
        self.indexstore = indexstore
        self.system_properties = manager.open_database(SYSTEM_PROPERTIES_NAME)
        self.global_config = GlobalConfigStore(manager, read_only=read_only)
        self.id_store = manager.open_database(ID_STORE_NAME)
        self.id_authority = ConsistentKeyIDAuthority(
            self.id_store, self._base_tx, block_size=id_block_size,
            conflict_mode=ConflictAvoidanceMode(id_conflict_mode),
            conflict_tag=id_conflict_tag,
            conflict_tag_bits=id_conflict_tag_bits,
            max_retries=id_max_retries,
            read_only=read_only,
        )
        # mutation-epoch tracker: edgestore row key -> epoch of its last
        # committed mutation (this instance). Powers incremental CSR refresh
        # (olap/csr.py refresh_csr): re-read only rows touched since a
        # snapshot instead of rescanning the store (SURVEY.md §7 hard part
        # (e) — OLTP mutations -> CSR deltas without full rebuilds).
        self._mutation_epochs: Dict[bytes, int] = {}
        self._epoch = 0
        self._epoch_lock = threading.Lock()
        #: tracker size bound — beyond it the tracker resets and records the
        #: overflow epoch; snapshots older than that must full-reload
        #: (bounds memory on write-heavy workloads that never refresh)
        self._epoch_track_limit = 1_000_000
        self._overflow_epoch = 0
        #: delta-CSR change-capture sink (register_change_capture)
        self._change_capture = None
        # consistent-key lockers over dedicated lock stores (reference:
        # Backend.java:184-213 wraps stores in ExpectedValueCheckingStore)
        from janusgraph_tpu.storage.locking import (
            ConsistentKeyLocker,
            mediator_for,
        )

        self.rid = uuid.uuid4().bytes[:8]
        mediator = mediator_for(manager)
        self.edge_locker = ConsistentKeyLocker(
            manager.open_database(EDGESTORE_NAME + LOCK_STORE_SUFFIX),
            manager.begin_transaction,
            self.rid,
            mediator,
        )
        self.index_locker = ConsistentKeyLocker(
            manager.open_database(INDEXSTORE_NAME + LOCK_STORE_SUFFIX),
            manager.begin_transaction,
            self.rid,
            mediator,
        )

    def clear_caches(self) -> None:
        """Drop all cached slices (schema-eviction broadcast handler)."""
        for store in (self.edgestore, self.indexstore):
            if isinstance(store, ExpirationCacheStore):
                store.invalidate_all()

    def configure_lockers(
        self, wait_ms: float, expiry_ms: float, retries: int,
        clean_expired: bool = False,
    ) -> None:
        for locker in (self.edge_locker, self.index_locker):
            locker.wait_ms = wait_ms
            locker.expiry_ms = expiry_ms
            locker.retries = retries
            locker.clean_expired = clean_expired

    def begin_transaction(self, config: Optional[dict] = None) -> "BackendTransaction":
        return BackendTransaction(self, self.manager.begin_transaction(config))

    # -- mutation-epoch tracking (incremental CSR refresh) ------------------
    def note_edge_mutations(self, keys, mutations=None) -> None:
        with self._epoch_lock:
            self._epoch += 1
            e = self._epoch
            for key in keys:
                self._mutation_epochs[key] = e
            if len(self._mutation_epochs) > self._epoch_track_limit:
                # reset rather than grow unboundedly; refreshes across the
                # reset fall back to a full reload
                self._mutation_epochs.clear()
                self._overflow_epoch = e
            # delta-CSR change capture (olap/delta.ChangeCapture): the
            # committed batch streams to the registered capture under the
            # epoch lock so batches land in epoch order
            if self._change_capture is not None and mutations is not None:
                self._change_capture(e, mutations)

    def mutation_epoch(self) -> int:
        """Monotonic counter bumped per committed edgestore batch; snapshot
        it alongside a CSR load, pass it to touched_since at refresh."""
        with self._epoch_lock:
            return self._epoch

    def touched_since(self, epoch: int) -> Optional[List[bytes]]:
        """Edgestore row keys mutated (by this instance) after `epoch`, or
        None when the tracker overflowed past that epoch (caller must
        full-reload)."""
        with self._epoch_lock:
            if epoch < self._overflow_epoch:
                return None
            return [k for k, e in self._mutation_epochs.items() if e > epoch]

    def touched_count_since(self, epoch: int) -> Optional[int]:
        """DISTINCT rows mutated since `epoch` — the refresh-work measure
        the staleness bound prices. The per-row epoch map already dedupes
        repeated touches of one row (within a tx via the mutation buffer,
        across txs via the epoch overwrite), so a workload hammering the
        same rows no longer inflates staleness one epoch per commit and
        forces spurious full repacks near the bound. None = overflow."""
        with self._epoch_lock:
            if epoch < self._overflow_epoch:
                return None
            return sum(1 for e in self._mutation_epochs.values() if e > epoch)

    def register_change_capture(self, callback) -> None:
        """Register the delta-CSR change-capture sink: called with
        (epoch, edgestore row mutations) for every committed batch."""
        with self._epoch_lock:
            self._change_capture = callback

    # -- global config on system_properties (reference: KCVSConfiguration) --
    def set_global_config(self, name: str, value: bytes) -> None:
        self.global_config.set_global_config(name, value)

    def get_global_config(self, name: str) -> Optional[bytes]:
        return self.global_config.get_global_config(name)

    def del_global_config(self, name: str) -> None:
        self.global_config.del_global_config(name)

    def list_global_config(self, prefix: str = "") -> List[str]:
        return self.global_config.list_global_config(prefix)

    def guard(self, op):
        """Run one backend operation under the configured retry guard
        (reference: BackendOperation.execute wrapping every storage call)."""
        from janusgraph_tpu.storage import backend_op

        return backend_op.execute(
            op,
            max_time_s=self.retry_time_s,
            base_delay_s=self.backoff_base_s,
            max_delay_s=self.backoff_max_s,
            max_attempts=self.retry_attempts,
        )

    def close(self) -> None:
        self.edgestore.close()
        self.indexstore.close()
        self.manager.close()

    def clear(self) -> None:
        self.manager.clear_storage()


class BackendTransaction:
    """Multiplexes reads over the backend stores and buffers writes until
    commit, flushing them as one batched mutate_many
    (reference: BackendTransaction.java + CacheTransaction.java)."""

    def __init__(self, backend: Backend, store_tx: StoreTransaction):
        self.backend = backend
        self.store_tx = store_tx
        self._mutations: Dict[str, Dict[bytes, KCVMutation]] = {}
        self._lock = threading.Lock()
        self._open = True
        # per-query resource accounting happens HERE for backends whose
        # manager does not account for itself (the remote KCVS client
        # counts at the wire — echo or decode — and counting again at
        # this layer would double every cell)
        self._ledger_local = not getattr(
            backend.manager, "ledger_self_accounting", False
        )

    def _accrue_read(self, entries: EntryList) -> EntryList:
        if self._ledger_local:
            from janusgraph_tpu.observability.profiler import (
                accrue,
                current_ledger,
            )

            if current_ledger() is not None:
                accrue(
                    cells_read=len(entries),
                    bytes_read=sum(len(c) + len(v) for c, v in entries),
                )
        return entries

    # ----------------------------------------------------------------- reads
    # (each read rides Backend.guard — the reference wraps EVERY storage
    # call in BackendOperation.execute; temporary failures replay with
    # jittered backoff instead of surfacing into the transaction layer)
    def edge_store_query(self, query: KeySliceQuery) -> EntryList:
        return self._accrue_read(self.backend.guard(
            lambda: self.backend.edgestore.get_slice(query, self.store_tx)
        ))

    def edge_store_multi_query(
        self, keys: Sequence[bytes], slice_query: SliceQuery
    ) -> Dict[bytes, EntryList]:
        res = self.backend.guard(
            lambda: self.backend.edgestore.get_slice_multi(
                keys, slice_query, self.store_tx
            )
        )
        self._accrue_read([e for entries in res.values() for e in entries])
        return res

    def index_query(self, query: KeySliceQuery) -> EntryList:
        return self._accrue_read(self.backend.guard(
            lambda: self.backend.indexstore.get_slice(query, self.store_tx)
        ))

    def index_query_uncached(self, query: KeySliceQuery) -> EntryList:
        """Bypass the per-instance slice cache — claim-time reads backing
        lock expectations must not see TTL-stale data."""
        store = self.backend.indexstore
        if isinstance(store, ExpirationCacheStore):
            store = store.wrapped
        return self._accrue_read(self.backend.guard(
            lambda: store.get_slice(query, self.store_tx)
        ))

    # ---------------------------------------------------------------- writes
    def _buffer(self, store: str, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        if self.backend.read_only:
            raise PermanentBackendError(
                "storage.read-only: the backend was opened read-only"
            )
        with self._lock:
            rows = self._mutations.setdefault(store, {})
            m = rows.setdefault(key, KCVMutation())
            m.merge(KCVMutation(additions=list(additions), deletions=list(deletions)))

    def mutate_edges(self, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        self._buffer(EDGESTORE_NAME, key, additions, deletions)

    def mutate_index(self, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        self._buffer(INDEXSTORE_NAME, key, additions, deletions)

    def has_mutations(self) -> bool:
        return any(
            not m.is_empty() for rows in self._mutations.values() for m in rows.values()
        )

    # ----------------------------------------------------------------- locks
    # (reference: BackendTransaction.acquireEdgeLock/acquireIndexLock →
    #  ExpectedValueCheckingStore.acquireLock)
    def acquire_edge_lock(
        self, key: bytes, column: bytes, expected=None
    ) -> None:
        from janusgraph_tpu.storage.locking import KeyColumn

        self.backend.edge_locker.write_lock(
            KeyColumn(key, column), self, expected
        )

    def acquire_index_lock(
        self, key: bytes, column: bytes, expected=None
    ) -> None:
        from janusgraph_tpu.storage.locking import KeyColumn

        self.backend.index_locker.write_lock(
            KeyColumn(key, column), self, expected
        )

    def _check_and_release_locks(self, commit: bool) -> None:
        from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

        be = self.backend
        try:
            if commit:
                for locker, store in (
                    (be.edge_locker, be.edgestore),
                    (be.index_locker, be.indexstore),
                ):
                    if not locker.held_by(self):
                        continue
                    # expected-value reads must see the real store, not a
                    # possibly-stale per-instance slice cache
                    if isinstance(store, ExpirationCacheStore):
                        store = store.wrapped
                    locker.check_locks(self)
                    locker.check_expected_values(
                        self,
                        lambda t, _s=store: be.guard(
                            lambda: _s.get_slice(
                                KeySliceQuery(
                                    t.key,
                                    SliceQuery(t.column, t.column + b"\x00"),
                                ),
                                self.store_tx,
                            )
                        ),
                    )
        except Exception:
            be.edge_locker.delete_locks(self)
            be.index_locker.delete_locks(self)
            raise

    # ---------------------------------------------------------------- commit
    def commit(self, preflush=None) -> None:
        """`preflush`: WAL hook invoked after the lock checks pass and
        immediately before the batched flush — the point past which a crash
        can tear the batch (core/graph.py commit_tx step 6)."""
        if not self._open:
            return
        try:
            self._check_and_release_locks(commit=True)
            if preflush is not None and self.has_mutations():
                preflush()
            if self._mutations and self._ledger_local:
                from janusgraph_tpu.observability.profiler import (
                    accrue,
                    current_ledger,
                )

                if current_ledger() is not None:
                    accrue(
                        cells_written=sum(
                            len(m.additions) + len(m.deletions)
                            for rows in self._mutations.values()
                            for m in rows.values()
                        ),
                        bytes_written=sum(
                            len(e[0]) + len(e[1])
                            for rows in self._mutations.values()
                            for m in rows.values() for e in m.additions
                        ),
                    )
            if self._mutations:
                if self.backend.metrics_enabled:
                    # batched writes bypass the per-store wrapper, so they
                    # are counted here (reference: MetricInstrumentedStoreManager
                    # times mutateMany at the manager level)
                    from janusgraph_tpu.util.metrics import metrics as _m

                    with _m.time("storage.mutateMany"):
                        self.backend.guard(
                            lambda: self.backend.manager.mutate_many(
                                self._mutations, self.store_tx
                            )
                        )
                    for store_name, rows in self._mutations.items():
                        # '.rows' suffix: distinct from the per-call 'mutate'
                        # timer namespace of MetricInstrumentedStore
                        # graphlint: disable=JG110 -- store names are the fixed schema-declared store set (edgestore/indexstore/system)
                        _m.counter(f"storage.{store_name}.mutate.rows").inc(
                            len(rows)
                        )
                else:
                    self.backend.guard(
                        lambda: self.backend.manager.mutate_many(
                            self._mutations, self.store_tx
                        )
                    )
                # mutation-epoch bump for touched edgestore rows; the
                # batch itself streams to the delta-CSR change capture
                edge_rows = self._mutations.get(EDGESTORE_NAME)
                if edge_rows:
                    self.backend.note_edge_mutations(
                        edge_rows.keys(), edge_rows
                    )
                # cache invalidation for mutated rows
                for store_name, rows in self._mutations.items():
                    store = (
                        self.backend.edgestore
                        if store_name == EDGESTORE_NAME
                        else self.backend.indexstore
                        if store_name == INDEXSTORE_NAME
                        else None
                    )
                    if isinstance(store, ExpirationCacheStore):
                        for key in rows:
                            store.invalidate(key)
                self._mutations = {}
            self.store_tx.commit()
        finally:
            self.backend.edge_locker.delete_locks(self)
            self.backend.index_locker.delete_locks(self)
            self._open = False

    def rollback(self) -> None:
        self._mutations = {}
        self.backend.edge_locker.delete_locks(self)
        self.backend.index_locker.delete_locks(self)
        self.store_tx.rollback()
        self._open = False
