"""Backend orchestration: opens the named stores, wires caches and the ID
authority, and builds buffered backend transactions.

Capability parity with the reference's orchestrator
(reference: diskstorage/Backend.java:80 — opens edgestore/graphindex/
janusgraph_ids/system_properties and wraps caches; BackendTransaction.java —
multiplexes per-store operations; CacheTransaction.java:217 — buffers
mutations and flushes in batches).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from janusgraph_tpu.storage.cache import ExpirationCacheStore
from janusgraph_tpu.storage.idauthority import (
    ConsistentKeyIDAuthority,
    ID_STORE_NAME,
)
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStoreManager,
    KeySliceQuery,
    SliceQuery,
    StoreTransaction,
)

EDGESTORE_NAME = "edgestore"
INDEXSTORE_NAME = "graphindex"
SYSTEM_PROPERTIES_NAME = "system_properties"
TXLOG_NAME = "txlog"
SYSTEMLOG_NAME = "systemlog"


class Backend:
    """Owns the store manager and the named stores of one graph."""

    def __init__(
        self,
        manager: KeyColumnValueStoreManager,
        cache_enabled: bool = True,
        cache_size: int = 65536,
        id_block_size: int = 10_000,
    ):
        self.manager = manager
        self._base_tx = manager.begin_transaction()
        edgestore = manager.open_database(EDGESTORE_NAME)
        indexstore = manager.open_database(INDEXSTORE_NAME)
        if cache_enabled:
            # 80/20 edge/index cache split like the reference (Backend.java:107)
            edgestore = ExpirationCacheStore(edgestore, int(cache_size * 0.8))
            indexstore = ExpirationCacheStore(indexstore, int(cache_size * 0.2))
        self.edgestore = edgestore
        self.indexstore = indexstore
        self.system_properties = manager.open_database(SYSTEM_PROPERTIES_NAME)
        self.id_store = manager.open_database(ID_STORE_NAME)
        self.id_authority = ConsistentKeyIDAuthority(
            self.id_store, self._base_tx, block_size=id_block_size
        )

    def begin_transaction(self, config: Optional[dict] = None) -> "BackendTransaction":
        return BackendTransaction(self, self.manager.begin_transaction(config))

    # -- global config on system_properties (reference: KCVSConfiguration) --
    _CONFIG_KEY = b"\x00config"

    def set_global_config(self, name: str, value: bytes) -> None:
        self.system_properties.mutate(
            self._CONFIG_KEY, [(name.encode(), value)], [], self._base_tx
        )

    def get_global_config(self, name: str) -> Optional[bytes]:
        col = name.encode()
        entries = self.system_properties.get_slice(
            KeySliceQuery(
                self._CONFIG_KEY, SliceQuery(col, col + b"\x00")
            ),
            self._base_tx,
        )
        return entries[0][1] if entries else None

    def close(self) -> None:
        self.edgestore.close()
        self.indexstore.close()
        self.manager.close()

    def clear(self) -> None:
        self.manager.clear_storage()


class BackendTransaction:
    """Multiplexes reads over the backend stores and buffers writes until
    commit, flushing them as one batched mutate_many
    (reference: BackendTransaction.java + CacheTransaction.java)."""

    def __init__(self, backend: Backend, store_tx: StoreTransaction):
        self.backend = backend
        self.store_tx = store_tx
        self._mutations: Dict[str, Dict[bytes, KCVMutation]] = {}
        self._lock = threading.Lock()
        self._open = True

    # ----------------------------------------------------------------- reads
    def edge_store_query(self, query: KeySliceQuery) -> EntryList:
        return self.backend.edgestore.get_slice(query, self.store_tx)

    def edge_store_multi_query(
        self, keys: Sequence[bytes], slice_query: SliceQuery
    ) -> Dict[bytes, EntryList]:
        return self.backend.edgestore.get_slice_multi(keys, slice_query, self.store_tx)

    def index_query(self, query: KeySliceQuery) -> EntryList:
        return self.backend.indexstore.get_slice(query, self.store_tx)

    # ---------------------------------------------------------------- writes
    def _buffer(self, store: str, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        with self._lock:
            rows = self._mutations.setdefault(store, {})
            m = rows.setdefault(key, KCVMutation())
            m.merge(KCVMutation(additions=list(additions), deletions=list(deletions)))

    def mutate_edges(self, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        self._buffer(EDGESTORE_NAME, key, additions, deletions)

    def mutate_index(self, key: bytes, additions: EntryList, deletions: Sequence[bytes]):
        self._buffer(INDEXSTORE_NAME, key, additions, deletions)

    def has_mutations(self) -> bool:
        return any(
            not m.is_empty() for rows in self._mutations.values() for m in rows.values()
        )

    # ---------------------------------------------------------------- commit
    def commit(self) -> None:
        if not self._open:
            return
        try:
            if self._mutations:
                self.backend.manager.mutate_many(self._mutations, self.store_tx)
                # cache invalidation for mutated rows
                for store_name, rows in self._mutations.items():
                    store = (
                        self.backend.edgestore
                        if store_name == EDGESTORE_NAME
                        else self.backend.indexstore
                        if store_name == INDEXSTORE_NAME
                        else None
                    )
                    if isinstance(store, ExpirationCacheStore):
                        for key in rows:
                            store.invalidate(key)
                self._mutations = {}
            self.store_tx.commit()
        finally:
            self._open = False

    def rollback(self) -> None:
        self._mutations = {}
        self.store_tx.rollback()
        self._open = False
