"""Store-level TTL wrapper.

Capability parity with the reference's TTL emulation
(reference: diskstorage/keycolumnvalue/ttl/TTLKCVSManager.java:119 — wraps a
manager and attaches a store-wide TTL to every written cell). The reference
delegates expiry to backends with native cell TTL; here expiry is
self-contained so it works over ANY backend: each stored value is framed as
[8-byte big-endian expire-ns | payload] (expire 0 = never), reads filter and
strip expired cells lazily, and `purge_expired()` reclaims space eagerly.
"""

from __future__ import annotations

import struct
import time
from typing import Dict, Iterator, Optional, Sequence, Tuple

from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)

_EXP = struct.Struct(">Q")


def _now_ns() -> int:
    return time.time_ns()


class TTLKCVStore(KeyColumnValueStore):
    def __init__(self, wrapped: KeyColumnValueStore, ttl_seconds: float):
        self.wrapped = wrapped
        self.ttl_seconds = ttl_seconds

    @property
    def name(self) -> str:
        return self.wrapped.name

    def _wrap_value(self, value: bytes, cell_expire_ns: int = 0) -> bytes:
        exp = 0 if self.ttl_seconds <= 0 else _now_ns() + int(self.ttl_seconds * 1e9)
        if cell_expire_ns:
            # per-cell TTL (3-tuple addition): the earlier deadline wins
            exp = cell_expire_ns if not exp else min(exp, cell_expire_ns)
        return _EXP.pack(exp) + value

    def _frame_addition(self, e):
        """(col, val[, expire_ns]) -> (col, framed-val): per-cell expiry is
        folded into this wrapper's own value framing, so the wrapped store
        needs no cell-TTL support of its own."""
        return (e[0], self._wrap_value(e[1], e[2] if len(e) >= 3 else 0))

    @staticmethod
    def _live(framed: bytes, now: int) -> Optional[bytes]:
        (exp,) = _EXP.unpack_from(framed)
        if exp and exp <= now:
            return None
        return framed[_EXP.size:]

    def _filter(self, entries: EntryList) -> EntryList:
        now = _now_ns()
        out: EntryList = []
        for c, v in entries:
            payload = self._live(v, now)
            if payload is not None:
                out.append((c, payload))
        return out

    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        return self._filter(self.wrapped.get_slice(query, txh))

    def get_slice_multi(self, keys, slice_query, txh):
        res = self.wrapped.get_slice_multi(keys, slice_query, txh)
        return {k: self._filter(v) for k, v in res.items()}

    def mutate(
        self,
        key: bytes,
        additions: EntryList,
        deletions: Sequence[bytes],
        txh: StoreTransaction,
    ) -> None:
        framed = [self._frame_addition(e) for e in additions]
        self.wrapped.mutate(key, framed, deletions, txh)

    def get_keys(self, query, txh) -> Iterator[Tuple[bytes, EntryList]]:
        for key, entries in self.wrapped.get_keys(query, txh):
            live = self._filter(entries)
            if live:
                yield key, live

    def purge_expired(self, txh: StoreTransaction) -> int:
        """Eagerly delete expired cells; returns the number purged."""
        now = _now_ns()
        purged = 0
        for key, entries in self.wrapped.get_keys(SliceQuery(), txh):
            dead = [c for c, v in entries if self._live(v, now) is None]
            if dead:
                self.wrapped.mutate(key, [], dead, txh)
                purged += len(dead)
        return purged

    def close(self) -> None:
        self.wrapped.close()


class TTLStoreManager(KeyColumnValueStoreManager):
    """Wraps any manager, giving each store a TTL (default or per-store)."""

    def __init__(
        self,
        wrapped: KeyColumnValueStoreManager,
        default_ttl_seconds: float = 0.0,
        per_store_ttl: Optional[Dict[str, float]] = None,
    ):
        self.wrapped = wrapped
        self.default_ttl = default_ttl_seconds
        self.per_store_ttl = per_store_ttl or {}
        self._stores: Dict[str, TTLKCVStore] = {}

    @property
    def features(self) -> StoreFeatures:
        f = self.wrapped.features
        return StoreFeatures(**{**f.__dict__, "cell_ttl": True})

    @property
    def ledger_self_accounting(self) -> bool:
        """Pass-through: a wrapped remote client accounts its own cells,
        so BackendTransaction must not count them a second time."""
        return getattr(self.wrapped, "ledger_self_accounting", False)

    @property
    def name(self) -> str:
        return f"ttl({self.wrapped.name})"

    def open_database(self, name: str) -> TTLKCVStore:
        if name not in self._stores:
            ttl = self.per_store_ttl.get(name, self.default_ttl)
            self._stores[name] = TTLKCVStore(
                self.wrapped.open_database(name), ttl
            )
        return self._stores[name]

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return self.wrapped.begin_transaction(config)

    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        framed: Dict[str, Dict[bytes, KCVMutation]] = {}
        for store_name, rows in mutations.items():
            store = self.open_database(store_name)
            framed[store_name] = {
                key: KCVMutation(
                    additions=[
                        store._frame_addition(e) for e in mut.additions
                    ],
                    deletions=list(mut.deletions),
                )
                for key, mut in rows.items()
            }
        self.wrapped.mutate_many(framed, txh)

    def get_local_key_partition(self):
        return self.wrapped.get_local_key_partition()

    def close(self) -> None:
        self.wrapped.close()

    def clear_storage(self) -> None:
        self.wrapped.clear_storage()

    def exists(self) -> bool:
        return self.wrapped.exists()
