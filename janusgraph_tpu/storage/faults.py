"""Chaos engine: seeded, deterministic fault injection at the KCVS seam.

The reference wraps every storage call in a retrying guard
(reference: diskstorage/util/BackendOperation.java) and recovers torn
commits from its write-ahead tx log, but nothing in either codebase ever
*exercises* a failure — so none of the recovery paths are proven. This
module makes failures injectable, survivable, and observable:

- :class:`FaultPlan` — a seeded plan of fault decisions. Every decision is
  a pure function of ``(seed, fault kind, per-kind operation index)``
  (a stable CRC hash, not a shared RNG stream), so the same seed over the
  same workload reproduces the exact same fault sequence — including under
  partial replays, which a shared RNG cursor cannot do. Every injected
  fault is appended to a bounded ``journal`` for assertions and reports.
- :class:`FaultInjectingStoreManager` / :class:`FaultInjectingStore` —
  wrap any :class:`KeyColumnValueStoreManager` and execute the plan on the
  data path. System stores (ids, config, logs, locks) are exempt by
  default: chaos targets the data plane, never the recovery machinery
  that must repair it.

Fault kinds (all off by default):

===================  =====================================================
``read`` / ``write`` probabilistic :class:`InjectedFaultError`
                     (a ``TemporaryBackendError``) on slice reads and
                     mutations — absorbed by the backend_op retry guard
``latency``          injected latency spikes on reads
``overload``         a seeded latency STORM: beginning at read index
                     ``overload-at``, the next ``overload-ops`` reads
                     each stall ``overload-latency-ms`` — the sustained
                     saturation scenario the admission controller
                     (server/admission.py) is tested against
``torn``             crash after applying a PREFIX of a ``mutate_many``
                     batch (:class:`InjectedCrashError`) — the torn-commit
                     case healed by ``TornCommitRecovery`` on reopen
``lock``             lease expiry: the Nth lock check sees a skewed clock,
                     so the holder's claim reads as expired
                     (``TemporaryLockingError``; re-acquirable after)
``scan``             kill a row scan mid-stream — absorbed by
                     StandardScanner's per-partition retry + resume
``superstep``        preempt an OLAP superstep
                     (:class:`SuperstepPreempted`) — absorbed by the
                     executors' checkpoint auto-resume
``shard_preempt``    preempt ONE shard of a multi-chip sharded run
                     mid-superstep (:class:`ShardPreempted`) — absorbed by
                     the sharded executor's cross-shard auto-resume (all
                     shards roll back to the last complete manifest)
``collective``       a cross-shard collective (halo all_to_all / psum
                     barrier) times out (:class:`CollectiveTimeout`) —
                     same roll-back-to-manifest recovery
``halo_drop``        a destination-binned halo batch is dropped in flight
                     (:class:`HaloDropped`) — same recovery
``straggler``        per-(shard, superstep) latency skew: the chosen shard
                     "runs late" by ``shard-straggler-ms`` (no exception;
                     feeds straggler detection / the skew gauge)
``replica_kill``     kill ONE serving replica of a fleet at the scheduled
                     fleet tick — the router + retry budgets must absorb
                     it (server/fleet.py; executed by the fleet harness
                     consulting :meth:`FaultPlan.fleet_hook`)
``replica_restart``  the killed replica rejoins at the scheduled fleet
                     tick (warm-up from the shard-checkpoint snapshot
                     pack exercises the join path)
``replica_partition`` the chosen replica keeps serving HTTP (the router
                     still sees it) but its STORAGE reads/writes fail for
                     a seeded window (``replica-partition-at`` ..
                     ``+ replica-partition-ops``) — the breaker trips,
                     /healthz degrades, and the router must route around
                     a replica that looks alive but cannot reach data
``cdc_torn_segment`` the Nth CDC log append writes HALF a frame and
                     crashes (:class:`storage.cdc.CDCTornWrite`) — reopen
                     recovery drops exactly the torn suffix; sealed
                     segments are never at risk (storage/cdc.py)
``cdc_lagging_follower`` a follower's next ``follower-lag-pulls`` pulls
                     skip applying (staleness grows past the bound,
                     /healthz degrades) — promotion force-pulls through
                     the window, so leader failover is never blocked by
                     the lag fault (server/fleet.py ``CDCFollower``)
``stalled_lock``     the chosen op holds an instrumented lock for
                     ``stall-lock-ms`` (the hook returns the hold
                     duration; the CALLER holds the lock and sleeps, so
                     the decision stays pure) — the stall watchdog must
                     flight a ``lock_convoy`` with the holder's stack
                     and capture a bundle (observability/continuous.py)
``wedged_thread``    the chosen op wedges its worker thread (the hook
                     returns True once; the caller blocks until
                     released) — the watchdog's progress checker must
                     flight a ``stall``
===================  =====================================================

The four ``shard-*`` kinds are scheduled/decided exactly like the
single-device kinds — pure functions of ``(seed, kind, index)`` — so a
seeded multi-chip chaos soak reproduces the identical fault sequence,
including across auto-resume replays (straggler decisions key on the
ABSOLUTE ``(superstep, shard)`` pair, not a shared cursor, so a replayed
superstep sees the same skew it saw the first time).

Wiring: ``storage.faults.enabled=true`` makes ``open_graph`` wrap its
store manager and expose the plan as ``graph.fault_plan``; the OLAP
computer forwards ``plan.olap_hook`` into the executors. See
docs/robustness.md for the chaos-test recipe.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.exceptions import (
    CollectiveTimeout,
    HaloDropped,
    InjectedCrashError,
    InjectedFaultError,
    ShardPreempted,
    SuperstepPreempted,
)
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)

#: stores the injector touches by default — the data plane only. The id
#: authority, global config, durable logs, and lock stores stay clean so
#: recovery can always run (chaos that corrupts the repair path proves
#: nothing).
DEFAULT_FAULT_STORES = ("edgestore", "graphindex")

#: clock skew applied to a lock check chosen for lease expiry: one hour,
#: far past any sane locks.expiry-ms, so the holder's claim always reads
#: as expired regardless of tuning
LOCK_EXPIRY_SKEW_NS = 3_600 * 1_000_000_000


class FaultPlan:
    """A deterministic, seeded schedule of faults.

    Probabilistic kinds (read/write/latency) fire when
    ``hash(seed, kind, n) < rate`` for the kind's n-th operation; scheduled
    kinds (torn/lock/scan/superstep) fire at an exact per-kind operation
    index. Counters are per kind, so interleaving between kinds (e.g. a
    cache absorbing reads) never shifts another kind's schedule.
    """

    def __init__(
        self,
        seed: int = 0,
        read_error_rate: float = 0.0,
        write_error_rate: float = 0.0,
        latency_ms: float = 0.0,
        latency_rate: float = 0.0,
        torn_mutation_at: int = -1,
        lock_expiry_at: int = -1,
        scan_kill_at: int = -1,
        scan_kill_after_rows: int = 8,
        overload_at: int = -1,
        overload_ops: int = 0,
        overload_latency_ms: float = 0.0,
        preempt_superstep: int = -1,
        shard_preempt_superstep: int = -1,
        shard_preempt_shard: int = -1,
        collective_timeout_at: int = -1,
        halo_drop_at: int = -1,
        straggler_ms: float = 0.0,
        straggler_rate: float = 0.0,
        replica_kill_at: int = -1,
        replica_restart_at: int = -1,
        replica_partition_at: int = -1,
        replica_partition_ops: int = 0,
        replica_target: int = -1,
        cdc_torn_at: int = -1,
        follower_lag_at: int = -1,
        follower_lag_pulls: int = 0,
        stall_lock_at: int = -1,
        stall_lock_ms: float = 0.0,
        wedge_thread_at: int = -1,
        stores: Sequence[str] = DEFAULT_FAULT_STORES,
        journal_limit: int = 4096,
    ):
        self.seed = int(seed)
        self.read_error_rate = read_error_rate
        self.write_error_rate = write_error_rate
        self.latency_ms = latency_ms
        self.latency_rate = latency_rate
        self.overload_at = overload_at
        self.overload_ops = overload_ops
        self.overload_latency_ms = overload_latency_ms
        self.torn_mutation_at = torn_mutation_at
        self.lock_expiry_at = lock_expiry_at
        self.scan_kill_at = scan_kill_at
        self.scan_kill_after_rows = scan_kill_after_rows
        self.preempt_superstep = preempt_superstep
        self.shard_preempt_superstep = shard_preempt_superstep
        self.shard_preempt_shard = shard_preempt_shard
        self.collective_timeout_at = collective_timeout_at
        self.halo_drop_at = halo_drop_at
        self.straggler_ms = straggler_ms
        self.straggler_rate = straggler_rate
        self.replica_kill_at = replica_kill_at
        self.replica_restart_at = replica_restart_at
        self.replica_partition_at = replica_partition_at
        self.replica_partition_ops = replica_partition_ops
        self._replica_target_cfg = replica_target
        self.cdc_torn_at = cdc_torn_at
        self.follower_lag_at = follower_lag_at
        self.follower_lag_pulls = follower_lag_pulls
        self._cdc_torn_fired = False
        self._follower_lag_recorded = False
        self.stall_lock_at = stall_lock_at
        self.stall_lock_ms = stall_lock_ms
        self.wedge_thread_at = wedge_thread_at
        self._stall_lock_fired = False
        self._wedge_fired = False
        #: which fleet replica THIS plan instance belongs to (set by the
        #: fleet harness when wiring each replica's graph; -1 = not part
        #: of a fleet, so the partition window never applies)
        self.replica_index = -1
        self._replica_killed = False
        self._replica_restarted = False
        self._partition_recorded = False
        self.stores = tuple(stores)
        self.journal_limit = journal_limit
        #: injected-fault record: [{"kind", "n", ...}] — deterministic
        #: content only (no wall-clock), so two runs with one seed compare
        #: journal-equal
        self.journal: List[dict] = []
        self._counters: Dict[str, int] = {}
        self._preempted = False
        self._shard_preempted = False
        self._collective_fired = False
        self._halo_dropped = False
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, cfg) -> "FaultPlan":
        """Build from the ``storage.faults.*`` option family."""
        stores = [
            s.strip()
            for s in cfg.get("storage.faults.stores").split(",")
            if s.strip()
        ] or list(DEFAULT_FAULT_STORES)
        return cls(
            seed=cfg.get("storage.faults.seed"),
            read_error_rate=cfg.get("storage.faults.read-error-rate"),
            write_error_rate=cfg.get("storage.faults.write-error-rate"),
            latency_ms=cfg.get("storage.faults.latency-ms"),
            latency_rate=cfg.get("storage.faults.latency-rate"),
            overload_at=cfg.get("storage.faults.overload-at"),
            overload_ops=cfg.get("storage.faults.overload-ops"),
            overload_latency_ms=cfg.get(
                "storage.faults.overload-latency-ms"
            ),
            torn_mutation_at=cfg.get("storage.faults.torn-mutation-at"),
            lock_expiry_at=cfg.get("storage.faults.lock-expiry-at"),
            scan_kill_at=cfg.get("storage.faults.scan-kill-at"),
            scan_kill_after_rows=cfg.get(
                "storage.faults.scan-kill-after-rows"
            ),
            preempt_superstep=cfg.get("storage.faults.preempt-superstep"),
            shard_preempt_superstep=cfg.get(
                "storage.faults.shard-preempt-superstep"
            ),
            shard_preempt_shard=cfg.get(
                "storage.faults.shard-preempt-shard"
            ),
            collective_timeout_at=cfg.get(
                "storage.faults.shard-collective-timeout-at"
            ),
            halo_drop_at=cfg.get("storage.faults.shard-halo-drop-at"),
            straggler_ms=cfg.get("storage.faults.shard-straggler-ms"),
            straggler_rate=cfg.get("storage.faults.shard-straggler-rate"),
            replica_kill_at=cfg.get("storage.faults.replica-kill-at"),
            replica_restart_at=cfg.get(
                "storage.faults.replica-restart-at"
            ),
            replica_partition_at=cfg.get(
                "storage.faults.replica-partition-at"
            ),
            replica_partition_ops=cfg.get(
                "storage.faults.replica-partition-ops"
            ),
            replica_target=cfg.get("storage.faults.replica-target"),
            cdc_torn_at=cfg.get("storage.faults.cdc-torn-at"),
            follower_lag_at=cfg.get("storage.faults.follower-lag-at"),
            follower_lag_pulls=cfg.get(
                "storage.faults.follower-lag-pulls"
            ),
            stall_lock_at=cfg.get("storage.faults.stall-lock-at"),
            stall_lock_ms=cfg.get("storage.faults.stall-lock-ms"),
            wedge_thread_at=cfg.get("storage.faults.wedge-thread-at"),
            stores=stores,
        )

    # ------------------------------------------------------------- decisions
    def _tick(self, kind: str) -> int:
        with self._lock:
            n = self._counters.get(kind, 0)
            self._counters[kind] = n + 1
            return n

    def _chance(self, kind: str, n: int, rate: float) -> bool:
        if rate <= 0.0:
            return False
        h = zlib.crc32(f"{self.seed}:{kind}:{n}".encode())
        return (h / 0xFFFFFFFF) < rate

    def _record(self, kind: str, n: int, **detail) -> None:
        from janusgraph_tpu.observability import flight_recorder, registry

        # graphlint: disable=JG110 -- kind is the fixed injected-fault taxonomy (storage/faults.py fault kinds)
        registry.counter(f"chaos.injected.{kind}").inc()
        registry.counter("chaos.injected.total").inc()
        # the black box sees every injected fault (deterministic fields
        # only, so seeded runs produce comparable event sequences)
        flight_recorder.record(
            "fault", kind=kind, n=n, seed=self.seed, **detail
        )
        with self._lock:
            if len(self.journal) < self.journal_limit:
                self.journal.append({"kind": kind, "n": n, **detail})

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # ------------------------------------------------------------ fleet hooks
    def replica_target(self, num_replicas: int) -> int:
        """The deterministically chosen victim replica for the fleet fault
        kinds: ``replica-target`` when configured, else seed-hashed — the
        same pure-function discipline as the shard-preemption choice."""
        if self._replica_target_cfg >= 0:
            return self._replica_target_cfg % max(1, num_replicas)
        return zlib.crc32(f"{self.seed}:replica".encode()) % max(
            1, num_replicas
        )

    def arm_replica(self, index: int, num_replicas: int) -> None:
        """Bind this plan instance to fleet replica ``index`` (each replica
        opens its own graph, so each carries its own plan). Only the
        target replica's plan executes the partition window."""
        self.replica_index = int(index)
        self._num_replicas = int(num_replicas)

    def _partition_active(self, n: int) -> bool:
        """Whether data-plane op index ``n`` of THIS replica's plan falls
        inside the seeded partition window (router sees the replica, the
        replica cannot reach storage)."""
        if (
            self.replica_partition_at < 0
            or self.replica_partition_ops <= 0
            or self.replica_index < 0
        ):
            return False
        if self.replica_index != self.replica_target(
            getattr(self, "_num_replicas", 1)
        ):
            return False
        return (
            self.replica_partition_at
            <= n
            < self.replica_partition_at + self.replica_partition_ops
        )

    def fleet_hook(self, num_replicas: int) -> List[dict]:
        """Fleet-tick hook, consulted once per traffic tick by the fleet
        chaos driver (bench ``_fleet_chaos_stage`` / tests). Returns the
        scheduled fleet events for this tick — ``replica_kill`` at
        ``replica-kill-at``, ``replica_restart`` at ``replica-restart-at``
        — each fired once, journal-recorded, with the victim chosen by
        :meth:`replica_target`. The DRIVER executes the decision (stops /
        restarts the server), mirroring how the executors absorb
        ``sharded_hook`` decisions."""
        n = self._tick("fleet")
        events: List[dict] = []
        target = self.replica_target(num_replicas)
        if not self._replica_killed and 0 <= self.replica_kill_at <= n:
            self._replica_killed = True
            self._record("replica_kill", n, replica=target)
            events.append({"kind": "replica_kill", "replica": target})
        if (
            self._replica_killed
            and not self._replica_restarted
            and 0 <= self.replica_restart_at <= n
        ):
            self._replica_restarted = True
            self._record("replica_restart", n, replica=target)
            events.append({"kind": "replica_restart", "replica": target})
        return events

    # ------------------------------------------------------------- cdc hooks
    def cdc_torn_write(self) -> bool:
        """Tear THIS tail append (a partial frame hits disk and the
        writer dies)? Fires once at ``cdc-torn-at`` — the torn-tail case
        CDCLog recovery contains to exactly one frame (storage/cdc.py)."""
        n = self._tick("cdc-append")
        if not self._cdc_torn_fired and 0 <= self.cdc_torn_at <= n:
            self._cdc_torn_fired = True
            self._record("cdc_torn_segment", n)
            return True
        return False

    def follower_lag(self) -> bool:
        """Stall THIS follower pull (skip applying, so staleness grows)?
        True across the window [follower-lag-at, +follower-lag-pulls);
        journaled once at the leading edge. The router must respond by
        sending freshness-hinted traffic back to the leader."""
        n = self._tick("follower-pull")
        if (
            self.follower_lag_at >= 0
            and self.follower_lag_pulls > 0
            and self.follower_lag_at
            <= n
            < self.follower_lag_at + self.follower_lag_pulls
        ):
            if not self._follower_lag_recorded:
                self._follower_lag_recorded = True
                self._record(
                    "cdc_lagging_follower", n,
                    pulls=self.follower_lag_pulls,
                )
            return True
        return False

    # -------------------------------------------------------- watchdog hooks
    def stalled_lock(self, lock: str = "instrumented") -> float:
        """Hold duration (ms) for THIS instrumented-lock acquisition: the
        scheduled op index returns ``stall-lock-ms`` once, every other op
        returns 0. The CALLER holds the lock for that long (the decision
        is pure; the side effect — a convoy the watchdog must catch —
        happens at the call site), so two runs with one seed journal
        byte-equal."""
        n = self._tick("stall_lock")
        if (
            not self._stall_lock_fired
            and self.stall_lock_ms > 0
            and 0 <= self.stall_lock_at <= n
        ):
            self._stall_lock_fired = True
            self._record(
                "stalled_lock", n, lock=lock, ms=self.stall_lock_ms
            )
            return self.stall_lock_ms
        return 0.0

    def wedge_thread(self) -> bool:
        """Wedge THIS worker op? Fires once at ``wedge-thread-at``; the
        caller parks the thread (on an event the harness releases), so
        the watchdog's progress checker sees active work that stops
        moving."""
        n = self._tick("wedge_thread")
        if not self._wedge_fired and 0 <= self.wedge_thread_at <= n:
            self._wedge_fired = True
            self._record("wedged_thread", n)
            return True
        return False

    # ----------------------------------------------------------- store hooks
    def before_read(self, store: str) -> None:
        n = self._tick("read")
        if self._partition_active(n):
            # journaled once at the leading edge (a window of failing ops
            # would flood the ring), raised for every op inside it
            if not self._partition_recorded:
                self._partition_recorded = True
                self._record(
                    "replica_partition", n,
                    replica=self.replica_index,
                    ops=self.replica_partition_ops,
                )
            raise InjectedFaultError(
                f"injected storage partition: replica "
                f"{self.replica_index} cannot reach storage (read #{n}, "
                f"seed {self.seed})"
            )
        if (
            self.overload_at >= 0
            and self.overload_latency_ms > 0
            and self.overload_at <= n < self.overload_at + self.overload_ops
        ):
            # the STORM is index-scheduled like every other kind, so one
            # seed reproduces one saturation window; journaled once at
            # its leading edge (per-op records would flood the ring)
            if n == self.overload_at:
                self._record(
                    "overload", n,
                    store=store, ops=self.overload_ops,
                    ms=self.overload_latency_ms,
                )
            time.sleep(self.overload_latency_ms / 1000.0)
        if self._chance("latency", n, self.latency_rate) and self.latency_ms:
            self._record("latency", n, store=store, ms=self.latency_ms)
            time.sleep(self.latency_ms / 1000.0)
        if self._chance("read", n, self.read_error_rate):
            self._record("read", n, store=store)
            raise InjectedFaultError(
                f"injected read fault #{n} on {store} (seed {self.seed})"
            )

    def before_write(self, store: str) -> None:
        n = self._tick("write")
        if self._partition_active(n):
            raise InjectedFaultError(
                f"injected storage partition: replica "
                f"{self.replica_index} cannot reach storage (write #{n}, "
                f"seed {self.seed})"
            )
        if self._chance("write", n, self.write_error_rate):
            self._record("write", n, store=store)
            raise InjectedFaultError(
                f"injected write fault #{n} on {store} (seed {self.seed})"
            )

    def mutate_many_decision(self) -> Tuple[int, bool]:
        """(op index, tear this batch?) for one mutate_many call. Write-rate
        faults for the batch path are drawn here too (before anything is
        applied, so a retry is safe). The scheduled tear takes precedence —
        a probabilistic fault on the same index must not consume it."""
        n = self._tick("mutate_many")
        if n == self.torn_mutation_at:
            return n, True
        if self._chance("write", n, self.write_error_rate):
            self._record("write", n, store="mutate_many")
            raise InjectedFaultError(
                f"injected batch-write fault #{n} (seed {self.seed})"
            )
        return n, False

    def record_torn(self, n: int, applied_rows: int, total_rows: int) -> None:
        self._record(
            "torn", n, applied_rows=applied_rows, total_rows=total_rows
        )

    def scan_decision(self) -> Tuple[int, bool]:
        """(scan index, kill this scan mid-stream?)."""
        n = self._tick("scan")
        return n, n == self.scan_kill_at

    def record_scan_kill(self, n: int, store: str, rows: int) -> None:
        self._record("scan", n, store=store, after_rows=rows)

    # ------------------------------------------------------------- lock hook
    def lock_clock_ns(self) -> int:
        """Clock source for ConsistentKeyLocker checks: the scheduled check
        sees a one-hour-skewed clock, so every live claim (the holder's
        included) reads as expired — the lock-lease-expiry fault."""
        n = self._tick("lock_check")
        if n == self.lock_expiry_at:
            self._record("lock", n, skew_ns=LOCK_EXPIRY_SKEW_NS)
            return time.time_ns() + LOCK_EXPIRY_SKEW_NS
        return time.time_ns()

    # ------------------------------------------------------------- OLAP hook
    def olap_hook(self, step: int) -> None:
        """Executor fault hook: raises SuperstepPreempted ONCE when the run
        reaches the scheduled superstep; the auto-resume replay passes."""
        if self.preempt_superstep < 0 or self._preempted:
            return
        if step >= self.preempt_superstep:
            self._preempted = True
            self._record("superstep", self._tick("superstep"), step=step)
            raise SuperstepPreempted(
                f"injected preemption at superstep {step} "
                f"(seed {self.seed})"
            )

    # -------------------------------------------------------- sharded hooks
    def straggler_decisions(
        self, step: int, num_shards: int
    ) -> List[Tuple[int, float]]:
        """[(shard, ms)] latency-skew decisions for one superstep. Pure in
        the ABSOLUTE (superstep, shard) pair — not a shared cursor — so a
        replayed superstep (auto-resume) sees the same skew both times."""
        if self.straggler_rate <= 0.0 or not self.straggler_ms:
            return []
        out = []
        for shard in range(num_shards):
            if self._chance(
                "straggler", step * num_shards + shard, self.straggler_rate
            ):
                out.append((shard, self.straggler_ms))
        return out

    def sharded_hook(self, step: int, num_shards: int) -> List[dict]:
        """Superstep-boundary hook for the sharded executor (consulted once
        per host-visible superstep with the mesh size). Executes, in order:

        1. straggler skew — sleeps once for the slowest selected shard
           (the SPMD program runs at the pace of its slowest participant)
           and returns the per-shard skew records for the executor's
           straggler detector;
        2. collective timeout — the scheduled collective index raises
           :class:`CollectiveTimeout` (once);
        3. halo drop — the scheduled exchange index raises
           :class:`HaloDropped` (once);
        4. shard preemption — reaching the scheduled superstep raises
           :class:`ShardPreempted` (once) for a deterministically chosen
           shard (``shard-preempt-shard``, or seed-hashed when -1).

        All raised kinds are ``SuperstepPreempted`` subclasses, absorbed by
        the cross-shard auto-resume (roll back to the last manifest).
        """
        stragglers = self.straggler_decisions(step, num_shards)
        events: List[dict] = []
        for shard, ms in stragglers:
            self._record(
                "straggler", step * num_shards + shard,
                step=step, shard=shard, ms=ms,
            )
            events.append({"step": step, "shard": shard, "ms": ms})
        if stragglers:
            # one sleep at the barrier: every shard waits on the slowest
            time.sleep(max(ms for _s, ms in stragglers) / 1000.0)
        n = self._tick("collective")
        if not self._collective_fired and n == self.collective_timeout_at:
            self._collective_fired = True
            self._record("collective", n, step=step)
            raise CollectiveTimeout(
                f"injected collective timeout at superstep {step} "
                f"(collective #{n}, seed {self.seed})"
            )
        h = self._tick("halo")
        if not self._halo_dropped and h == self.halo_drop_at:
            self._halo_dropped = True
            self._record("halo_drop", h, step=step)
            raise HaloDropped(
                f"injected dropped halo batch at superstep {step} "
                f"(exchange #{h}, seed {self.seed})"
            )
        if (
            not self._shard_preempted
            and self.shard_preempt_superstep >= 0
            and step >= self.shard_preempt_superstep
        ):
            self._shard_preempted = True
            shard = self.shard_preempt_shard
            if shard < 0:
                shard = zlib.crc32(f"{self.seed}:shard".encode()) % max(
                    1, num_shards
                )
            self._record(
                "shard_preempt", self._tick("shard_preempt"),
                step=step, shard=shard,
            )
            raise ShardPreempted(
                f"injected preemption of shard {shard} at superstep "
                f"{step} (seed {self.seed})"
            )
        # the single-device preemption schedule still applies on a mesh
        self.olap_hook(step)
        return events


# ---------------------------------------------------------------------------
# store wrappers


class FaultInjectingStore(KeyColumnValueStore):
    """Executes a FaultPlan in front of one wrapped store."""

    def __init__(self, wrapped: KeyColumnValueStore, plan: FaultPlan):
        self.wrapped = wrapped
        self.plan = plan

    @property
    def name(self) -> str:
        return self.wrapped.name

    def get_slice(self, query: KeySliceQuery, txh) -> EntryList:
        self.plan.before_read(self.name)
        return self.wrapped.get_slice(query, txh)

    def get_slice_multi(
        self, keys: Sequence[bytes], slice_query: SliceQuery, txh
    ) -> Dict[bytes, EntryList]:
        # one decision per batched call — a multi-slice is one backend op
        self.plan.before_read(self.name)
        return self.wrapped.get_slice_multi(keys, slice_query, txh)

    def mutate(self, key, additions, deletions, txh) -> None:
        self.plan.before_write(self.name)
        self.wrapped.mutate(key, additions, deletions, txh)

    def acquire_lock(self, key, column, expected_value, txh) -> None:
        self.wrapped.acquire_lock(key, column, expected_value, txh)

    def get_keys(self, query, txh) -> Iterator[Tuple[bytes, EntryList]]:
        n, kill = self.plan.scan_decision()
        rows = 0
        for key, entries in self.wrapped.get_keys(query, txh):
            if kill and rows >= self.plan.scan_kill_after_rows:
                self.plan.record_scan_kill(n, self.name, rows)
                raise InjectedFaultError(
                    f"injected scan kill #{n} on {self.name} after "
                    f"{rows} rows (seed {self.plan.seed})"
                )
            rows += 1
            yield key, entries

    def close(self) -> None:
        self.wrapped.close()


class FaultInjectingStoreManager(KeyColumnValueStoreManager):
    """Wraps a KeyColumnValueStoreManager; data-plane stores named in the
    plan get a FaultInjectingStore, everything else passes through."""

    def __init__(self, wrapped: KeyColumnValueStoreManager, plan: FaultPlan):
        self.wrapped = wrapped
        self.plan = plan
        self._stores: Dict[str, KeyColumnValueStore] = {}

    @property
    def features(self) -> StoreFeatures:
        return self.wrapped.features

    @property
    def ledger_self_accounting(self) -> bool:
        """Pass-through: a wrapped remote client accounts its own cells,
        so BackendTransaction must not count them a second time."""
        return getattr(self.wrapped, "ledger_self_accounting", False)

    @property
    def name(self) -> str:
        return f"faulty({self.wrapped.name})"

    def open_database(self, name: str) -> KeyColumnValueStore:
        store = self._stores.get(name)
        if store is None:
            store = self.wrapped.open_database(name)
            if name in self.plan.stores:
                store = FaultInjectingStore(store, self.plan)
            self._stores[name] = store
        return store

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return self.wrapped.begin_transaction(config)

    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        faulted = {s: rows for s, rows in mutations.items()
                   if s in self.plan.stores and rows}
        if faulted:
            n, tear = self.plan.mutate_many_decision()
            if tear:
                self._tear(mutations, txh, n)
                return  # unreachable: _tear always raises
        self.wrapped.mutate_many(mutations, txh)

    def _tear(self, mutations, txh, n: int) -> None:
        """Apply a deterministic PREFIX of the batch row-by-row (per-row
        application is atomic, the batch is not — exactly the guarantee a
        non-transactional backend gives), then crash. The suffix is lost:
        the torn-commit case."""
        rows = [
            (store_name, key, m)
            for store_name in sorted(mutations)
            for key, m in sorted(mutations[store_name].items())
            if not m.is_empty()
        ]
        applied = max(1, len(rows) // 2) if rows else 0
        for store_name, key, m in rows[:applied]:
            self.wrapped.open_database(store_name).mutate(
                key, m.additions, m.deletions, txh
            )
        self.plan.record_torn(n, applied, len(rows))
        raise InjectedCrashError(
            f"injected crash: batch torn after {applied}/{len(rows)} rows "
            f"(mutate_many #{n}, seed {self.plan.seed})"
        )

    def get_local_key_partition(self):
        return self.wrapped.get_local_key_partition()

    def close(self) -> None:
        self.wrapped.close()

    def clear_storage(self) -> None:
        self.wrapped.clear_storage()

    def exists(self) -> bool:
        return self.wrapped.exists()

    def __getattr__(self, item):
        # adapter-specific extras (shared index providers, host/port, ...)
        # resolve against the wrapped manager
        return getattr(self.wrapped, item)
