"""Remote KCVS over TCP: a real networked storage backend.

This is the framework's cql/hbase-analogue (reference: the CQL adapter
speaks the Cassandra wire protocol to remote storage nodes —
CQLStoreManager.java:533, CQLKeyColumnValueStore.java:476; all inter-node
"communication" in the reference flows through such storage RPC, SURVEY.md
§2.4). Design is NOT a Cassandra clone: one compact length-prefixed binary
protocol carrying exactly the KCVS SPI (slice / multi-slice / mutate /
mutate-many / row scan), autocommit per request (the CQL adapter's
consistency-level model: no cross-request transaction state), row scans
STREAMED row-by-row so OLAP bulk loads don't materialize the store in
memory on either side.

Server: `RemoteStoreServer` exposes ANY KeyColumnValueStoreManager (in
memory, persistent local, sharded composite) over a socket — one thread per
connection. Client: `RemoteStoreManager` implements the full manager SPI;
every request is wrapped in the retrying backend-operation guard
(backend_op.execute), so transient connection failures replay with backoff
(reference: BackendOperation.java). Combine with ShardedStoreManager for a
multi-node remote cluster in tests (the "multi-node without a cluster"
technique over real sockets).

Wire format (big-endian):
  request:  [u32 body_len][u8 op][body]
  response: [u32 body_len][u8 status][body]   status: 0 ok / 1 temp / 2 perm
  scan responses stream after the status frame: ([u8 1][row])* [u8 0]
Strings/bytes are u32-length-prefixed; entry lists are u32-count prefixed.

Trace propagation (negotiated, byte-compatible): the `_OP_FEATURES`
payload of a trace-capable server carries a `"trace": true` key; only
after seeing it does a client set the high bit of the op byte
(op | 0x80) and prepend `[u8 hdr_len][TraceContext bytes]` to the body.
Old servers never receive flagged frames (the bit is gated on
negotiation), old clients never set it — mixed pairs speak the original
protocol unchanged, they just don't stitch. The server opens a child
span under the received context around each dispatched op, so one
client query yields one cross-process trace.

Resource-ledger propagation rides the same negotiation scheme on its own
bits: a ledger-capable server adds `"ledger": true` to the features
payload, and only then does a client with an ambient
:class:`~janusgraph_tpu.observability.profiler.ResourceLedger` set
`op | 0x40` — "measure this op and echo the costs". The server prepends
`[u8 len][ledger block]` (observability/profiler.py tag-value codec) to
the OK response body of flagged ops and annotates its span with the same
fields; the client merges the echo into the ambient ledger (without
re-annotating — the server's span already carries the fields, keeping
the trace-totals == span-sums invariant). Old peers in either direction
never see (or send) flagged frames. Streaming scans are never flagged;
the client counts the rows it decodes instead.

Deadline propagation rides a third bit (`op | 0x20`, negotiated via
`"deadline": true`): the client prepends `[u8 len][u32 remaining_ms]` —
the ambient deadline's REMAINING budget (core/deadline.py; relative, so
host clocks never need to agree) — and the server runs the dispatched op
under a matching deadline scope. An op arriving with 0 budget is refused
before touching the store (permanent status: the client never replays
it), and the serving node's own downstream retries stop when the budget
runs out — the mechanism that kills retry storms at the bottom of the
stack instead of the top. Same compatibility discipline as the other
two bits: mixed old/new pairs speak the original protocol unchanged.

Pipelined async framing rides a fourth bit (`op | 0x10`, negotiated via
`"pipeline": true` — storage/pipeline.py): instead of one synchronous
op per round-trip under a per-connection lock, the client queues ops on
a small set of pipelined sockets; a writer thread coalesces the queue
into batched wire frames (same-store getSlice ops merge into one
getSliceMulti, same-store mutates into one mutateMany, everything else
rides a batch carrier), and responses carry per-frame request ids so
they complete out of order. The server dispatches each sub-op on a
per-connection worker pool — every op keeps its OWN trace context,
ledger echo, deadline budget, breaker accounting, and fault-injection
attribution; the carrier frame has no identity of its own. Old peers in
either direction never see a flagged frame: the synchronous path
remains byte-identical and is the negotiated fallback.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.exceptions import (
    PermanentBackendError,
    TemporaryBackendError,
)
from janusgraph_tpu.storage import backend_op
from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)

# ops
_OP_FEATURES = 1
_OP_GET_SLICE = 2
_OP_GET_SLICE_MULTI = 3
_OP_MUTATE = 4
_OP_MUTATE_MANY = 5
_OP_SCAN_ALL = 6
_OP_SCAN_RANGE = 7
_OP_CLEAR = 8
_OP_EXISTS = 9
#: batch carrier for pipelined framing: the body is [u32 nsub] followed
#: by length-prefixed pipelined sub-frames (storage/pipeline.iter_batch)
_OP_BATCH = 10

#: high bit of the op byte: the body is prefixed with
#: [u8 hdr_len][TraceContext bytes]. Sent only after the server's
#: features payload negotiated `"trace": true`.
_TRACE_FLAG = 0x80
#: second flag bit: "measure this op's resource costs and prepend a
#: ledger block to the OK response". Sent only after the server's
#: features payload negotiated `"ledger": true`.
_LEDGER_FLAG = 0x40
#: third flag bit: the body carries a deadline prefix
#: ([u8 len=4][u32 remaining_ms], after the trace prefix when both ride)
#: — "stop working on this op once the caller's budget is spent". Sent
#: only after the server's features payload negotiated
#: `"deadline": true` (same old/new byte-compat discipline as the trace
#: and ledger bits: un-negotiated peers never see a flagged frame).
_DEADLINE_FLAG = 0x20
#: fourth flag bit: pipelined framing — [u32 req_id] leads the body and
#: the response echoes it on status|0x10 (storage/pipeline.py). Sent
#: only after the server's features payload negotiated
#: `"pipeline": true` (same discipline as the other three bits).
_PIPELINE_FLAG = 0x10
_FLAG_MASK = _TRACE_FLAG | _LEDGER_FLAG | _DEADLINE_FLAG | _PIPELINE_FLAG

_OP_NAMES = {
    _OP_FEATURES: "features",
    _OP_GET_SLICE: "getSlice",
    _OP_GET_SLICE_MULTI: "getSliceMulti",
    _OP_MUTATE: "mutate",
    _OP_MUTATE_MANY: "mutateMany",
    _OP_SCAN_ALL: "scanAll",
    _OP_SCAN_RANGE: "scanRange",
    _OP_CLEAR: "clear",
    _OP_EXISTS: "exists",
    _OP_BATCH: "pipelineBatch",
}

_STATUS_OK = 0
_STATUS_TEMP = 1
_STATUS_PERM = 2


def encode_trace_prefix(ctx) -> bytes:
    """[u8 hdr_len][ctx bytes] — length-prefixed so the header codec can
    grow without another protocol negotiation."""
    raw = ctx.to_bytes()
    return bytes([len(raw)]) + raw


def split_trace_prefix(body: bytes):
    """Inverse of encode_trace_prefix: (TraceContext|None, rest-of-body).
    A malformed header degrades to an untraced frame, never an error."""
    from janusgraph_tpu.observability.spans import TraceContext

    if not body:
        return None, body
    hlen = body[0]
    if len(body) < 1 + hlen:
        return None, body
    return TraceContext.from_bytes(body[1:1 + hlen]), body[1 + hlen:]


def encode_deadline_prefix(remaining_ms: float) -> bytes:
    """``[u8 len=4][u32 remaining_ms]`` — REMAINING budget, not an absolute
    instant (clocks are not comparable across hosts). Length-prefixed like
    the trace header so the codec can grow without a protocol bump; a
    spent budget clamps to 0 rather than wrapping."""
    from janusgraph_tpu.core.deadline import MAX_WIRE_MS

    ms = max(0, min(int(remaining_ms), MAX_WIRE_MS))
    return bytes([4]) + struct.pack(">I", ms)


def split_deadline_prefix(body: bytes):
    """Inverse of encode_deadline_prefix: (remaining_ms|None, rest).
    Malformed prefixes degrade to an un-deadlined frame, never an error."""
    if not body:
        return None, body
    hlen = body[0]
    if hlen < 4 or len(body) < 1 + hlen:
        return None, body
    (ms,) = struct.unpack_from(">I", body, 1)
    return float(ms), body[1 + hlen:]


@contextmanager
def _deadline_guard(budget_ms):
    """Serve one dispatched op under the caller's remaining budget. A
    frame that arrives with its budget already spent (0 on the wire) is
    refused before touching the store — DeadlineExceededError serializes
    as a PERMANENT status, so the client never replays it."""
    if budget_ms is None:
        yield
        return
    from janusgraph_tpu.core.deadline import deadline_scope
    from janusgraph_tpu.exceptions import DeadlineExceededError

    if budget_ms <= 0:
        raise DeadlineExceededError(
            "op arrived with its caller deadline already spent"
        )
    with deadline_scope(budget_ms):
        yield


# ------------------------------------------------------------------ encoding
def _pb(out: List[bytes], b: bytes) -> None:
    out.append(struct.pack(">I", len(b)))
    out.append(b)


def _ps(out: List[bytes], s: str) -> None:
    _pb(out, s.encode())


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def u32(self) -> int:
        (v,) = struct.unpack_from(">I", self.data, self.off)
        self.off += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from(">Q", self.data, self.off)
        self.off += 8
        return v

    def u8(self) -> int:
        v = self.data[self.off]
        self.off += 1
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        v = self.data[self.off : self.off + n]
        self.off += n
        return v

    def str_(self) -> str:
        return self.bytes_().decode()


def _encode_entries(out: List[bytes], entries: EntryList) -> None:
    out.append(struct.pack(">I", len(entries)))
    for col, val in entries:
        _pb(out, col)
        _pb(out, val)


def _decode_entries(r: _Reader) -> EntryList:
    n = r.u32()
    return [(r.bytes_(), r.bytes_()) for _ in range(n)]


def _encode_additions(out: List[bytes], entries: EntryList) -> None:
    """Mutation additions: (col, val[, expire_ns]) — a u64 expiry (0 = no
    per-cell TTL) rides every entry so cell-TTL types work over the wire."""
    out.append(struct.pack(">I", len(entries)))
    for e in entries:
        _pb(out, e[0])
        _pb(out, e[1])
        out.append(struct.pack(">Q", e[2] if len(e) >= 3 else 0))


def _decode_additions(r: _Reader) -> EntryList:
    n = r.u32()
    out = []
    for _ in range(n):
        col, val = r.bytes_(), r.bytes_()
        exp = r.u64()
        out.append((col, val, exp) if exp else (col, val))
    return out


def _encode_slice(out: List[bytes], sq: SliceQuery) -> None:
    _pb(out, sq.start)
    _pb(out, sq.end if sq.end is not None else b"")
    out.append(struct.pack(">i", -1 if sq.limit is None else sq.limit))


def _decode_slice(r: _Reader) -> SliceQuery:
    start = r.bytes_()
    end = r.bytes_()
    (limit,) = struct.unpack_from(">i", r.data, r.off)
    r.off += 4
    return SliceQuery(start, end or None, None if limit < 0 else limit)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


# -------------------------------------------------------------------- server
class _Handler(socketserver.BaseRequestHandler):
    #: populated per flagged request by handle(); branch code accrues
    #: measured costs here and _reply prepends them to the OK frame
    _led = None
    _op_t0 = 0

    def handle(self):
        import time as _time

        mgr = self.server.manager  # type: ignore[attr-defined]
        sock = self.request
        pipe = None
        try:
            while True:
                try:
                    head = _recv_exact(sock, 5)
                except ConnectionError:
                    return
                (body_len,) = struct.unpack(">I", head[:4])
                raw = head[4]
                op = raw & ~_FLAG_MASK
                body = _recv_exact(sock, body_len) if body_len else b""
                if raw & _PIPELINE_FLAG:
                    if not getattr(self.server, "pipeline", True):
                        # a pre-pipeline server never strips the 0x10
                        # bit: the flagged op is simply unknown (byte-
                        # identical to real old-server behavior; a
                        # compliant client never sends this)
                        op = raw & ~(
                            _TRACE_FLAG | _LEDGER_FLAG | _DEADLINE_FLAG
                        )
                    else:
                        # pipelined framing (negotiated): every wire
                        # frame runs as one per-connection pool task —
                        # frames complete out of order, each sub-op
                        # replies with its own request id, and a
                        # frame's replies flush in one write
                        from janusgraph_tpu.storage.pipeline import (
                            ServerPipeline,
                            _InlineReply,
                            iter_batch,
                        )

                        if pipe is None:
                            pipe = ServerPipeline(sock, workers=getattr(
                                self.server, "pipeline_workers", 4
                            ))
                        t_arr = _time.monotonic()
                        if op != _OP_BATCH and pipe.serve_inline_ok():
                            # sequential FAST traffic: serve on this
                            # thread — no pool handoff; concurrency and
                            # slow ops ride per-sub-op pool tasks below
                            self._serve_pipelined(
                                mgr, _InlineReply(pipe), raw, body, t_arr
                            )
                            pipe.note_duration(
                                _time.monotonic() - t_arr
                            )
                            continue
                        subs = (
                            list(iter_batch(body))
                            if op == _OP_BATCH else [(raw, body)]
                        )
                        for sub_raw, sub_body in subs:
                            pipe.submit_op(
                                self._serve_pipelined, mgr, sub_raw,
                                sub_body, t_arr,
                            )
                        continue
                ctx = None
                if raw & _TRACE_FLAG:
                    ctx, body = split_trace_prefix(body)
                budget_ms = None
                if raw & _DEADLINE_FLAG:
                    budget_ms, body = split_deadline_prefix(body)
                self._led = {} if raw & _LEDGER_FLAG else None
                self._op_t0 = _time.perf_counter_ns()
                try:
                    # the serving node inherits the caller's remaining
                    # budget: its own retries/backoff (e.g. a layered
                    # remote behind this manager) stop when the budget is
                    # spent, and an op arriving already-expired is refused
                    # without touching the store
                    with _deadline_guard(budget_ms):
                        if ctx is not None:
                            from janusgraph_tpu.observability import tracer

                            # child span under the client's context: the
                            # storage node's ops join the caller's trace
                            with tracer.child_span(
                                ctx,
                                f"store.remote.{_OP_NAMES.get(op, op)}",
                                store_manager=getattr(mgr, "name", ""),
                            ) as sp:
                                self._dispatch(mgr, sock, op, body)
                                if self._led:
                                    # the storage node OWNS these
                                    # measurements: it annotates its own
                                    # span, the client merges the echo
                                    # without re-annotating
                                    sp.annotate(**{
                                        f"ledger.{k}": v
                                        for k, v in self._led.items()
                                        if k != "wall_ns"
                                    })
                        else:
                            self._dispatch(mgr, sock, op, body)
                # graphlint: disable=JG204 -- protocol boundary: the error is serialized to the client as a temporary status frame, and the CLIENT retries
                except (TemporaryBackendError, ConnectionError) as e:
                    self._reply(sock, _STATUS_TEMP, str(e).encode())
                except Exception as e:  # noqa: BLE001 - protocol boundary
                    self._reply(sock, _STATUS_PERM, f"{type(e).__name__}: {e}".encode())
                finally:
                    self._led = None
        except (ConnectionResetError, BrokenPipeError):
            return
        finally:
            if pipe is not None:
                pipe.close()

    def _serve_pipelined(self, mgr, out, raw, body, t_arrival) -> None:
        """One pipelined sub-op: same per-op machinery as the sync path
        (trace child span, deadline guard, ledger echo) with the reply
        addressed by request id into the frame's reply buffer. Runs on
        a pool thread — all state is local, never on the handler
        instance."""
        import time as _time

        op = raw & ~_FLAG_MASK
        (req_id,) = struct.unpack_from(">I", body, 0)
        body = body[4:]
        ctx = None
        if raw & _TRACE_FLAG:
            ctx, body = split_trace_prefix(body)
        budget_ms = None
        if raw & _DEADLINE_FLAG:
            budget_ms, body = split_deadline_prefix(body)
            if budget_ms is not None:
                # time spent queued behind sibling sub-ops counts
                # against THIS op's budget (the sync path's dispatch
                # queue time is ~0, the pipelined path's is not)
                budget_ms -= (_time.monotonic() - t_arrival) * 1000.0
        led = {} if raw & _LEDGER_FLAG else None
        t0 = _time.perf_counter_ns()
        try:
            with _deadline_guard(budget_ms):
                if ctx is not None:
                    from janusgraph_tpu.observability import tracer

                    with tracer.child_span(
                        ctx,
                        f"store.remote.{_OP_NAMES.get(op, op)}",
                        store_manager=getattr(mgr, "name", ""),
                        pipelined=True,
                    ) as sp:
                        payload = self._execute(mgr, op, body, led)
                        if led:
                            sp.annotate(**{
                                f"ledger.{k}": v
                                for k, v in led.items()
                                if k != "wall_ns"
                            })
                else:
                    payload = self._execute(mgr, op, body, led)
            if led is not None:
                from janusgraph_tpu.observability.profiler import (
                    encode_ledger_block,
                )

                led["wall_ns"] = _time.perf_counter_ns() - t0
                payload = encode_ledger_block(led) + payload
            out.reply(req_id, _STATUS_OK, payload)
        # graphlint: disable=JG204 -- protocol boundary: the error is serialized to the client as a temporary status frame addressed to this op's request id, and the CLIENT retries
        except (TemporaryBackendError, ConnectionError) as e:
            out.reply(req_id, _STATUS_TEMP, str(e).encode())
        except Exception as e:  # noqa: BLE001 - protocol boundary
            out.reply(
                req_id, _STATUS_PERM, f"{type(e).__name__}: {e}".encode()
            )

    def _reply(self, sock, status: int, body: bytes) -> None:
        if self._led is not None and status == _STATUS_OK:
            import time as _time

            from janusgraph_tpu.observability.profiler import (
                encode_ledger_block,
            )

            self._led["wall_ns"] = _time.perf_counter_ns() - self._op_t0
            body = encode_ledger_block(self._led) + body
        sock.sendall(struct.pack(">IB", len(body), status) + body)

    def _dispatch(self, mgr, sock, op: int, body: bytes) -> None:
        r = _Reader(body)
        if op == _OP_FEATURES:
            f = mgr.features
            import json

            feats = {
                k: getattr(f, k)
                for k in (
                    "ordered_scan", "unordered_scan", "multi_query",
                    "batch_mutation", "key_consistent", "persists",
                    "cell_ttl", "timestamps",
                )
            }
            # protocol feature bits: this server accepts 0x80-flagged
            # frames carrying a trace header, 0x40-flagged frames asking
            # for a resource-ledger echo, 0x20-flagged frames carrying
            # a deadline prefix, and 0x10-flagged pipelined frames
            # (absent on old servers, so new clients degrade cleanly in
            # every dimension)
            if getattr(self.server, "trace_propagation", True):
                feats["trace"] = True
            if getattr(self.server, "ledger_echo", True):
                feats["ledger"] = True
            if getattr(self.server, "deadline_propagation", True):
                feats["deadline"] = True
            if getattr(self.server, "pipeline", True):
                feats["pipeline"] = True
            self._reply(sock, _STATUS_OK, json.dumps(feats).encode())
            return
        if op in (_OP_SCAN_ALL, _OP_SCAN_RANGE):
            txh = mgr.begin_transaction()
            store = mgr.open_database(r.str_())
            if op == _OP_SCAN_RANGE:
                key_start = r.bytes_()
                key_end = r.bytes_()
                sq = _decode_slice(r)
                query = KeyRangeQuery(key_start, key_end, sq)
            else:
                query = _decode_slice(r)
            # stream rows after an OK frame; [1][row]* then [0]
            self._reply(sock, _STATUS_OK, b"")
            for key, entries in store.get_keys(query, txh):
                out = [b"\x01"]
                _pb(out, key)
                _encode_entries(out, entries)
                sock.sendall(b"".join(out))
            sock.sendall(b"\x00")
            return
        self._reply(sock, _STATUS_OK, self._execute(mgr, op, body, self._led))

    def _execute(self, mgr, op: int, body: bytes, led) -> bytes:
        """One non-streaming op -> OK payload bytes. Shared by the sync
        dispatch and the pipelined per-sub-op path; raising serializes
        as a status frame in either framing."""
        r = _Reader(body)
        txh = mgr.begin_transaction()
        if op == _OP_GET_SLICE:
            store = mgr.open_database(r.str_())
            key = r.bytes_()
            sq = _decode_slice(r)
            entries = store.get_slice(KeySliceQuery(key, sq), txh)
            if led is not None:
                led["cells_read"] = len(entries)
                led["bytes_read"] = sum(
                    len(c) + len(v) for c, v in entries
                )
            out: List[bytes] = []
            _encode_entries(out, entries)
            return b"".join(out)
        if op == _OP_GET_SLICE_MULTI:
            store = mgr.open_database(r.str_())
            nkeys = r.u32()
            keys = [r.bytes_() for _ in range(nkeys)]
            sq = _decode_slice(r)
            res = store.get_slice_multi(keys, sq, txh)
            if led is not None:
                led["cells_read"] = sum(len(e) for e in res.values())
                led["bytes_read"] = sum(
                    len(c) + len(v)
                    for e in res.values() for c, v in e
                )
            out = [struct.pack(">I", len(keys))]
            for k in keys:
                _pb(out, k)
                _encode_entries(out, res.get(k, []))
            return b"".join(out)
        if op == _OP_MUTATE:
            store = mgr.open_database(r.str_())
            key = r.bytes_()
            adds = _decode_additions(r)
            ndels = r.u32()
            dels = [r.bytes_() for _ in range(ndels)]
            if led is not None:
                led["cells_written"] = len(adds) + ndels
                led["bytes_written"] = sum(
                    len(e[0]) + len(e[1]) for e in adds
                )
            store.mutate(key, adds, dels, txh)
            txh.commit()
            return b""
        if op == _OP_MUTATE_MANY:
            nstores = r.u32()
            muts: Dict[str, Dict[bytes, KCVMutation]] = {}
            for _ in range(nstores):
                sname = r.str_()
                nrows = r.u32()
                rows: Dict[bytes, KCVMutation] = {}
                for _ in range(nrows):
                    key = r.bytes_()
                    adds = _decode_additions(r)
                    ndels = r.u32()
                    dels = [r.bytes_() for _ in range(ndels)]
                    m = KCVMutation()
                    m.additions.extend(adds)
                    m.deletions.extend(dels)
                    rows[key] = m
                muts[sname] = rows
            if led is not None:
                led["cells_written"] = sum(
                    len(m.additions) + len(m.deletions)
                    for rows in muts.values() for m in rows.values()
                )
                led["bytes_written"] = sum(
                    len(e[0]) + len(e[1])
                    for rows in muts.values()
                    for m in rows.values() for e in m.additions
                )
            mgr.mutate_many(muts, txh)
            txh.commit()
            return b""
        if op == _OP_CLEAR:
            mgr.clear_storage()
            return b""
        if op == _OP_EXISTS:
            return b"\x01" if mgr.exists() else b"\x00"
        if op in (_OP_FEATURES, _OP_SCAN_ALL, _OP_SCAN_RANGE):
            # streaming/negotiation ops never ride pipelined frames
            raise PermanentBackendError(
                f"op {_OP_NAMES.get(op, op)} is not pipelineable"
            )
        raise PermanentBackendError(f"unknown op {op}")


class RemoteStoreServer:
    """Serve a KCVS manager over TCP (threaded; port 0 = ephemeral).
    ``trace_propagation=False`` serves the pre-trace features payload,
    ``ledger_echo=False`` the pre-ledger one, ``deadline_propagation=
    False`` the pre-deadline one, ``pipeline=False`` the pre-pipeline
    one — "old-featured" servers for compatibility tests and staged
    rollouts. ``pipeline_workers`` sizes the per-connection dispatch
    pool for pipelined frames (out-of-order completion depth)."""

    def __init__(self, manager, host: str = "127.0.0.1", port: int = 0,
                 trace_propagation: bool = True, ledger_echo: bool = True,
                 deadline_propagation: bool = True, pipeline: bool = True,
                 pipeline_workers: int = 4):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _Handler)
        self._srv.manager = manager  # type: ignore[attr-defined]
        self._srv.trace_propagation = trace_propagation  # type: ignore[attr-defined]
        self._srv.ledger_echo = ledger_echo  # type: ignore[attr-defined]
        self._srv.deadline_propagation = deadline_propagation  # type: ignore[attr-defined]
        self._srv.pipeline = pipeline  # type: ignore[attr-defined]
        self._srv.pipeline_workers = pipeline_workers  # type: ignore[attr-defined]
        self.manager = manager
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.server_address  # type: ignore[return-value]

    def start(self) -> "RemoteStoreServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="kcvs-server"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# -------------------------------------------------------------------- client
class _Conn:
    """One pooled connection; serialized per-request by its own lock."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 30.0):
        self.host, self.port = host, port
        self.connect_timeout_s = connect_timeout_s
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None

    def _connect(self):
        s = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = s

    def request(self, op: int, body: bytes) -> Tuple[int, bytes, socket.socket]:
        """Send one request; return (status, body, sock) — sock is needed by
        streaming (scan) callers who continue reading row frames."""
        if self.sock is None:
            try:
                self._connect()
            except OSError as e:
                raise TemporaryBackendError(f"connect failed: {e}") from e
        try:
            self.sock.sendall(struct.pack(">IB", len(body), op) + body)
            head = _recv_exact(self.sock, 5)
            (blen,) = struct.unpack(">I", head[:4])
            status = head[4]
            payload = _recv_exact(self.sock, blen) if blen else b""
            return status, payload, self.sock
        except (OSError, ConnectionError) as e:
            # drop the broken socket so the next attempt redials
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            raise TemporaryBackendError(f"request failed: {e}") from e


# hot-path handles (resolved once; per-op `from x import y` contends on
# the import lock across submitting threads)
_DEADLINE_MOD = None
_TRACER = None
_PROFILER_MOD = None


def _hot_mods():
    global _DEADLINE_MOD, _TRACER, _PROFILER_MOD
    if _DEADLINE_MOD is None:
        from janusgraph_tpu.core import deadline as _d
        from janusgraph_tpu.observability import tracer as _t
        from janusgraph_tpu.observability import profiler as _p
        _DEADLINE_MOD, _TRACER, _PROFILER_MOD = _d, _t, _p
    return _DEADLINE_MOD, _TRACER, _PROFILER_MOD


class RemoteKCVStore(KeyColumnValueStore):
    def __init__(self, manager: "RemoteStoreManager", name: str):
        self._manager = manager
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def _count_read(self, fields, entries) -> None:
        """Fallback accounting against an old (pre-ledger) server: no echo
        came back, so count the decoded entries locally as the PRIMARY
        accrual (annotates the client-side span). A ledger-disabled client
        (resource_ledger=False — the "old client" compatibility mode)
        stays entirely ledger-oblivious."""
        if fields is not None or not self._manager.resource_ledger:
            return
        from janusgraph_tpu.observability.profiler import (
            accrue,
            current_ledger,
        )

        if current_ledger() is not None:
            accrue(
                cells_read=len(entries),
                bytes_read=sum(len(c) + len(v) for c, v in entries),
            )

    def get_slice(self, query: KeySliceQuery, txh) -> EntryList:
        sl: List[bytes] = []
        _encode_slice(sl, query.slice)
        slice_bytes = b"".join(sl)
        out: List[bytes] = []
        _ps(out, self._name)
        _pb(out, query.key)
        # the merge hint lets the pipeline writer coalesce same-slice
        # getSlice ops from concurrent callers into ONE getSliceMulti
        # wire frame; the response demuxes back per key
        payload, fields = self._manager._call_ledger(
            _OP_GET_SLICE, b"".join(out) + slice_bytes,
            merge=("gs", self._name, query.key, slice_bytes),
        )
        entries = _decode_entries(_Reader(payload))
        self._count_read(fields, entries)
        return entries

    def get_slice_multi(self, keys, slice_query, txh):
        mgr = self._manager
        keys = list(keys)
        mux = (
            mgr._mux_for(_OP_GET_SLICE_MULTI)
            if (len(keys) > mgr.pipeline_multi_chunk
                and mgr._should_pipeline())
            else None
        )
        if mux is not None:
            # pipelined path under CONCURRENCY: chunk the key set into
            # sibling sub-frames gathered over the shared pipelined
            # sockets — in-flight chunks from many callers interleave
            # on few connections instead of convoying on the pool locks
            return self._slice_multi_pipelined(keys, slice_query)
        # client-side parallel multi-slice (reference: Backend.java:215-221
        # parallelizes multi-key reads on an executor; storage.
        # parallel-backend-ops): split the key set across the connection
        # pool so independent sockets serve chunks concurrently
        nconn = len(mgr._pool)
        if (mgr.parallel_ops and nconn > 1
                and len(keys) > mgr.parallel_slice_factor * nconn):
            chunk = -(-len(keys) // nconn)
            parts = [keys[i:i + chunk] for i in range(0, len(keys), chunk)]
            merged = {}
            for res in mgr._executor().map(
                lambda part: self._slice_multi_call(part, slice_query),
                parts,
            ):
                merged.update(res)
            return merged
        return self._slice_multi_call(keys, slice_query)

    def _multi_body(self, keys, slice_query) -> bytes:
        out: List[bytes] = []
        _ps(out, self._name)
        out.append(struct.pack(">I", len(keys)))
        for k in keys:
            _pb(out, k)
        _encode_slice(out, slice_query)
        return b"".join(out)

    def _slice_multi_pipelined(self, keys, slice_query):
        mgr = self._manager
        chunk = mgr.pipeline_multi_chunk
        parts = [keys[i:i + chunk] for i in range(0, len(keys), chunk)]
        results = mgr._pipe_gather(
            _OP_GET_SLICE_MULTI,
            [self._multi_body(p, slice_query) for p in parts],
        )
        merged = {}
        uncounted: List = []
        for payload, fields in results:
            res = _decode_multi_payload(payload)
            merged.update(res)
            if fields is None:
                # this chunk came back without a server echo: count its
                # decoded entries locally (per-chunk attribution)
                uncounted.extend(
                    e for entries in res.values() for e in entries
                )
        if uncounted:
            self._count_read(None, uncounted)
        return merged

    def _slice_multi_call(self, keys, slice_query):
        payload, fields = self._manager._call_ledger(
            _OP_GET_SLICE_MULTI, self._multi_body(keys, slice_query)
        )
        res = _decode_multi_payload(payload)
        self._count_read(
            fields, [e for entries in res.values() for e in entries]
        )
        return res

    def mutate(self, key, additions, deletions, txh) -> None:
        row: List[bytes] = []
        _pb(row, key)
        _encode_additions(row, additions)
        row.append(struct.pack(">I", len(deletions)))
        for col in deletions:
            _pb(row, col)
        row_bytes = b"".join(row)
        out: List[bytes] = []
        _ps(out, self._name)
        # the merge hint lets the writer fold same-store mutates from
        # concurrent callers into ONE mutateMany wire frame (the row
        # layout is shared between the two ops by construction)
        _payload, fields = self._manager._call_ledger(
            _OP_MUTATE, b"".join(out) + row_bytes,
            merge=("mu", self._name, key, row_bytes),
        )
        if fields is None and self._manager.resource_ledger:
            from janusgraph_tpu.observability.profiler import (
                accrue,
                current_ledger,
            )

            if current_ledger() is not None:
                accrue(
                    cells_written=len(additions) + len(deletions),
                    bytes_written=sum(
                        len(e[0]) + len(e[1]) for e in additions
                    ),
                )

    def get_keys(self, query, txh) -> Iterator[Tuple[bytes, EntryList]]:
        out: List[bytes] = []
        _ps(out, self._name)
        if isinstance(query, KeyRangeQuery):
            op = _OP_SCAN_RANGE
            _pb(out, query.key_start)
            _pb(out, query.key_end)
            _encode_slice(out, query.slice)
        else:
            op = _OP_SCAN_ALL
            _encode_slice(out, query)
        # each scan gets a DEDICATED connection: the row stream occupies the
        # socket until exhausted, and a consumer abandoning the generator
        # mid-stream must not leave unread row bytes to desync a pooled
        # connection's next request — the private socket just closes.
        # Scans are never ledger-flagged (the row stream can't carry an
        # echo block); the client counts what it decodes instead.
        op, frame, _ = self._manager._frame(
            op, b"".join(out), allow_ledger=False
        )
        conn = _Conn(self._manager.host, self._manager.port)
        cells = scanned_bytes = 0
        try:
            status, payload, sock = conn.request(op, frame)
            if status != _STATUS_OK:
                _raise_status(status, payload)
            while True:
                marker = _recv_exact(sock, 1)
                if marker == b"\x00":
                    break
                key = _recv_exact(sock, struct.unpack(
                    ">I", _recv_exact(sock, 4))[0])
                (n,) = struct.unpack(">I", _recv_exact(sock, 4))
                entries = []
                for _ in range(n):
                    (cl,) = struct.unpack(">I", _recv_exact(sock, 4))
                    col = _recv_exact(sock, cl)
                    (vl,) = struct.unpack(">I", _recv_exact(sock, 4))
                    val = _recv_exact(sock, vl)
                    entries.append((col, val))
                cells += n
                scanned_bytes += len(key) + sum(
                    len(c) + len(v) for c, v in entries
                )
                yield key, entries
        finally:
            if conn.sock is not None:
                try:
                    conn.sock.close()
                except OSError:
                    pass
            if (cells or scanned_bytes) and self._manager.resource_ledger:
                from janusgraph_tpu.observability.profiler import accrue

                accrue(cells_read=cells, bytes_read=scanned_bytes)


def _raise_status(status: int, payload: bytes):
    msg = payload.decode("utf-8", "replace")
    if status == _STATUS_TEMP:
        raise TemporaryBackendError(msg)
    raise PermanentBackendError(msg)


def _entries_payload(entries: EntryList) -> bytes:
    """Entries -> a single-getSlice OK payload (the pipeline demuxes a
    merged multi response into per-op payloads byte-identical to an
    unmerged reply, so callers decode one way)."""
    out: List[bytes] = []
    _encode_entries(out, entries)
    return b"".join(out)


def _decode_multi_payload(payload: bytes) -> Dict[bytes, EntryList]:
    """A getSliceMulti OK payload -> {key: entries}."""
    r = _Reader(payload)
    n = r.u32()
    res: Dict[bytes, EntryList] = {}
    for _ in range(n):
        key = r.bytes_()
        res[key] = _decode_entries(r)
    return res


class RemoteStoreManager(KeyColumnValueStoreManager):
    """Client-side manager speaking the remote KCVS protocol."""

    def __init__(self, host: str, port: int, pool_size: int = 4,
                 retry_time_s: float = 10.0,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 parallel_ops: bool = True,
                 connect_timeout_s: float = 30.0,
                 max_attempts: int = 0,
                 parallel_slice_factor: int = 2,
                 breaker_enabled: bool = False,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_ms: float = 1000.0,
                 breaker_half_open_probes: int = 1,
                 trace_propagation: bool = True,
                 resource_ledger: bool = True,
                 deadline_propagation: bool = True,
                 pipeline: bool = True,
                 pipeline_connections: int = 2,
                 pipeline_depth: int = 128,
                 pipeline_max_batch: int = 64,
                 pipeline_multi_chunk: int = 512,
                 pipeline_stall_ms: float = 200.0,
                 pipeline_coalesce_us: float = 150.0):
        self.host, self.port = host, port
        #: metrics.trace-propagation — attach the ambient TraceContext to
        #: op frames, but ONLY once the server's features payload
        #: negotiated the bit (None = not yet negotiated)
        self.trace_propagation = trace_propagation
        self._remote_trace: Optional[bool] = None
        #: metrics.resource-ledger — flag ops for a server-side cost echo
        #: (same negotiation discipline as tracing)
        self.resource_ledger = resource_ledger
        self._remote_ledger: Optional[bool] = None
        #: server.deadline.propagation — forward the ambient deadline's
        #: remaining budget on op frames (same negotiation discipline)
        self.deadline_propagation = deadline_propagation
        self._remote_deadline: Optional[bool] = None
        #: storage.remote.pipeline — route ops over pipelined async
        #: framing (storage/pipeline.py) once the server negotiates the
        #: `pipeline` feature bit; the sync pool stays the fallback for
        #: old servers, scans, and negotiation itself
        self.pipeline = pipeline
        self.pipeline_connections = pipeline_connections
        self.pipeline_depth = pipeline_depth
        self.pipeline_max_batch = pipeline_max_batch
        #: keys-per-sub-frame chunk for pipelined multi-slice reads:
        #: big prefetch batches split into chunks served concurrently
        #: by the server's per-connection pool
        self.pipeline_multi_chunk = pipeline_multi_chunk
        self.pipeline_stall_ms = pipeline_stall_ms
        self.pipeline_coalesce_us = pipeline_coalesce_us
        self._remote_pipeline: Optional[bool] = None
        self._mux = None
        self._mux_lock = threading.Lock()
        self._pipeline_fallback_noted = False
        #: concurrent _call_ledger calls right now (GIL-atomic += is
        #: fidelity enough): the ADAPTIVE routing signal — a manager
        #: with a single sequential caller takes the sync fast path
        #: (identical cost to the pre-pipeline client), and the
        #: pipelined mux engages the moment callers overlap
        self._calls_active = 0
        #: EWMA of recent op service time: pipelining pays when per-op
        #: LATENCY dominates (in-flight demand beyond the connection
        #: budget would otherwise convoy on the pool locks); against a
        #: fast backend the sync pool already schedules optimally and
        #: the mux machinery would only add CPU
        self._op_ewma_s = 0.0
        #: the KCVS client accounts cells/bytes itself (echo or local
        #: decode), so BackendTransaction must not count the same ops
        self.ledger_self_accounting = True
        self.retry_time_s = retry_time_s
        self.connect_timeout_s = connect_timeout_s
        self.max_attempts = max_attempts
        #: storage.parallel-backend-ops — client-side multi-slice fan-out
        self.parallel_ops = parallel_ops
        #: storage.remote.parallel-slice-factor — fan-out fires past
        #: factor x pool connections (below that, chunking overhead wins)
        self.parallel_slice_factor = parallel_slice_factor
        self._pool_executor = None
        self._executor_lock = threading.Lock()
        # per-CLIENT retry backoff (storage.backoff-base-ms/-max-ms):
        # tuning one graph's backend must not affect others in-process
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._pool = [
            _Conn(host, port, connect_timeout_s) for _ in range(pool_size)
        ]
        self._pool_lock = threading.Lock()
        self._pool_idx = 0
        self._stores: Dict[str, RemoteKCVStore] = {}
        self._features: Optional[StoreFeatures] = None
        # circuit breaker (storage.breaker.*): a DOWN server makes every
        # attempt fail fast after the threshold instead of each caller
        # burning its full retry budget against a dead endpoint
        self.breaker = None
        if breaker_enabled:
            from janusgraph_tpu.storage.circuit import CircuitBreaker

            self.breaker = CircuitBreaker(
                "storage.remote",
                failure_threshold=breaker_failure_threshold,
                reset_timeout_s=breaker_reset_ms / 1000.0,
                half_open_probes=breaker_half_open_probes,
            )

    def _executor(self):
        """Persistent fan-out pool for parallel multi-slice reads — per-call
        ThreadPoolExecutor creation would pay thread spawn/join on every
        batched backend read (hot under prefetch-heavy traversals)."""
        if self._pool_executor is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._executor_lock:
                if self._pool_executor is None:
                    self._pool_executor = ThreadPoolExecutor(
                        max_workers=len(self._pool),
                        thread_name_prefix="kcvs-multislice",
                    )
        return self._pool_executor

    def _acquire(self) -> _Conn:
        with self._pool_lock:
            conn = self._pool[self._pool_idx % len(self._pool)]
            self._pool_idx += 1
            return conn

    def _frame_parts(
        self, op: int, allow_ledger: bool = True
    ) -> Tuple[int, bytes, bool, Optional[float]]:
        """(flags, trace_prefix, want_ledger, expires_at): the ambient
        trace context is encoded (trace flag) when there is one AND the
        server negotiated the trace feature bit; the ledger flag is set
        when an ambient ResourceLedger exists AND the server negotiated
        the ledger bit; the deadline flag when an ambient deadline exists
        AND the server negotiated it — the REMAINING budget is carried as
        ``expires_at`` (monotonic) and encoded at SEND time, so queue
        dwell in the pipelined path keeps charging the op. The first
        qualifying call triggers the (lazy) features negotiation; a
        server we can't reach yet just stays un-negotiated for this
        frame. ``allow_ledger=False`` for streaming ops (scans) — their
        response cannot carry a block, the client counts decoded rows
        instead."""
        if op == _OP_FEATURES:
            return 0, b"", False, None
        import time as _time

        _dl, tracer, _prof = _hot_mods()
        ctx = tracer.current_context() if self.trace_propagation else None
        led = (
            _prof.current_ledger()
            if (allow_ledger and self.resource_ledger)
            else None
        )
        budget = _dl.remaining_ms() if self.deadline_propagation else None
        if ctx is None and led is None and budget is None:
            return 0, b"", False, None
        if (self._remote_trace is None or self._remote_ledger is None
                or self._remote_deadline is None):
            try:
                _ = self.features
            # graphlint: disable=JG204 -- negotiation is best-effort: the frame just goes unflagged, and the op itself will surface the failure through its own retry guard
            except (TemporaryBackendError, PermanentBackendError):
                return 0, b"", False, None
        flags = 0
        prefix = b""
        expires_at = None
        if budget is not None and self._remote_deadline:
            flags |= _DEADLINE_FLAG
            expires_at = _time.monotonic() + budget / 1000.0
        if ctx is not None and self._remote_trace:
            flags |= _TRACE_FLAG
            prefix = encode_trace_prefix(ctx)
        if led is not None and self._remote_ledger:
            flags |= _LEDGER_FLAG
        return flags, prefix, bool(flags & _LEDGER_FLAG), expires_at

    def _frame(
        self, op: int, body: bytes, allow_ledger: bool = True
    ) -> Tuple[int, bytes, bool]:
        """Synchronous-framing view of _frame_parts: (op|flags, body with
        prefixes prepended, want_ledger). The deadline prefix is encoded
        now — the sync path sends immediately. Trace prefix OUTSIDE the
        deadline prefix (the server strips trace first)."""
        import time as _time

        flags, prefix, want_ledger, expires_at = self._frame_parts(
            op, allow_ledger
        )
        if flags & _DEADLINE_FLAG:
            prefix = prefix + encode_deadline_prefix(
                max(0.0, (expires_at - _time.monotonic()) * 1000.0)
            )
        return op | flags, prefix + body, want_ledger

    def _call(self, op: int, body: bytes) -> bytes:
        """One wire call; a ledger echo on the response is merged into the
        ambient ledger (see _call_ledger for callers that need to know
        whether the echo happened)."""
        payload, _ = self._call_ledger(op, body)
        return payload

    def _mux_for(self, op: int):
        """The pipeline mux when this op should ride pipelined framing:
        enabled, negotiated, and not a negotiation/streaming op. Returns
        None on the sync path. A server that did NOT negotiate the bit
        notes a one-time negotiation fallback (counter + flight)."""
        if not self.pipeline or op == _OP_FEATURES:
            return None
        if self._remote_pipeline is None:
            try:
                _ = self.features
            # graphlint: disable=JG204 -- negotiation is best-effort: the op falls back to the sync path, whose own retry guard surfaces the failure
            except (TemporaryBackendError, PermanentBackendError):
                return None
        if not self._remote_pipeline:
            if not self._pipeline_fallback_noted:
                self._pipeline_fallback_noted = True
                from janusgraph_tpu.observability import (
                    flight_recorder,
                    registry,
                )

                registry.counter(
                    "storage.remote.pipeline.fallbacks"
                ).inc()
                flight_recorder.record(
                    "pipeline_fallback",
                    endpoint=f"{self.host}:{self.port}",
                    protocol="storage.remote",
                    reason="server did not negotiate the pipeline bit",
                )
            return None
        if self._mux is None:
            from janusgraph_tpu.storage.pipeline import PipelineMux

            with self._mux_lock:
                if self._mux is None:
                    from janusgraph_tpu.observability.profiler import (
                        split_ledger_block,
                    )

                    self._mux = PipelineMux(
                        self.host, self.port,
                        connections=self.pipeline_connections,
                        connect_timeout_s=self.connect_timeout_s,
                        depth=self.pipeline_depth,
                        max_batch=self.pipeline_max_batch,
                        stall_ms=self.pipeline_stall_ms,
                        coalesce_us=self.pipeline_coalesce_us,
                        metric_prefix="storage.remote",
                        batch_op=_OP_BATCH,
                        split_ledger=split_ledger_block,
                        encode_entries=_entries_payload,
                        decode_multi=_decode_multi_payload,
                    )
        return self._mux

    def _result_timeout(self) -> float:
        """Belt-and-suspenders bound on waiting for a pipelined response:
        the reader's socket timeout tears the connection down first in
        any real hang, failing the future with a temporary error."""
        return self.connect_timeout_s + self.retry_time_s

    #: ops slower than this engage pipelined routing under concurrency —
    #: a real storage node's service time (media + fabric RTT), not a
    #: loopback echo: against a microsecond backend the sync pool
    #: already schedules optimally and the mux would only add CPU
    _PIPELINE_LATENCY_GATE_S = 0.0006

    def _call_ledger(
        self, op: int, body: bytes, merge: Optional[tuple] = None
    ) -> Tuple[bytes, Optional[dict]]:
        self._calls_active += 1
        try:
            return self._call_ledger_inner(op, body, merge)
        finally:
            self._calls_active -= 1

    def _should_pipeline(self) -> bool:
        """Adaptive routing: pipelined framing engages when (a) callers
        overlap beyond what the sync pool can serve one-op-per-lock AND
        (b) per-op service time is latency-dominated — or when the mux
        already has ops in flight (stay engaged through a burst). A
        sequential caller, or a microsecond-fast backend, keeps the sync
        fast path and its exact pre-pipeline cost. Checked BEFORE any
        negotiation, so an idle/sequential manager performs no extra
        wire attempts (breaker accounting stays one event per op)."""
        if not self.pipeline:
            return False
        if self._mux is not None and self._mux.busy():
            return True
        return (
            self._calls_active > len(self._pool)
            and self._op_ewma_s > self._PIPELINE_LATENCY_GATE_S
        )

    def _call_ledger_inner(
        self, op: int, body: bytes, merge: Optional[tuple] = None
    ) -> Tuple[bytes, Optional[dict]]:
        mux = self._mux_for(op) if self._should_pipeline() else None
        if mux is not None:
            from janusgraph_tpu.storage.pipeline import WireOp

            flags, prefix, want_ledger, expires_at = self._frame_parts(op)
            item = WireOp(
                op, flags, prefix, body, want_ledger=want_ledger,
                merge=merge, expires_at=expires_at,
            )
            timeout = self._result_timeout()

            def attempt():
                # one submit+wait is one network attempt: a per-op
                # failure (connection loss, temp status, injected fault)
                # fails THIS op's future only — sibling in-flight ops
                # complete, and the breaker counts exactly this op
                return mux.submit(item).result(timeout)

            guarded = attempt
            if self.breaker is not None:
                guarded = lambda: self.breaker.call(attempt)  # noqa: E731
            payload, fields = backend_op.execute(
                guarded,
                max_time_s=self.retry_time_s,
                base_delay_s=self.backoff_base_s,
                max_delay_s=self.backoff_max_s,
                max_attempts=self.max_attempts,
            )
            if want_ledger and fields is not None:
                from janusgraph_tpu.observability.profiler import merge_echo

                # the reader thread split the echo; the MERGE happens
                # here on the caller's thread, inside its ambient ledger
                merge_echo(fields, layer="store.remote")
            return payload, fields
        op, body, want_ledger = self._frame(op, body)

        def attempt() -> bytes:
            import time as _time

            conn = self._acquire()
            with conn.lock:
                # the per-connection lock serializes request/response
                # framing on one socket — the SYNC path for sequential
                # callers, fast backends, old servers, and disabled
                # pipelining; the pipelined mux above engages when
                # latency-dominated concurrency outgrows the pool
                t0 = _time.monotonic()
                # graphlint: disable=JG203 -- re-scoped (ISSUE 11): adaptive/negotiated fallback only — conn.lock serializes sync framing on this socket; latency-dominated concurrency rides the pipelined mux instead
                status, payload, _sock = conn.request(op, body)
                # the true round-trip service time (lock wait excluded):
                # the adaptive gate's latency signal
                self._op_ewma_s = (
                    0.9 * self._op_ewma_s
                    + 0.1 * (_time.monotonic() - t0)
                )
            if status != _STATUS_OK:
                _raise_status(status, payload)
            return payload

        guarded = attempt
        if self.breaker is not None:
            # breaker INSIDE the retry guard: each network attempt is one
            # breaker event, and an open circuit raises CircuitOpenError
            # (permanent to the guard) so callers fail fast instead of
            # spinning out their whole backoff budget
            guarded = lambda: self.breaker.call(attempt)  # noqa: E731
        payload = backend_op.execute(
            guarded,
            max_time_s=self.retry_time_s,
            base_delay_s=self.backoff_base_s,
            max_delay_s=self.backoff_max_s,
            max_attempts=self.max_attempts,
        )
        fields = None
        if want_ledger:
            from janusgraph_tpu.observability.profiler import (
                merge_echo,
                split_ledger_block,
            )

            fields, payload = split_ledger_block(payload)
            # the storage node measured (and span-annotated) these costs;
            # merge them into the caller's ledger without re-annotating
            merge_echo(fields, layer="store.remote")
        return payload, fields

    def _pipe_gather(
        self, op: int, bodies: List[bytes]
    ) -> List[Tuple[bytes, Optional[dict]]]:
        """Submit many sibling ops concurrently over the mux and gather
        (payload, fields) per op. With the breaker enabled the ops run
        through the standard guarded path serially instead, so every
        network attempt stays one breaker event."""
        mux = self._mux_for(op)
        if mux is None or self.breaker is not None:
            return [self._call_ledger(op, b) for b in bodies]
        from janusgraph_tpu.storage.pipeline import WireOp

        flags, prefix, want_ledger, expires_at = self._frame_parts(op)
        items = [
            WireOp(op, flags, prefix, b, want_ledger=want_ledger,
                   expires_at=expires_at)
            for b in bodies
        ]
        futs = [mux.submit(it) for it in items]
        timeout = self._result_timeout()
        out: List[Tuple[bytes, Optional[dict]]] = []
        for it, fut in zip(items, futs):
            try:
                out.append(fut.result(timeout))
            except TemporaryBackendError:
                # replay just this op through the retry guard; siblings
                # already in flight are unaffected
                out.append(backend_op.execute(
                    lambda it=it: mux.submit(it).result(timeout),
                    max_time_s=self.retry_time_s,
                    base_delay_s=self.backoff_base_s,
                    max_delay_s=self.backoff_max_s,
                    max_attempts=self.max_attempts,
                ))
        if want_ledger:
            from janusgraph_tpu.observability.profiler import merge_echo

            for _payload, fields in out:
                if fields is not None:
                    merge_echo(fields, layer="store.remote")
        return out

    @property
    def features(self) -> StoreFeatures:
        if self._features is None:
            import json

            remote = json.loads(self._call(_OP_FEATURES, b"").decode())
            # protocol capabilities, not store features: a missing key is
            # an old server — trace headers / ledger / deadline /
            # pipeline flags are never sent
            self._remote_trace = bool(remote.pop("trace", False))
            self._remote_ledger = bool(remote.pop("ledger", False))
            self._remote_deadline = bool(remote.pop("deadline", False))
            self._remote_pipeline = bool(remote.pop("pipeline", False))
            self._features = StoreFeatures(
                distributed=True,
                network_attached=True,  # peers beyond this process can write
                locking=False,       # consistent-key locker wraps this store
                transactional=False,  # autocommit per request (CQL model)
                multi_query=True,
                batch_mutation=True,
                **{k: v for k, v in remote.items()
                   if k not in ("multi_query", "batch_mutation")},
            )
        return self._features

    @property
    def name(self) -> str:
        return f"remote({self.host}:{self.port})"

    def open_database(self, name: str) -> RemoteKCVStore:
        if name not in self._stores:
            self._stores[name] = RemoteKCVStore(self, name)
        return self._stores[name]

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return StoreTransaction(config)

    def mutate_many(self, mutations, txh) -> None:
        out: List[bytes] = [struct.pack(">I", len(mutations))]
        for sname, rows in mutations.items():
            _ps(out, sname)
            out.append(struct.pack(">I", len(rows)))
            for key, m in rows.items():
                _pb(out, key)
                _encode_additions(out, m.additions)
                out.append(struct.pack(">I", len(m.deletions)))
                for col in m.deletions:
                    _pb(out, col)
        _payload, fields = self._call_ledger(_OP_MUTATE_MANY, b"".join(out))
        if fields is None and self.resource_ledger:
            from janusgraph_tpu.observability.profiler import (
                accrue,
                current_ledger,
            )

            if current_ledger() is not None:
                accrue(
                    cells_written=sum(
                        len(m.additions) + len(m.deletions)
                        for rows in mutations.values()
                        for m in rows.values()
                    ),
                    bytes_written=sum(
                        len(e[0]) + len(e[1])
                        for rows in mutations.values()
                        for m in rows.values() for e in m.additions
                    ),
                )

    def close(self) -> None:
        if self._mux is not None:
            self._mux.close()
            self._mux = None
        if self._pool_executor is not None:
            self._pool_executor.shutdown(wait=False)
            self._pool_executor = None
        for conn in self._pool:
            with conn.lock:
                if conn.sock is not None:
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
                    conn.sock = None

    def clear_storage(self) -> None:
        self._call(_OP_CLEAR, b"")

    def exists(self) -> bool:
        return self._call(_OP_EXISTS, b"") == b"\x01"
