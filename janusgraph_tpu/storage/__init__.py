from janusgraph_tpu.storage.kcvs import (  # noqa: F401
    Entry,
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager  # noqa: F401
