"""Ordered key-value SPI + KCVS adapter.

The reference's BerkeleyJE backend is an *ordered key-value* store adapted to
the KCVS contract by concatenating row key and column into one composite key
(reference: diskstorage/keycolumnvalue/keyvalue/OrderedKeyValueStoreAdapter.java:389,
KeyValueStore SPI in the same package). Same design here: an
`OrderedKeyValueStore` exposes get/insert/delete/range-scan over single keys;
`OrderedKVAdapter` layers sorted wide rows on top via an order-preserving
composite encoding, so any ordered KV engine (the persistent LocalKVStore,
an LSM, a future C++ engine) becomes a full KCVS backend.
"""

from __future__ import annotations

import abc
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.storage.kcvs import (
    EntryList,
    KCVMutation,
    KeyColumnValueStore,
    KeyColumnValueStoreManager,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    StoreFeatures,
    StoreTransaction,
)

# ---------------------------------------------------------------- composite
# Order-preserving prefix-free key encoding: 0x00 in the row key escapes to
# 0x00 0xFF, the key terminates with 0x00 0x00, the column follows verbatim.
# Escape (0xFF) sorts above terminator (0x00), so for any keys a < b every
# composite of a sorts before every composite of b, and within one key the
# composites sort by column — slices become contiguous KV ranges.

_TERM = b"\x00\x00"


def encode_key(key: bytes) -> bytes:
    return key.replace(b"\x00", b"\x00\xff") + _TERM


def encode_composite(key: bytes, column: bytes) -> bytes:
    return encode_key(key) + column


def decode_composite(composite: bytes) -> Tuple[bytes, bytes]:
    i = 0
    out = bytearray()
    while True:
        j = composite.index(b"\x00", i)
        out += composite[i:j]
        nxt = composite[j + 1]
        if nxt == 0x00:  # terminator
            return bytes(out), composite[j + 2:]
        out += b"\x00"  # escaped zero
        i = j + 2


# --------------------------------------------------------------------- SPI

class OrderedKeyValueStore(abc.ABC):
    """Sorted single-key/value store (reference: keyvalue/OrderedKeyValueStore.java)."""

    @property
    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def get(self, key: bytes, txh: StoreTransaction) -> Optional[bytes]:
        ...

    @abc.abstractmethod
    def insert(self, key: bytes, value: bytes, txh: StoreTransaction) -> None:
        ...

    @abc.abstractmethod
    def delete(self, key: bytes, txh: StoreTransaction) -> None:
        ...

    @abc.abstractmethod
    def scan(
        self, start: bytes, end: Optional[bytes], txh: StoreTransaction
    ) -> Iterator[Tuple[bytes, bytes]]:
        """Iterate (key, value) with start <= key < end in ascending order
        (end=None: to the last key)."""

    def close(self) -> None:  # pragma: no cover - trivial
        pass


class OrderedKeyValueStoreManager(abc.ABC):
    """Factory for ordered KV stores (reference: OrderedKeyValueStoreManager)."""

    @property
    @abc.abstractmethod
    def features(self) -> StoreFeatures:
        ...

    @abc.abstractmethod
    def open_database(self, name: str) -> OrderedKeyValueStore:
        ...

    @abc.abstractmethod
    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        ...

    @abc.abstractmethod
    def close(self) -> None:
        ...

    @abc.abstractmethod
    def clear_storage(self) -> None:
        ...

    def exists(self) -> bool:
        return True


# ----------------------------------------------------------------- adapter

class OrderedKVAdapter(KeyColumnValueStore):
    """KCVS emulation over an ordered KV store: row slices are contiguous
    composite-key range scans (reference: OrderedKeyValueStoreAdapter.java)."""

    def __init__(self, kv: OrderedKeyValueStore):
        self.kv = kv

    @property
    def name(self) -> str:
        return self.kv.name

    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        prefix = encode_key(query.key)
        start = prefix + query.start
        end = None if query.end is None else prefix + query.end
        out: EntryList = []
        for ck, v in self.kv.scan(start, end, txh):
            if not ck.startswith(prefix):
                break
            out.append((ck[len(prefix):], v))
            if query.limit is not None and len(out) >= query.limit:
                break
        return out

    def mutate(
        self,
        key: bytes,
        additions: EntryList,
        deletions: Sequence[bytes],
        txh: StoreTransaction,
    ) -> None:
        prefix = encode_key(key)
        added = {c for c, _ in additions}
        for col in deletions:
            if col not in added:
                self.kv.delete(prefix + col, txh)
        for col, val in additions:
            self.kv.insert(prefix + col, val, txh)

    def get_keys(
        self, query, txh: StoreTransaction
    ) -> Iterator[Tuple[bytes, EntryList]]:
        if isinstance(query, KeyRangeQuery):
            start = encode_key(query.key_start)
            end = encode_key(query.key_end)
            sq = query.slice
        else:
            start, end, sq = b"", None, query
        # Row grouping via prefix match, not per-cell decode: within the
        # encoded-key part 0x00 is always followed by 0xFF, so the FIRST
        # b"\x00\x00" in a composite is exactly the terminator, and only
        # composites of the same row start with (encoded key + terminator) —
        # one C-level startswith per cell replaces the byte-walk decode
        # (this adapter is the OLAP full-scan hot path).
        limit = sq.limit
        contains = sq.contains
        prefix: Optional[bytes] = None
        plen = 0
        cur_key: Optional[bytes] = None
        cur_entries: EntryList = []
        for ck, v in self.kv.scan(start, end, txh):
            if prefix is None or not ck.startswith(prefix):
                if cur_entries:
                    yield cur_key, cur_entries
                t = ck.find(_TERM)
                kenc = ck[:t]
                cur_key = kenc.replace(b"\x00\xff", b"\x00")
                prefix = kenc + _TERM
                plen = len(prefix)
                cur_entries = []
            col = ck[plen:]
            if contains(col) and (limit is None or len(cur_entries) < limit):
                cur_entries.append((col, v))
        if cur_entries:
            yield cur_key, cur_entries

    def close(self) -> None:
        self.kv.close()


class OrderedKVAdapterManager(KeyColumnValueStoreManager):
    """Wraps an OrderedKeyValueStoreManager as a KCVS manager."""

    def __init__(self, kv_manager: OrderedKeyValueStoreManager):
        self.kv_manager = kv_manager
        self._stores: Dict[str, OrderedKVAdapter] = {}

    @property
    def features(self) -> StoreFeatures:
        return self.kv_manager.features

    @property
    def name(self) -> str:
        return f"kv-adapter({type(self.kv_manager).__name__})"

    def open_database(self, name: str) -> OrderedKVAdapter:
        if name not in self._stores:
            self._stores[name] = OrderedKVAdapter(
                self.kv_manager.open_database(name)
            )
        return self._stores[name]

    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        return self.kv_manager.begin_transaction(config)

    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        for store_name, rows in mutations.items():
            store = self.open_database(store_name)
            for key, mut in rows.items():
                store.mutate(key, mut.additions, mut.deletions, txh)

    def close(self) -> None:
        self.kv_manager.close()

    def clear_storage(self) -> None:
        self.kv_manager.clear_storage()

    def exists(self) -> bool:
        return self.kv_manager.exists()
