"""Key-Column-Value store SPI — the layer-1 storage contract.

The whole graph (vertices, edges, properties, schema, indexes, ID counters,
logs, config) lives in a handful of named stores of *sorted wide rows*:
``key -> sorted[(column, value)]`` with byte-wise lexicographic ordering on
both keys and columns. Everything above this SPI is backend-agnostic.

Capability parity with the reference SPI
(reference: diskstorage/keycolumnvalue/KeyColumnValueStore.java:39 —
getSlice/mutate/acquireLock/getKeys; KeyColumnValueStoreManager.java:31 —
mutateMany; StoreFeatures.java:28 — capability flags), re-designed for a
Python/numpy host runtime feeding a TPU compute path: slice results are
columnar ``EntryList``s that can expose zero-copy numpy views for bulk
CSR decoding.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field, replace
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from janusgraph_tpu.exceptions import PermanentBackendError

# A column-value entry. Kept as a plain tuple (column, value) for speed;
# helper accessors below. Columns and values are immutable `bytes`.
Entry = Tuple[bytes, bytes]
EntryList = List[Entry]

@dataclass(frozen=True)
class SliceQuery:
    """A contiguous column range [start, end) on one row, with a limit.

    Byte-lexicographic bounds; ``end=None`` means unbounded (strictly after
    every possible column — no byte sentinel can express that). ``limit``
    caps the number of returned entries
    (reference: diskstorage/keycolumnvalue/SliceQuery.java).
    """

    start: bytes = b""
    end: Optional[bytes] = None
    limit: Optional[int] = None

    def with_limit(self, limit: int) -> "SliceQuery":
        return replace(self, limit=limit)

    def contains(self, column: bytes) -> bool:
        return self.start <= column and (self.end is None or column < self.end)

    def subsumes(self, other: "SliceQuery") -> bool:
        if self.start > other.start:
            return False
        if self.end is not None and (other.end is None or self.end < other.end):
            return False
        return self.limit is None or (
            other.limit is not None and self.limit >= other.limit
        )


@dataclass(frozen=True)
class KeySliceQuery:
    """A SliceQuery bound to a specific row key."""

    key: bytes
    slice: SliceQuery

    @property
    def start(self) -> bytes:
        return self.slice.start

    @property
    def end(self) -> bytes:
        return self.slice.end

    @property
    def limit(self) -> Optional[int]:
        return self.slice.limit


@dataclass(frozen=True)
class KeyRangeQuery:
    """Iterate keys in [key_start, key_end) returning a column slice per key.

    Requires ordered-scan capability (reference: KCVS.getKeys(KeyRangeQuery)).
    """

    key_start: bytes
    key_end: bytes
    slice: SliceQuery


@dataclass
class KCVMutation:
    """Batched additions + deletions for one row.

    Deletions are column keys. Additions are (column, value) entries.
    (reference: diskstorage/keycolumnvalue/KCVMutation.java)
    """

    additions: EntryList = field(default_factory=list)
    deletions: List[bytes] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.additions and not self.deletions

    def merge(self, other: "KCVMutation") -> None:
        """Merge a *later* mutation into this one, preserving temporal order:
        a later deletion cancels an earlier addition of the same column and
        vice versa (reference: KCVSMutation consolidation semantics).
        Addition entries are (column, value) or (column, value, expire_ns)
        for cell-TTL backends — indexed, never unpacked, so both co-exist."""
        if other.deletions:
            dels = set(other.deletions)
            self.additions = [e for e in self.additions if e[0] not in dels]
            self.deletions.extend(other.deletions)
        if other.additions:
            adds = {e[0] for e in other.additions}
            self.deletions = [d for d in self.deletions if d not in adds]
            self.additions.extend(other.additions)


@dataclass(frozen=True)
class StoreFeatures:
    """Capability flags a backend advertises; upper layers adapt to them.

    (reference: diskstorage/keycolumnvalue/StandardStoreFeatures.java)
    """

    ordered_scan: bool = False
    unordered_scan: bool = False
    multi_query: bool = False
    locking: bool = False          # native per-cell locking
    batch_mutation: bool = False
    transactional: bool = False
    key_consistent: bool = False   # quorum-consistent single-key reads
    distributed: bool = False
    #: storage is reachable by writers OUTSIDE this process (a network
    #: client adapter): cell payloads cross a trust boundary, so upper
    #: layers must not decode formats that execute on read (pickle).
    #: distinct from `distributed` — an in-process sharded composite is
    #: distributed but only this process writes to it
    network_attached: bool = False
    persists: bool = False
    cell_ttl: bool = False
    timestamps: bool = False

    @property
    def scan(self) -> bool:
        return self.ordered_scan or self.unordered_scan


class StoreTransaction:
    """Handle for backend-level transaction state.

    Backends without native transactions use this only to carry config
    (consistency level, timestamps). Commit/rollback are no-ops there.
    """

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}

    def commit(self) -> None:  # pragma: no cover - trivial
        pass

    def rollback(self) -> None:  # pragma: no cover - trivial
        pass


class KeyColumnValueStore(abc.ABC):
    """One named store of sorted wide rows."""

    @property
    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def get_slice(self, query: KeySliceQuery, txh: StoreTransaction) -> EntryList:
        """Return entries of row ``query.key`` with columns in the slice range,
        sorted ascending by column, truncated at ``limit``."""

    def get_slice_multi(
        self, keys: Sequence[bytes], slice_query: SliceQuery, txh: StoreTransaction
    ) -> Dict[bytes, EntryList]:
        """Batched multi-row slice (the multiQuery path). Default: loop."""
        return {
            k: self.get_slice(KeySliceQuery(k, slice_query), txh) for k in keys
        }

    @abc.abstractmethod
    def mutate(
        self,
        key: bytes,
        additions: EntryList,
        deletions: Sequence[bytes],
        txh: StoreTransaction,
    ) -> None:
        """Atomically apply additions and deletions to one row. Additions win
        over deletions of the same column within one call."""

    def acquire_lock(
        self, key: bytes, column: bytes, expected_value: Optional[bytes],
        txh: StoreTransaction,
    ) -> None:
        """Claim a lock hint for (key, column); only for stores with native
        locking. Others are wrapped by the consistent-key locker."""
        raise PermanentBackendError(f"store {self.name} does not support native locking")

    @abc.abstractmethod
    def get_keys(
        self, query, txh: StoreTransaction
    ) -> Iterator[Tuple[bytes, EntryList]]:
        """Iterate rows. ``query`` is a SliceQuery (all keys, unordered OK) or a
        KeyRangeQuery (ordered range scan). Yields (key, entries) with entries
        restricted to the query's column slice; rows with no matching entries
        are skipped."""

    def close(self) -> None:  # pragma: no cover - trivial
        pass


class KeyColumnValueStoreManager(abc.ABC):
    """Factory/registry of stores in one backend plus batched cross-store
    mutation (reference: KeyColumnValueStoreManager.java:31)."""

    @property
    @abc.abstractmethod
    def features(self) -> StoreFeatures:
        ...

    @property
    def name(self) -> str:
        return type(self).__name__

    @abc.abstractmethod
    def open_database(self, name: str) -> KeyColumnValueStore:
        ...

    @abc.abstractmethod
    def begin_transaction(self, config: Optional[dict] = None) -> StoreTransaction:
        ...

    @abc.abstractmethod
    def mutate_many(
        self,
        mutations: Dict[str, Dict[bytes, KCVMutation]],
        txh: StoreTransaction,
    ) -> None:
        """Apply mutations across stores: {store_name: {key: KCVMutation}}.

        ``features.batch_mutation`` means the backend accepts the whole batch
        in one call (e.g. one RPC); it does NOT imply cross-row atomicity —
        per-row application is atomic, the batch is not (matching reference
        semantics where only `transactional` backends give batch atomicity).
        """

    def get_local_key_partition(self):
        """Key ranges held locally (region-aware backends); None otherwise."""
        return None

    @abc.abstractmethod
    def close(self) -> None:
        ...

    @abc.abstractmethod
    def clear_storage(self) -> None:
        ...

    def exists(self) -> bool:
        return True


def entries_in_slice(entries: EntryList, q: SliceQuery) -> EntryList:
    """Filter an already-sorted EntryList down to a slice (helper for caches
    answering a narrower query from a wider cached result)."""
    import bisect

    lo = bisect.bisect_left(entries, (q.start, b""))
    hi = len(entries) if q.end is None else bisect.bisect_left(entries, (q.end, b""))
    out = entries[lo:hi]
    if q.limit is not None and len(out) > q.limit:
        out = out[: q.limit]
    return out
