"""Metric primitives + the telemetry registry.

Absorbs the registry half of ``util/metrics.py`` (which now re-exports
from here) and upgrades the latency story from flat mean/max timers to
fixed log-scale bucket reservoirs with p50/p95/p99:

- buckets are powers of two over one shared ladder (``BUCKET_BOUNDS``),
  so recording is a ``bit_length``-class operation with no allocation and
  percentiles are a bounded cumulative walk — cheap enough for the
  instrumented-store hot path;
- everything is thread-safe behind per-metric locks;
- nothing here may be called from jit-traced code (graphlint JG106): a
  registry write at trace time records once per COMPILE, and a traced
  value in an attribute would force a host sync.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

#: shared log2 bucket ladder: bounds[i] = 2**(i - 20), covering ~1e-6
#: (sub-microsecond in ns terms: fractional units) up to 2**43 (~8.8e12 —
#: 2.4 hours in nanoseconds, terabytes in bytes). One ladder for every
#: histogram keeps exposition buckets consistent across scrapes.
_LOW_EXP = -20
_NUM_BUCKETS = 64
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    2.0 ** (i + _LOW_EXP) for i in range(_NUM_BUCKETS)
)


def bucket_index(value: float) -> int:
    """Index of the first bound >= value; ``_NUM_BUCKETS`` = overflow."""
    if value <= BUCKET_BOUNDS[0]:
        return 0
    return bisect_left(BUCKET_BOUNDS, value)


class Counter:
    __slots__ = ("count", "_lock")

    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def inc(self, delta: int = 1) -> None:
        with self._lock:
            self.count += delta


class Gauge:
    """Last-write-wins scalar (OLAP superstep count, pad ratio, ...)."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self.value += float(delta)


class Histogram:
    """Fixed log-scale bucket reservoir over non-negative values.

    ``observe`` is O(log buckets) under one lock; ``percentile`` walks the
    (copied) counts. Values beyond the top bound land in a dedicated
    overflow slot so finite-bucket cumulative counts stay honest for the
    Prometheus ``le`` semantics.
    """

    __slots__ = ("count", "total", "max", "_counts", "_lock")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._counts = [0] * (_NUM_BUCKETS + 1)  # +1 = overflow (+Inf)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bucket_index(value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += value
            if value > self.max:
                self.max = value

    def state(self) -> Tuple[int, float, float, List[int]]:
        """ONE-lock consistent read: ``(count, total, max, bucket counts)``
        with ``sum(counts) == count`` guaranteed. Every reader below (and
        the history sampler's window deltas) goes through here — a reader
        taking count and buckets under SEPARATE lock acquisitions can see
        a torn window when an observe lands in between."""
        with self._lock:
            return self.count, self.total, self.max, list(self._counts)

    @staticmethod
    def percentile_of(counts: List[int], q: float, hi: float) -> float:
        """Quantile over one (possibly windowed) bucket-count vector."""
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = max(1, int(q * total + 0.5))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return BUCKET_BOUNDS[i] if i < _NUM_BUCKETS else hi
        return hi

    def percentile(self, q: float) -> float:
        """Upper bucket bound at quantile ``q`` in [0, 1] (0.0 if empty).
        Log-bucket resolution: the answer is exact to within 2x."""
        _count, _total, hi, counts = self.state()
        return self.percentile_of(counts, q, hi)

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """``[(le_bound, cumulative_count)]`` for the finite buckets that
        carry data (plus every bound below the max observed bucket that
        contributes to the cumulative shape), for exposition."""
        _count, _total, _hi, counts = self.state()
        out: List[Tuple[float, int]] = []
        cum = 0
        for i in range(_NUM_BUCKETS):
            cum += counts[i]
            if counts[i]:
                out.append((BUCKET_BOUNDS[i], cum))
        return out

    def summary(self) -> Dict[str, float]:
        # one consistent state read feeds every field: count, sum, and the
        # percentiles all describe the SAME point in time even while other
        # threads keep observing
        count, total, hi, counts = self.state()
        return {
            "count": count,
            "sum": total,
            "max": hi,
            "p50": self.percentile_of(counts, 0.50, hi),
            "p95": self.percentile_of(counts, 0.95, hi),
            "p99": self.percentile_of(counts, 0.99, hi),
        }


class Timer(Histogram):
    """Latency histogram in nanoseconds. Keeps the legacy flat-timer
    surface (``count``/``total_ns``/``max_ns``/``mean_ms``) on top of the
    bucket reservoir so p50/p95/p99 report uniformly everywhere the old
    mean/max timer did."""

    __slots__ = ()

    def update(self, elapsed_ns: int) -> None:
        self.observe(float(elapsed_ns))

    @property
    def total_ns(self) -> int:
        return int(self.total)

    @property
    def max_ns(self) -> int:
        return int(self.max)

    @property
    def mean_ms(self) -> float:
        return (self.total / self.count) / 1e6 if self.count else 0.0

    def percentile_ms(self, q: float) -> float:
        return self.percentile(q) / 1e6


class TelemetryRegistry:
    """The process registry (reference: MetricManager.java:36), grown
    four metric kinds (counter/timer/histogram/gauge) plus a bounded
    per-kind run-record log (`record_run`) that surfaces structured
    execution records — e.g. the OLAP executor's per-run info — without
    private-attribute spelunking."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._timers: Dict[str, Timer] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._runs: Dict[str, deque] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ accessors
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter())
        return c

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            with self._lock:
                t = self._timers.setdefault(name, Timer())
        return t

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram())
        return h

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge())
        return g

    def set_gauge(self, name: str, value: float) -> None:
        self.gauge(name).set(value)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.timer(name).update(time.perf_counter_ns() - t0)

    # ---------------------------------------------------------- run records
    def record_run(self, kind: str, info: dict, keep: int = 32) -> None:
        """Append one structured execution record (e.g. an OLAP run's
        ``{"path", "supersteps", "wall_s", "superstep_records", ...}``)."""
        with self._lock:
            dq = self._runs.get(kind)
            if dq is None:
                dq = self._runs.setdefault(kind, deque(maxlen=keep))
        dq.append(dict(info))

    def runs(self, kind: str) -> List[dict]:
        dq = self._runs.get(kind)
        return [dict(r) for r in dq] if dq else []

    def last_run(self, kind: str) -> Optional[dict]:
        dq = self._runs.get(kind)
        return dict(dq[-1]) if dq else None

    # ------------------------------------------------------------- reporting
    def metric_objects(self):
        """Stable shallow copies of the four metric maps (for renderers)."""
        with self._lock:
            return (
                dict(self._counters),
                dict(self._timers),
                dict(self._histograms),
                dict(self._gauges),
            )

    def snapshot(self) -> Dict[str, dict]:
        """ONE dict over all metric kinds in stable dotted-name order, so
        snapshot diffs are deterministic regardless of kind or insertion
        order. Timers and histograms report count + percentiles uniformly
        (the old reporter asymmetry — counters with counts, timers with
        mean/max only — is gone)."""
        counters, timers, histograms, gauges = self.metric_objects()
        out: Dict[str, dict] = {}
        names = sorted(
            set(counters) | set(timers) | set(histograms) | set(gauges)
        )
        for name in names:
            if name in counters:
                out[name] = {"type": "counter", "count": counters[name].count}
            elif name in timers:
                # one state() read per timer: count/total/percentiles stay
                # mutually consistent under concurrent updates
                count, total, hi, counts = timers[name].state()
                out[name] = {
                    "type": "timer",
                    "count": count,
                    "total_ms": total / 1e6,
                    "mean_ms": (total / count) / 1e6 if count else 0.0,
                    "max_ms": hi / 1e6,
                    "p50_ms": Histogram.percentile_of(counts, 0.50, hi) / 1e6,
                    "p95_ms": Histogram.percentile_of(counts, 0.95, hi) / 1e6,
                    "p99_ms": Histogram.percentile_of(counts, 0.99, hi) / 1e6,
                }
            elif name in histograms:
                h = histograms[name]
                out[name] = {"type": "histogram", **h.summary()}
            else:
                out[name] = {"type": "gauge", "value": gauges[name].value}
        return out

    def report(self) -> str:
        """Console reporter (reference: console reporter config
        GraphDatabaseConfiguration.java:1012). Same columns for every
        latency metric: count, mean, p50, p95, p99, total."""
        lines = [
            f"{'name':46} {'count':>9} {'mean_ms':>9} {'p50_ms':>9} "
            f"{'p95_ms':>9} {'p99_ms':>9} {'total_ms':>10}"
        ]
        for name, m in self.snapshot().items():
            if m["type"] == "counter":
                lines.append(f"{name:46} {m['count']:>9}")
            elif m["type"] == "gauge":
                lines.append(f"{name:46} {'':>9} {m['value']:>9.3f}")
            elif m["type"] == "histogram":
                lines.append(
                    f"{name:46} {m['count']:>9} {'':>9} {m['p50']:>9.3f} "
                    f"{m['p95']:>9.3f} {m['p99']:>9.3f} {m['sum']:>10.2f}"
                )
            else:
                lines.append(
                    f"{name:46} {m['count']:>9} {m['mean_ms']:>9.3f} "
                    f"{m['p50_ms']:>9.3f} {m['p95_ms']:>9.3f} "
                    f"{m['p99_ms']:>9.3f} {m['total_ms']:>10.2f}"
                )
        return "\n".join(lines)

    def get_count(self, name: str) -> int:
        c = self._counters.get(name)
        if c is not None:
            return c.count
        t = self._timers.get(name)
        if t is not None:
            return t.count
        h = self._histograms.get(name)
        return h.count if h is not None else 0

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._histograms.clear()
            self._gauges.clear()
            self._runs.clear()
