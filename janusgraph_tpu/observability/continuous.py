"""Continuous profiling plane: always-on sampling profiler, runtime
stall watchdog, and anomaly forensics bundles.

The five observability layers before this one are request-scoped or
pull-based — the roofline ledger prices individual queries, benchdiff
flags *that* a cell regressed — but nothing can say *where the wall
went* between two windows, and lock discipline is enforced only
statically (JG2xx/JG4xx).  This module closes both gaps at runtime:

``SamplingProfiler``
    A daemon thread samples ``sys._current_frames()`` at
    ``metrics.profile-hz`` and folds every stack into collapsed-stack
    lines (the same ``frame;frame;frame weight_us`` vocabulary as
    :mod:`janusgraph_tpu.observability.profiler`'s ``flame_lines``).
    Stacks accumulate into the *current* window, which is sealed into a
    bounded ring whenever a ``MetricsHistory`` window lands — the
    profiler registers a history listener, so a flame window carries the
    exact ``seq`` of the metrics window it joins and the two can be
    correlated after the fact.  When history is not running the profiler
    self-seals on a fallback cadence (tagged ``seq=-1``).  Every
    sampling pass self-measures both wall and CPU cost
    (``time.thread_time`` is exact for the calling thread); the lifetime
    CPU ratio is exported as ``observability.profiler.overhead_cpu_pct``
    and gated < 1% in the saturation bench.  Per-thread CPU attribution
    reads ``/proc/self/task/<tid>/stat`` utime+stime on Linux and
    degrades to empty elsewhere.

``InstrumentedLock`` / ``StallWatchdog``
    The runtime twin of graphlint's static lock rules.  An
    ``InstrumentedLock`` records its owner (thread ident + acquire
    time) and registers blocked waiters with the watchdog; the watchdog
    thread scans the wait table and the registered progress sources
    (active requests, supersteps, CDC pulls) and flights
    ``lock_convoy`` / ``stall`` events — edge-triggered per key — each
    carrying the owner's stack snatched from the sampler ring, plus the
    wait-for edge (waiter → owner).  A confirmed stall triggers a
    forensics bundle.

``BundleWriter``
    On SLO page (healthz ok→degraded flip), watchdog stall, or
    unhandled server error, capture one bundle: recent flame windows +
    flight ring + timeseries tail + all-thread stack dump + active
    request table + watchdog state.  Edge-triggered and rate-limited
    (``metrics.bundle-min-interval-s``), written tmp+rename atomic with
    bounded retention (``metrics.bundle-retention``), served at
    ``GET /debug/bundle`` and via ``janusgraph_tpu bundle``.

``flamediff``
    Frame-by-frame diff of two flame sources (windows or bench
    artifacts): per-frame aggregated weight deltas ranked by |delta|
    (deterministic name tie-break).  benchdiff attaches the top-3 frame
    deltas to any regressed cell whose artifacts embed flame data, so a
    regression names the frames that got slower.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from janusgraph_tpu.observability.flight import recorder as flight_recorder
from janusgraph_tpu.observability.profiler import _FRAME_SANITIZE
from janusgraph_tpu.observability.timeseries import history

__all__ = [
    "BundleWriter",
    "InstrumentedLock",
    "SamplingProfiler",
    "StallWatchdog",
    "bundle_writer",
    "flamediff",
    "flame_from_artifact",
    "sampling_profiler",
    "watchdog",
]

_MAX_DEPTH = 64


#: fold caches: most sampled threads are BLOCKED (selectors, queue
#: waits), so the same frame chain recurs sample after sample — caching
#: the collapsed string by the chain's code objects turns the hot fold
#: into one tuple-hash lookup. Keys hold the code objects alive, so ids
#: can never alias; both caches are bounded.
_LABEL_CACHE: Dict[object, str] = {}
_STACK_CACHE: Dict[tuple, str] = {}


def _frame_label(code) -> str:
    got = _LABEL_CACHE.get(code)
    if got is None:
        name = "%s:%s" % (
            os.path.basename(code.co_filename), code.co_name,
        )
        got = _FRAME_SANITIZE.sub("_", name)
        if len(_LABEL_CACHE) < 8192:
            _LABEL_CACHE[code] = got
    return got


def _fold_frame(frame) -> str:
    """Collapse a frame chain into a root→leaf ``file:func;...`` stack
    string, sanitized with the shared flame vocabulary."""
    codes: List[object] = []
    f = frame
    while f is not None and len(codes) < _MAX_DEPTH:
        codes.append(f.f_code)
        f = f.f_back
    key = tuple(codes)
    got = _STACK_CACHE.get(key)
    if got is None:
        got = ";".join(_frame_label(c) for c in reversed(codes))
        if len(_STACK_CACHE) < 4096:
            _STACK_CACHE[key] = got
    return got


def _proc_thread_cpu() -> Dict[int, float]:
    """Per-native-thread CPU seconds from /proc (Linux); empty map when
    the proc filesystem is unavailable (macOS, sandboxes)."""
    out: Dict[int, float] = {}
    task_dir = "/proc/self/task"
    try:
        tids = os.listdir(task_dir)
    except OSError:
        return out
    tick = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
    for tid in tids:
        try:
            with open("%s/%s/stat" % (task_dir, tid), "rb") as fh:
                raw = fh.read().decode("ascii", "replace")
            # field 2 is "(comm)" and may contain spaces — split after it
            rest = raw.rsplit(")", 1)[1].split()
            utime, stime = int(rest[11]), int(rest[12])
            out[int(tid)] = (utime + stime) / float(tick)
        except (OSError, ValueError, IndexError):
            continue
    return out


class SamplingProfiler:
    """Always-on low-rate stack sampler with self-measured overhead.

    Lifecycle mirrors ``MetricsHistory``: a module singleton the server
    starts/stops; ``configure()`` is applied at graph-open time from
    ``metrics.profile-*`` keys.  ``sample_once()`` and
    ``seal_window(seq)`` are public so fake-clock tests drive the
    profiler without the thread.
    """

    def __init__(
        self,
        hz: float = 20.0,
        max_windows: int = 60,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.hz = float(hz)
        self.enabled = False
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(max_windows))
        self._pending: Dict[str, int] = {}
        self._pending_samples = 0
        self._last_stacks: Dict[int, Tuple[str, str]] = {}
        self._prev_thread_cpu: Dict[int, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started_at = 0.0
        self._last_seal = 0.0
        self._last_seal_seq = 0
        self._died: Optional[str] = None
        #: per-seal hooks (the telemetry bus); called AFTER the ring
        #: lock is released, exceptions swallowed — the same contract
        #: as MetricsHistory listeners
        self._seal_listeners: List[Callable[[dict], None]] = []
        # lifetime self-cost (the PR 17 discipline: wall AND cpu,
        # 1-core honest — cpu_pct is against elapsed wall on one core)
        self._overhead_wall_s = 0.0
        self._overhead_cpu_s = 0.0
        self._samples = 0
        self._windows_sealed = 0

    # ------------------------------------------------------------- config
    def configure(
        self,
        hz: Optional[float] = None,
        max_windows: Optional[int] = None,
        enabled: Optional[bool] = None,
    ) -> None:
        with self._lock:
            if hz is not None and hz > 0:
                self.hz = float(hz)
            if max_windows is not None and max_windows != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=int(max_windows))
            if enabled is not None:
                self.enabled = bool(enabled)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Start the sampler thread (idempotent) and attach the
        history listener so flame windows seal in lockstep with metrics
        windows."""
        with self._lock:
            self.enabled = True
            self._died = None
        history.add_listener(self._on_history_window)
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        now = self._clock()
        with self._lock:
            self._started_at = now
            self._last_seal = now
        self._thread = threading.Thread(
            target=self._run, name="profiler-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.enabled = False
        history.remove_listener(self._on_history_window)
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        try:
            while not self._stop.wait(1.0 / max(self.hz, 0.1)):
                self.sample_once()
                # fallback sealing when MetricsHistory is not running —
                # windows stay bounded, just unaligned (seq=-1)
                horizon = max(4.0 * history.interval_s, 2.0)
                if self._clock() - self._last_seal > horizon:
                    self.seal_window(seq=-1)
        except Exception as e:  # noqa: BLE001 - record before dying (JG112)
            with self._lock:
                self._died = repr(e)
            flight_recorder.record(
                "thread_error", thread="profiler-sampler", error=repr(e)
            )

    # ----------------------------------------------------------- sampling
    def sample_once(self) -> int:
        """One sampling pass over all threads except the sampler itself.
        Returns the number of stacks folded.  Self-cost (wall + CPU) is
        accumulated; ``thread_time`` measures exactly this thread."""
        w0 = time.perf_counter()
        c0 = time.thread_time()
        own = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        period_us = int(1e6 / max(self.hz, 0.1))
        folded = 0
        # fold outside the lock: the stack cache makes this cheap, and
        # the lock hold shrinks to dict updates
        stacks = [
            (ident, _fold_frame(frame))
            for ident, frame in frames.items()
            if ident != own
        ]
        with self._lock:
            for ident, stack in stacks:
                if not stack:
                    continue
                self._pending[stack] = (
                    self._pending.get(stack, 0) + period_us
                )
                self._last_stacks[ident] = (
                    names.get(ident, str(ident)), stack
                )
            self._pending_samples += 1
            self._samples += 1
            self._overhead_wall_s += time.perf_counter() - w0
            self._overhead_cpu_s += time.thread_time() - c0
            folded = len(frames) - (1 if own in frames else 0)
        return folded

    def add_seal_listener(self, fn: Callable[[dict], None]) -> None:
        """Register a per-seal hook (the streaming telemetry bus);
        runs on the sealing thread after the flame window lands."""
        if fn not in self._seal_listeners:
            self._seal_listeners.append(fn)

    def remove_seal_listener(self, fn) -> None:
        if fn in self._seal_listeners:
            self._seal_listeners.remove(fn)

    def last_seal_seq(self) -> int:
        """Seq of the newest HISTORY-ALIGNED flame seal — the ``flame``
        stream's cursor position (fallback seals carry seq=-1 and have
        no stable cursor, so they never advance this)."""
        with self._lock:
            return self._last_seal_seq

    def _on_history_window(self, window: dict) -> None:
        self.seal_window(seq=int(window.get("seq", -1)))

    def seal_window(self, seq: int = -1) -> dict:
        """Seal the pending stacks into a flame window tagged with the
        metrics-history window ``seq`` it joins."""
        cpu_now = _proc_thread_cpu()
        names = {
            t.native_id: t.name
            for t in threading.enumerate()
            if t.native_id is not None
        }
        with self._lock:
            cpu_ms: Dict[str, float] = {}
            for tid, secs in cpu_now.items():
                prev = self._prev_thread_cpu.get(tid)
                if prev is not None and secs >= prev:
                    delta = (secs - prev) * 1000.0
                    if delta > 0:
                        cpu_ms[names.get(tid, str(tid))] = round(delta, 3)
            self._prev_thread_cpu = cpu_now
            window = {
                "seq": seq,
                "ts": self._wall(),
                "t": self._clock(),
                "samples": self._pending_samples,
                "stacks": dict(self._pending),
                "cpu_ms_by_thread": cpu_ms,
            }
            self._ring.append(window)
            self._pending = {}
            self._pending_samples = 0
            self._windows_sealed += 1
            self._last_seal = self._clock()
            if seq > 0:
                self._last_seal_seq = seq
            listeners = list(self._seal_listeners)
        from janusgraph_tpu.observability import registry

        registry.set_gauge(
            "observability.profiler.overhead_cpu_pct",
            round(self.overhead_cpu_pct(), 4),
        )
        for fn in listeners:
            try:
                fn(window)
            except Exception:  # noqa: BLE001 - a listener must not kill sealing
                pass
        return window

    # ----------------------------------------------------------- querying
    def windows(self, last: int = 0) -> List[dict]:
        """The most recent ``last`` sealed flame windows (0 = all),
        oldest first."""
        with self._lock:
            wins = list(self._ring)
        return wins[-last:] if last > 0 else wins

    def merged_stacks(self, last: int = 0) -> Dict[str, int]:
        """Collapsed stacks merged across the requested windows plus the
        current pending window."""
        merged: Dict[str, int] = {}
        for w in self.windows(last):
            for stack, us in w["stacks"].items():
                merged[stack] = merged.get(stack, 0) + us
        with self._lock:
            for stack, us in self._pending.items():
                merged[stack] = merged.get(stack, 0) + us
        return merged

    def flame_text(self, last: int = 0) -> str:
        """Collapsed-stack flamegraph text (``stack weight_us`` lines,
        heaviest first) — the same vocabulary as ``flame_lines``."""
        merged = self.merged_stacks(last)
        lines = [
            "%s %d" % (stack, us)
            for stack, us in sorted(
                merged.items(), key=lambda kv: (-kv[1], kv[0])
            )
        ]
        return "\n".join(lines) + ("\n" if lines else "")

    def stack_of(self, ident: int) -> Optional[str]:
        """Last sampled stack for a thread ident — the watchdog snatches
        a lock owner's stack from here."""
        with self._lock:
            got = self._last_stacks.get(ident)
        return got[1] if got else None

    def overhead_cpu_pct(self) -> float:
        elapsed = self._clock() - self._started_at
        if elapsed <= 0 or self._started_at == 0.0:
            return 0.0
        return 100.0 * self._overhead_cpu_s / elapsed

    def overhead_wall_pct(self) -> float:
        elapsed = self._clock() - self._started_at
        if elapsed <= 0 or self._started_at == 0.0:
            return 0.0
        return 100.0 * self._overhead_wall_s / elapsed

    def status(self) -> dict:
        """The /healthz ``profiler`` sub-block."""
        with self._lock:
            windows = len(self._ring)
        return {
            "enabled": self.enabled,
            "alive": self.alive,
            "died": self._died,
            "hz": self.hz,
            "samples": self._samples,
            "windows": windows,
            "windows_sealed": self._windows_sealed,
            "overhead_cpu_pct": round(self.overhead_cpu_pct(), 4),
            "overhead_wall_pct": round(self.overhead_wall_pct(), 4),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending = {}
            self._pending_samples = 0
            self._last_stacks = {}
            self._prev_thread_cpu = {}
            self._overhead_wall_s = 0.0
            self._overhead_cpu_s = 0.0
            self._samples = 0
            self._windows_sealed = 0
            self._last_seal_seq = 0
            self._died = None
            self._started_at = 0.0
            self._seal_listeners.clear()


class InstrumentedLock:
    """A named lock whose owner and waiters are visible to the
    watchdog.  The fast path is one extra non-blocking try; contended
    acquires register in the watchdog wait table so a convoy is
    observable *while it is happening*, not only after release."""

    def __init__(
        self,
        name: str,
        watchdog: Optional["StallWatchdog"] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self._lock = threading.Lock()
        self._clock = clock
        self._meta = threading.Lock()
        self.owner: Optional[int] = None
        self.owner_name: str = ""
        self.owner_since: float = 0.0
        self.waiters: Dict[int, Tuple[str, float]] = {}
        self._wd = watchdog if watchdog is not None else watchdog_singleton()
        self._wd.register_lock(self)

    def acquire(self, timeout: float = -1) -> bool:
        me = threading.get_ident()
        my_name = threading.current_thread().name
        settled = False
        ok = self._lock.acquire(blocking=False)
        try:
            if not ok:
                with self._meta:
                    self.waiters[me] = (my_name, self._clock())
                ok = self._acquire_contended(me, timeout)
            if ok:
                self._granted(me, my_name)
            settled = True
            return ok
        finally:
            # bookkeeping raised after the inner lock was won: release
            # it rather than leak a lock the caller never learned it
            # holds
            if ok and not settled:
                self._lock.release()

    def _acquire_contended(self, me: int, timeout: float) -> bool:
        """Blocking inner acquire for the contended path; the caller
        already registered ``me`` in the waiter table — popped here on
        every exit."""
        settled = False
        ok = self._lock.acquire(timeout=timeout if timeout >= 0 else -1)
        try:
            with self._meta:
                self.waiters.pop(me, None)
            settled = True
            return ok
        finally:
            if ok and not settled:
                self._lock.release()

    def _granted(self, ident: int, name: str) -> None:
        with self._meta:
            self.owner = ident
            self.owner_name = name
            self.owner_since = self._clock()

    def release(self) -> None:
        with self._meta:
            self.owner = None
            self.owner_name = ""
            self.owner_since = 0.0
        self._lock.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def state(self) -> dict:
        with self._meta:
            return {
                "name": self.name,
                "owner": self.owner_name or None,
                "held_s": (
                    round(self._clock() - self.owner_since, 3)
                    if self.owner is not None
                    else 0.0
                ),
                "waiters": len(self.waiters),
            }


class StallWatchdog:
    """Scans instrumented-lock wait tables and progress sources and
    flights ``lock_convoy`` / ``stall`` events with the owner's sampled
    stack.  Edge-triggered per (kind, key): one event per episode, the
    key re-arms when the waiter is granted / progress resumes."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.interval_s = 1.0
        self.stall_s = 5.0
        self.enabled = False
        self._clock = clock
        self._lock = threading.Lock()
        self._locks: List[InstrumentedLock] = []
        self._progress: Dict[str, Callable[[], dict]] = {}
        self._last_progress: Dict[str, Tuple[object, float]] = {}
        self._flagged: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._died: Optional[str] = None
        self.events = 0

    def configure(
        self,
        interval_s: Optional[float] = None,
        stall_s: Optional[float] = None,
        enabled: Optional[bool] = None,
    ) -> None:
        if interval_s is not None and interval_s > 0:
            self.interval_s = float(interval_s)
        if stall_s is not None and stall_s > 0:
            self.stall_s = float(stall_s)
        if enabled is not None:
            self.enabled = bool(enabled)

    # -------------------------------------------------------- registration
    def register_lock(self, lock: InstrumentedLock) -> None:
        with self._lock:
            if lock not in self._locks:
                self._locks.append(lock)

    def register_progress(
        self, name: str, fn: Callable[[], dict]
    ) -> None:
        """``fn`` returns ``{"active": int, "progress": value}`` —
        active work whose progress value does not change for
        ``stall_s`` is a stall."""
        with self._lock:
            self._progress[name] = fn

    def unregister_progress(self, name: str) -> None:
        with self._lock:
            self._progress.pop(name, None)
            self._last_progress.pop(name, None)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        with self._lock:
            self.enabled = True
            self._died = None
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.enabled = False
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        try:
            while not self._stop.wait(self.interval_s):
                self.check()
        except Exception as e:  # noqa: BLE001 - record before dying (JG112)
            with self._lock:
                self._died = repr(e)
            flight_recorder.record(
                "thread_error", thread="stall-watchdog", error=repr(e)
            )

    # ----------------------------------------------------------- detection
    def check(self, now: Optional[float] = None) -> List[dict]:
        """One scan pass (public so fake-clock tests drive it).
        Returns the events flighted this pass.  Detection mutates the
        edge-trigger state under ``_lock``; flighting and bundle
        capture run after release so forensics I/O never happens while
        the watchdog lock is held."""
        from janusgraph_tpu.observability import registry

        now = self._clock() if now is None else now
        convoys: List[dict] = []
        stalls: List[dict] = []
        with self._lock:
            locks = list(self._locks)
            progress = dict(self._progress)
        for lk in locks:
            with lk._meta:
                waiters = dict(lk.waiters)
                owner = lk.owner
                owner_name = lk.owner_name
            live_keys = {("lock", lk.name, ident) for ident in waiters}
            with self._lock:
                # re-arm keys whose waiter was granted or gave up
                self._flagged = {
                    k
                    for k in self._flagged
                    if not (
                        k[0] == "lock"
                        and k[1] == lk.name
                        and k not in live_keys
                    )
                }
                for ident, (wname, since) in waiters.items():
                    key = ("lock", lk.name, ident)
                    wait_s = now - since
                    if wait_s < self.stall_s or key in self._flagged:
                        continue
                    self._flagged.add(key)
                    self.events += 1
                    convoys.append({
                        "lock": lk.name,
                        "waiter": wname,
                        "wait_s": round(wait_s, 3),
                        "owner": owner,
                        "owner_name": owner_name,
                    })
        for name, fn in progress.items():
            try:
                snap = fn() or {}
            except Exception:  # noqa: BLE001 - a bad source must not kill scans
                flight_recorder.record(
                    "thread_error", thread="stall-watchdog",
                    error="progress source %r raised" % name,
                )
                continue
            active = int(snap.get("active", 0))
            value = snap.get("progress")
            key = ("progress", name)
            with self._lock:
                if active <= 0:
                    self._last_progress.pop(name, None)
                    self._flagged.discard(key)
                    continue
                prev = self._last_progress.get(name)
                if prev is None or prev[0] != value:
                    self._last_progress[name] = (value, now)
                    self._flagged.discard(key)
                    continue
                stuck_s = now - prev[1]
                if stuck_s < self.stall_s or key in self._flagged:
                    continue
                self._flagged.add(key)
                self.events += 1
            stalls.append({
                "source": name,
                "active": active,
                "stuck_s": round(stuck_s, 3),
                "progress": value,
            })
        fired: List[dict] = []
        for c in convoys:
            owner_stack = (
                sampling_profiler.stack_of(c["owner"])
                if c["owner"] is not None
                else None
            )
            ev = flight_recorder.record(
                "lock_convoy",
                lock=c["lock"],
                waiter=c["waiter"],
                wait_s=c["wait_s"],
                owner=c["owner_name"] or None,
                owner_stack=owner_stack,
                wait_for=[c["waiter"], c["owner_name"] or "?"],
            )
            registry.counter("observability.watchdog.lock_convoys").inc()
            fired.append(ev)
            bundle_writer.capture(reason="lock-convoy")
        for s in stalls:
            ev = flight_recorder.record("stall", **s)
            registry.counter("observability.watchdog.stalls").inc()
            fired.append(ev)
            bundle_writer.capture(reason="stall")
        return fired

    def state(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "alive": self.alive,
                "died": self._died,
                "interval_s": self.interval_s,
                "stall_s": self.stall_s,
                "locks": [lk.state() for lk in self._locks],
                "sources": sorted(self._progress),
                "events": self.events,
            }

    def reset(self) -> None:
        with self._lock:
            self._locks = []
            self._progress = {}
            self._last_progress = {}
            self._flagged = set()
            self.events = 0
            self._died = None


class BundleWriter:
    """Anomaly forensics bundles: one self-contained JSON per episode,
    written tmp+rename atomic with bounded retention.  ``capture()``
    never raises — forensics must not take down the server it is
    diagnosing."""

    def __init__(
        self,
        directory: str = "",
        retention: int = 8,
        min_interval_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.directory = directory
        self.retention = int(retention)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._last_capture = 0.0
        self._seq = 0
        self.written = 0
        self.suppressed = 0
        self._request_table: Optional[Callable[[], list]] = None

    def configure(
        self,
        directory: Optional[str] = None,
        retention: Optional[int] = None,
        min_interval_s: Optional[float] = None,
    ) -> None:
        if directory is not None:
            self.directory = directory
        if retention is not None and retention > 0:
            self.retention = int(retention)
        if min_interval_s is not None and min_interval_s >= 0:
            self.min_interval_s = float(min_interval_s)

    def set_request_table(
        self, provider: Optional[Callable[[], list]]
    ) -> None:
        """The server registers its active-request table here."""
        self._request_table = provider

    # ------------------------------------------------------------- capture
    def _all_stacks(self) -> Dict[str, List[str]]:
        names = {t.ident: t.name for t in threading.enumerate()}
        out: Dict[str, List[str]] = {}
        for ident, frame in sys._current_frames().items():
            label = "%s (%d)" % (names.get(ident, "?"), ident)
            out[label] = [
                ln.rstrip("\n")
                for ln in traceback.format_stack(frame)
            ]
        return out

    def build(self, reason: str) -> dict:
        requests: list = []
        if self._request_table is not None:
            try:
                requests = list(self._request_table())
            except Exception:  # noqa: BLE001 - a bad provider must not block forensics
                requests = [{"error": "request-table provider raised"}]
        return {
            "reason": reason,
            "ts": self._wall(),
            "pid": os.getpid(),
            "flame_windows": sampling_profiler.windows(last=5),
            "profiler": sampling_profiler.status(),
            "flight": flight_recorder.snapshot(),
            "timeseries": history.windows(last=10),
            "stacks": self._all_stacks(),
            "requests": requests,
            "watchdog": watchdog.state(),
        }

    def capture(
        self, reason: str, force: bool = False
    ) -> Optional[str]:
        """Capture one bundle (edge-triggered callers + this rate limit
        keep a flapping pager from writing a bundle per second).
        Returns the path, or None when suppressed or disabled."""
        if not self.directory:
            return None
        from janusgraph_tpu.observability import registry

        with self._lock:
            now = self._clock()
            if (
                not force
                and self._last_capture > 0.0
                and now - self._last_capture < self.min_interval_s
            ):
                self.suppressed += 1
                registry.counter(
                    "observability.bundles.suppressed"
                ).inc()
                return None
            self._last_capture = now
            self._seq += 1
            seq = self._seq
        try:
            bundle = self.build(reason)
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(
                self.directory,
                "bundle-%d-%04d.json" % (os.getpid(), seq),
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(bundle, fh, indent=1, sort_keys=True)
            os.replace(tmp, path)
            with self._lock:
                self.written += 1
            registry.counter("observability.bundles.written").inc()
            flight_recorder.record("bundle", reason=reason, path=path)
            self._prune()
            return path
        except Exception as e:  # noqa: BLE001 - forensics must not raise
            flight_recorder.record(
                "thread_error", thread="bundle-writer", error=repr(e)
            )
            return None

    def _prune(self) -> None:
        try:
            names = sorted(
                n
                for n in os.listdir(self.directory)
                if n.startswith("bundle-") and n.endswith(".json")
            )
            for n in names[: max(0, len(names) - self.retention)]:
                os.remove(os.path.join(self.directory, n))
        except OSError:
            pass

    # ------------------------------------------------------------- reading
    def list_bundles(self) -> List[str]:
        if not self.directory:
            return []
        try:
            return sorted(
                os.path.join(self.directory, n)
                for n in os.listdir(self.directory)
                if n.startswith("bundle-") and n.endswith(".json")
            )
        except OSError:
            return []

    def latest(self) -> Optional[dict]:
        """Newest readable bundle — a torn/partial file (killed writer)
        is skipped, not fatal."""
        for path in reversed(self.list_bundles()):
            try:
                with open(path) as fh:
                    got = json.load(fh)
                got["path"] = path
                return got
            except (OSError, ValueError):
                continue
        return None

    def status(self) -> dict:
        return {
            "dir": self.directory or None,
            "retention": self.retention,
            "min_interval_s": self.min_interval_s,
            "written": self.written,
            "suppressed": self.suppressed,
            "on_disk": len(self.list_bundles()),
        }

    def reset(self) -> None:
        with self._lock:
            self._last_capture = 0.0
            self._seq = 0
            self.written = 0
            self.suppressed = 0
            self._request_table = None


# ---------------------------------------------------------------- flamediff
def _frame_weights(stacks: Dict[str, float]) -> Dict[str, float]:
    """Aggregate weight per *frame*: each stack's weight is charged once
    to every distinct frame on it (inclusive time, recursion-safe)."""
    out: Dict[str, float] = {}
    for stack, weight in stacks.items():
        for frame in set(stack.split(";")):
            if frame:
                out[frame] = out.get(frame, 0.0) + float(weight)
    return out


def flame_from_artifact(obj: dict) -> Optional[Dict[str, float]]:
    """Pull collapsed stacks out of a bench stage/artifact dict, a
    flame window, or a raw ``{stack: weight}`` map."""
    if not isinstance(obj, dict):
        return None
    if "stacks" in obj and isinstance(obj["stacks"], dict):
        return {str(k): float(v) for k, v in obj["stacks"].items()}
    flame = obj.get("flame")
    if isinstance(flame, dict):
        inner = flame.get("stacks", flame)
        if isinstance(inner, dict):
            return {str(k): float(v) for k, v in inner.items()}
    if obj and all(
        isinstance(v, (int, float)) for v in obj.values()
    ):
        return {str(k): float(v) for k, v in obj.items()}
    return None


def flamediff(
    old, new, top: int = 0
) -> List[dict]:
    """Frame-by-frame diff of two flame sources.  Ranked by |delta|
    descending with a deterministic frame-name tie-break, so two runs
    over the same artifacts produce byte-identical output."""
    old_map = flame_from_artifact(old) if isinstance(old, dict) else None
    new_map = flame_from_artifact(new) if isinstance(new, dict) else None
    if old_map is None or new_map is None:
        return []
    old_f = _frame_weights(old_map)
    new_f = _frame_weights(new_map)
    rows = []
    for frame in sorted(set(old_f) | set(new_f)):
        o = old_f.get(frame, 0.0)
        n = new_f.get(frame, 0.0)
        delta = n - o
        if delta == 0.0:
            continue
        rows.append(
            {
                "frame": frame,
                "old_us": round(o, 1),
                "new_us": round(n, 1),
                "delta_us": round(delta, 1),
                "delta_pct": (
                    round(100.0 * delta / o, 2) if o > 0 else None
                ),
            }
        )
    rows.sort(key=lambda r: (-abs(r["delta_us"]), r["frame"]))
    return rows[:top] if top > 0 else rows


# --------------------------------------------------------------- singletons
sampling_profiler = SamplingProfiler()
watchdog = StallWatchdog()
bundle_writer = BundleWriter()


def watchdog_singleton() -> StallWatchdog:
    return watchdog
