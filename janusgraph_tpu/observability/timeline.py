"""Superstep timelines: render OLAP run records to Chrome-trace JSON.

Every executor (fused / host-loop / frontier / sharded) already records a
structured ``run_info`` — per-superstep walls, exchange volumes, modeled
per-shard shares, checkpoint saves and resumes — into the registry's run
log (``registry.runs("olap")``). This module turns one such record into
the catapult / Chrome-trace event format (``chrome://tracing``,
https://ui.perfetto.dev — the ``{"traceEvents": [...]}`` JSON every trace
viewer loads), so exchange/compute/checkpoint overlap is finally VISIBLE
per superstep per shard instead of buried in JSON:

- row (tid) 0 is the host superstep lane: one ``X`` slice per superstep
  record, duration from its measured ``wall_ms`` (fused-path records are
  amortized chunk shares and carry ``approx: true`` through to the event
  args — the viewer shows honest provenance);
- sharded runs add one lane per shard: a ``compute`` slice scaled by the
  shard's measured/modeled share of the superstep wall, then an
  ``exchange`` slice covering the remainder (collective + barrier wait),
  annotated with the run's exchange mode/bytes/batches — the straggler
  shard is the lane whose compute slice pushes everyone's exchange right;
- checkpoint saves render as slices on the ``checkpoint`` lane at the
  superstep that paid them (``checkpoint_ms`` markers the executors
  stamp onto the saving record); resumes render at the front of the lane
  (``resume_ms`` total — the replay happened before the recorded steps).

Timestamps are cumulative microseconds from run start (catapult's unit).
``GET /profile/timeline?run=`` and ``janusgraph_tpu timeline`` serve the
rendering; the output loads unmodified in any Chrome-trace viewer.
"""

from __future__ import annotations

import json
from typing import List, Optional

PID = 1  # one process lane per rendered run


def _meta(name: str, tid: int, label: str) -> dict:
    return {
        "ph": "M", "pid": PID, "tid": tid, "name": name,
        "args": {"name": label},
    }


def _slice(name, ts_us, dur_us, tid, args=None, cat="olap") -> dict:
    ev = {
        "name": name, "ph": "X", "cat": cat, "pid": PID, "tid": tid,
        "ts": round(ts_us, 3), "dur": round(max(dur_us, 0.0), 3),
    }
    if args:
        ev["args"] = args
    return ev


_ARG_KEYS = (
    "frontier", "edges", "e_cap", "pad_ratio", "combiner", "channel",
    "compiled", "approx", "flops", "bytes_accessed",
    "operational_intensity", "roofline_utilization", "h2d_bytes",
)

#: lanes: 0 = host supersteps, 1 = checkpoint/resume control, 2+ = shards
TID_HOST = 0
TID_CONTROL = 1
TID_SHARD0 = 2


def timeline_events(run: dict) -> List[dict]:
    """Catapult events for ONE run record (the ``registry.runs("olap")``
    vocabulary both executors and the sharded plane publish)."""
    records = run.get("superstep_records") or []
    wall_ms = float(run.get("wall_s", 0.0)) * 1000.0
    n = max(len(records), 1)
    fallback_ms = wall_ms / n if wall_ms > 0 else 1.0
    path = run.get("path", "unknown")
    executor = run.get("executor", "tpu")
    events: List[dict] = [
        _meta("process_name", TID_HOST,
              f"olap {executor} ({path})"),
        _meta("thread_name", TID_HOST, "supersteps"),
    ]
    shards = (run.get("shards") or {}).get("per_shard") or []
    exchange = run.get("exchange") or {}
    if shards:
        for s, _row in enumerate(shards):
            events.append(
                _meta("thread_name", TID_SHARD0 + s, f"shard {s}")
            )
    need_control = bool(
        run.get("resumes") or
        any("checkpoint_ms" in r for r in records)
    )
    if need_control:
        events.append(_meta("thread_name", TID_CONTROL, "checkpoint"))

    ts = 0.0
    # resumes replayed BEFORE the recorded (post-resume) steps: one slice
    # at the front of the control lane keeps the run's wall honest
    resumes = int(run.get("resumes", 0) or 0)
    if resumes:
        resume_ms = float(run.get("resume_ms", 0.0) or 0.0)
        events.append(_slice(
            f"resume x{resumes}", 0.0, resume_ms * 1000.0, TID_CONTROL,
            args={"resumes": resumes, "resume_ms": resume_ms,
                  "steps": run.get("resume_steps")},
        ))
        ts = resume_ms * 1000.0

    # shard compute shares: scale each shard's modeled/measured wall by
    # its share of the slowest shard (the barrier pace-setter)
    shard_share = []
    if shards:
        walls = [
            float(r.get("measured_ms") or r.get("modeled_ms") or 0.0)
            for r in shards
        ]
        top = max(walls) if walls and max(walls) > 0 else 1.0
        shard_share = [w / top for w in walls]

    for i, r in enumerate(records):
        dur_us = float(r.get("wall_ms", fallback_ms)) * 1000.0
        args = {k: r[k] for k in _ARG_KEYS if k in r}
        step = int(r.get("step", i))
        events.append(_slice(
            f"superstep {step}", ts, dur_us, TID_HOST, args=args,
        ))
        for s, share in enumerate(shard_share):
            comp_us = dur_us * share
            events.append(_slice(
                "compute", ts, comp_us, TID_SHARD0 + s,
                args={"share": round(share, 4),
                      "cost_source": shards[s].get("cost_source")},
            ))
            ex_args = {
                "mode": exchange.get("mode"),
                "agg": exchange.get("agg"),
                "elems_per_superstep": exchange.get(
                    "elems_per_superstep",
                    exchange.get("elems"),
                ),
                "bytes_per_superstep": exchange.get(
                    "bytes_per_superstep", exchange.get("bytes"),
                ),
                "batches": exchange.get(
                    "batches_per_superstep", exchange.get("batches"),
                ),
            }
            events.append(_slice(
                "exchange", ts + comp_us, dur_us - comp_us,
                TID_SHARD0 + s,
                args={k: v for k, v in ex_args.items() if v is not None},
                cat="exchange",
            ))
        ck_ms = r.get("checkpoint_ms")
        if ck_ms is not None:
            # the save ran at the END of this superstep's boundary; its
            # wall is part of the recorded step wall on the single-
            # executor paths, so overlay it at the slice tail
            ck_us = float(ck_ms) * 1000.0
            events.append(_slice(
                "checkpoint_save", ts + max(dur_us - ck_us, 0.0), ck_us,
                TID_CONTROL,
                args={"step": step, "checkpoint_ms": ck_ms},
            ))
        ts += dur_us
    return events


def chrome_trace(run: dict) -> dict:
    """The full Chrome-trace document for one run record."""
    meta_keys = (
        "path", "executor", "supersteps", "wall_s", "resumes",
        "resume_ms", "strategy_resolved", "pad_ratio", "retraces",
    )
    return {
        "traceEvents": timeline_events(run),
        "displayTimeUnit": "ms",
        "otherData": {k: run[k] for k in meta_keys if k in run},
    }


def render_run(registry, run: int = -1, kind: str = "olap") -> Optional[dict]:
    """Render the ``run``-th retained record (negative = from the end,
    default last). None when no such record is retained."""
    runs = registry.runs(kind)
    if not runs:
        return None
    try:
        rec = runs[run]
    except IndexError:
        return None
    return chrome_trace(rec)


def validate_chrome_trace(doc) -> Optional[str]:
    """Light validity check (tests + CLI): the document must be
    JSON-serializable, carry a ``traceEvents`` list, and every event must
    have the catapult-required fields with sane values. Returns an error
    string or None."""
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as e:
        return f"not JSON-serializable: {e}"
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return "missing traceEvents list"
    for ev in doc["traceEvents"]:
        ph = ev.get("ph")
        if ph not in ("X", "M", "i", "B", "E", "C"):
            return f"unknown phase {ph!r}"
        if "name" not in ev or "pid" not in ev or "tid" not in ev:
            return f"event missing name/pid/tid: {ev}"
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)):
                return f"X event without numeric ts: {ev}"
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                return f"X event without non-negative dur: {ev}"
    return None
