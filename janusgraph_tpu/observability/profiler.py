"""Roofline profiler, per-query resource ledger, digest table, flame export.

PR 2/4 made latency and causality visible (histograms, spans, stitched
traces, the flight recorder); this module makes COST visible and
attributable — the two lenses the SpMM/graph-kernel literature says decide
graph-engine performance (PAPERS.md: arxiv 2011.06391 FusedMM lives or
dies by operational intensity; 2011.08451 finds bottlenecks through DRAM
traffic accounting):

- :class:`ResourceLedger` — a small per-query accumulator carried on the
  ambient context (contextvar, like the span tracer). Every instrumented
  layer accrues into it: cells read/written at the KCVS boundary, index
  hits, host<->device transfer bytes, retry replays, wall by layer. The
  remote-store/index protocols propagate a ledger request flag next to
  the trace header (behind the same feature-bit negotiation, so mixed
  old/new pairs stay byte-compatible) and the serving node echoes its
  measured costs back; the query server echoes the request's ledger to
  the driver in ``status.ledger``.

  Attribution invariant: every PRIMARY accrual also annotates the
  current span with ``ledger.<field>`` attributes; merges of a remote
  peer's echo never re-annotate (the peer's own span already carries the
  fields). A trace's ledger totals therefore equal the sum of the
  ``ledger.*`` attributes over its spans.

- **Roofline cost model** — superstep kernels are lowered once and XLA's
  ``cost_analysis()`` (flops, bytes accessed) harvested from the lowered
  module; a host-side estimator stands in when the backend exposes no
  cost analysis. Operational intensity (flops/byte) and %-of-roofline
  utilization (achieved flops/s over ``min(peak_flops, oi * peak_bw)``)
  land in every OLAP run record, per superstep and per E_cap tier.

- :class:`DigestTable` — traversals normalize to a shape digest (step
  vocabulary + index choice, literals stripped), and a bounded top-K
  table keyed by digest accumulates count / total cost / p50/p95 wall.
  Scrapeable at ``GET /profile`` and via ``janusgraph_tpu top``; slow-op
  and flight-recorder ``slow_span`` events carry the digest so recurring
  offenders group instead of appearing as one-offs.

- **Flamegraph export** — any stitched trace's span tree renders to
  collapsed-stack format (``frame;frame;frame weight_us``) with ledger
  annotations folded into frame names, at ``GET /profile/flame?trace=<id>``
  and ``janusgraph_tpu flame <id>``.

Recording is HOST-ONLY like the rest of the observability layer: no
ledger/digest/cost call may run inside jit-traced code (graphlint JG108,
same family as JG106/JG107).
"""

from __future__ import annotations

import contextvars
import hashlib
import re
import struct
import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# Resource ledger
# --------------------------------------------------------------------------

#: the counter vocabulary — one shared naming for OLTP profile trees,
#: OLAP run records, span annotations, and the wire blocks
COUNTER_FIELDS = (
    "cells_read",
    "cells_written",
    "bytes_read",
    "bytes_written",
    "index_hits",
    "retries",
    "h2d_bytes",
    "d2h_bytes",
)

#: wire tags (tag-value pairs, so the block can grow without a protocol
#: bump); wall_ns rides the wire but merges into wall_by_layer, not a
#: counter
_FIELD_TAGS: Dict[str, int] = {f: i + 1 for i, f in enumerate(COUNTER_FIELDS)}
_FIELD_TAGS["wall_ns"] = 15
_TAG_FIELDS = {v: k for k, v in _FIELD_TAGS.items()}


class ResourceLedger:
    """Per-query cost accumulator (cells, bytes, hits, retries, walls)."""

    __slots__ = ("counters", "wall_by_layer", "_lock")

    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.wall_by_layer: Dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, **fields) -> "ResourceLedger":
        with self._lock:
            for k, v in fields.items():
                if v:
                    self.counters[k] = self.counters.get(k, 0) + int(v)
        return self

    def add_wall(self, layer: str, ms: float) -> "ResourceLedger":
        with self._lock:
            self.wall_by_layer[layer] = (
                self.wall_by_layer.get(layer, 0.0) + float(ms)
            )
        return self

    def merge(self, other: "ResourceLedger") -> None:
        with other._lock:
            counters = dict(other.counters)
            walls = dict(other.wall_by_layer)
        self.add(**counters)
        for layer, ms in walls.items():
            self.add_wall(layer, ms)

    def get(self, field: str) -> int:
        with self._lock:
            return self.counters.get(field, 0)

    def op_cells(self) -> int:
        with self._lock:
            return self.counters.get("cells_read", 0) + self.counters.get(
                "cells_written", 0
            )

    def to_dict(self) -> dict:
        with self._lock:
            out: Dict[str, object] = dict(self.counters)
            if self.wall_by_layer:
                out["wall_ms_by_layer"] = {
                    k: round(v, 3) for k, v in self.wall_by_layer.items()
                }
        return out


_LEDGER_VAR: "contextvars.ContextVar[Optional[ResourceLedger]]" = (
    contextvars.ContextVar("janusgraph_tpu_ledger", default=None)
)


def current_ledger() -> Optional[ResourceLedger]:
    return _LEDGER_VAR.get()


@contextmanager
def ledger_scope():
    """Run a block under a fresh ledger; on exit the block's accruals
    merge into the enclosing scope (if any), so a nested ``.profile()``
    still counts toward the surrounding server request."""
    led = ResourceLedger()
    parent = _LEDGER_VAR.get()
    token = _LEDGER_VAR.set(led)
    try:
        yield led
    finally:
        _LEDGER_VAR.reset(token)
        if parent is not None:
            parent.merge(led)


def accrue(**fields) -> None:
    """PRIMARY accrual: add to the ambient ledger AND annotate the current
    span with aggregating ``ledger.<field>`` attributes. No-op outside a
    ledger scope (zero overhead for unprofiled work). Never call from
    jit-traced code (graphlint JG108)."""
    led = _LEDGER_VAR.get()
    if led is None:
        return
    led.add(**fields)
    from janusgraph_tpu.observability import tracer

    sp = tracer.current()
    if sp is not None:
        for k, v in fields.items():
            if v:
                key = f"ledger.{k}"
                sp.attrs[key] = int(sp.attrs.get(key, 0)) + int(v)


def accrue_wall(layer: str, ms: float) -> None:
    """Layer-wall accrual (no span annotation: the span's own duration
    already represents the wall; this just buckets it by layer)."""
    led = _LEDGER_VAR.get()
    if led is not None and ms:
        led.add_wall(layer, ms)


def merge_echo(fields: Optional[dict], layer: str = "") -> None:
    """Merge a remote peer's echoed ledger block into the ambient ledger
    WITHOUT annotating a span — the peer annotated its own span with the
    same fields, and the two sides of the wire must not double-count."""
    if not fields:
        return
    led = _LEDGER_VAR.get()
    if led is None:
        return
    counters = {k: v for k, v in fields.items() if k in _FIELD_TAGS and k != "wall_ns"}
    led.add(**counters)
    wall_ns = fields.get("wall_ns")
    if wall_ns and layer:
        led.add_wall(layer, wall_ns / 1e6)


# ------------------------------------------------------------- wire codec
_LEDGER_VERSION = 1


def encode_ledger_block(fields: dict) -> bytes:
    """``[u8 blen][ver:1][n:1]([tag:1][u64])*`` — length-prefixed like the
    trace-context prefix, so it can ride in front of any response body."""
    pairs = [
        (_FIELD_TAGS[k], int(v))
        for k, v in fields.items()
        if k in _FIELD_TAGS and v
    ]
    payload = bytes([_LEDGER_VERSION, len(pairs)]) + b"".join(
        struct.pack(">BQ", tag, value) for tag, value in pairs
    )
    return bytes([len(payload)]) + payload


def split_ledger_block(body: bytes) -> Tuple[Optional[dict], bytes]:
    """Inverse of :func:`encode_ledger_block`: (fields|None, rest).
    Malformed blocks degrade to None — a bad ledger must never fail the
    response it rides on."""
    if not body:
        return None, body
    blen = body[0]
    if len(body) < 1 + blen or blen < 2:
        return None, body
    payload, rest = body[1 : 1 + blen], body[1 + blen :]
    if payload[0] != _LEDGER_VERSION:
        return None, body
    n = payload[1]
    if len(payload) != 2 + 9 * n:
        return None, body
    fields: Dict[str, int] = {}
    for i in range(n):
        tag, value = struct.unpack_from(">BQ", payload, 2 + 9 * i)
        name = _TAG_FIELDS.get(tag)
        if name is not None:
            fields[name] = value
    return fields, rest


# --------------------------------------------------------------------------
# Query digests
# --------------------------------------------------------------------------

#: literals embedded in step labels (e.g. ``adjacentVertexHasId(1, 2)``)
_LITERAL_RE = re.compile(r"\(.*\)|['\"].*['\"]|\d+", re.S)


def traversal_shape(labels, plan: Optional[dict] = None) -> str:
    """Normalize a traversal to its shape: the step vocabulary joined in
    order with literals stripped, prefixed by the resolved access path
    (index choice included — two queries that differ only in literals or
    in nothing the planner sees share a shape)."""
    plan = plan or {}
    access = plan.get("access", "traversal")
    index = plan.get("index")
    head = f"{access}[{index}]" if index else str(access)
    steps = [_LITERAL_RE.sub("", str(lb)).strip() or "step" for lb in labels]
    return ">".join([head] + steps) if steps else head


def shape_digest(shape: str) -> str:
    """Stable 8-hex-char digest of a shape string."""
    return hashlib.sha1(shape.encode()).hexdigest()[:8]


class DigestTable:
    """Bounded top-K table of query digests ranked by total cost.

    One entry per digest: occurrence count, total wall, total cells, and
    a log-bucket wall histogram for p50/p95. When the table exceeds its
    capacity the entry with the smallest total cost is evicted — heavy
    hitters survive, one-off shapes age out."""

    def __init__(self, capacity: int = 128):
        self.capacity = capacity
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def configure(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and capacity > 0:
                self.capacity = capacity

    def observe(
        self, digest: str, shape: str, wall_ms: float, cells: int = 0
    ) -> None:
        """Record one execution of a digest. Never call from jit-traced
        code (graphlint JG108)."""
        from janusgraph_tpu.observability.metrics_core import Histogram

        with self._lock:
            e = self._entries.get(digest)
            if e is None:
                e = self._entries[digest] = {
                    "digest": digest,
                    "shape": shape,
                    "count": 0,
                    "total_ms": 0.0,
                    "total_cells": 0,
                    "hist": Histogram(),
                }
            e["count"] += 1
            e["total_ms"] += float(wall_ms)
            e["total_cells"] += int(cells)
            e["hist"].observe(float(wall_ms))
            if len(self._entries) > self.capacity:
                victim = min(
                    self._entries, key=lambda d: self._entries[d]["total_ms"]
                )
                del self._entries[victim]

    def mean_cost_ms(self, digest: str) -> Optional[float]:
        """Measured mean wall of one digest (total/count), or None when
        the table has never seen it — the admission controller's price
        lookup (unknown shapes pay its default price instead)."""
        with self._lock:
            e = self._entries.get(digest)
            if e is None or not e["count"]:
                return None
            return e["total_ms"] / e["count"]

    def top(self, k: int = 10) -> List[dict]:
        """The k digests with the largest total cost, descending."""
        with self._lock:
            entries = list(self._entries.values())
        entries.sort(key=lambda e: e["total_ms"], reverse=True)
        out = []
        for e in entries[:k]:
            h = e["hist"]
            out.append({
                "digest": e["digest"],
                "shape": e["shape"],
                "count": e["count"],
                "total_ms": round(e["total_ms"], 3),
                "total_cells": e["total_cells"],
                "p50_ms": round(h.percentile(0.50), 3),
                "p95_ms": round(h.percentile(0.95), 3),
            })
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


#: process-wide digest table; GET /profile and `janusgraph_tpu top` read it
digest_table = DigestTable()


# ------------------------------------------------------------- price book
# Persistence for DigestTable contents (the "price book"): the OLTP shape
# table above and the admission controller's server-side table serialize
# to ONE JSON file next to the autotune record (computer.price-book-path,
# default <computer.checkpoint-path>.pricebook.json), written tmp+rename
# and loaded at graph open / server start — spillover promotion and
# admission pricing warm-start instead of re-learning every process
# lifetime.

_PRICE_BOOK_VERSION = 1


def digest_records(table: DigestTable) -> List[dict]:
    """Serialize a table's entries (histogram bucket counts included, so
    restored p50/p95 match the live table's log-bucket resolution)."""
    with table._lock:
        entries = [dict(e) for e in table._entries.values()]
    out = []
    for e in entries:
        h = e["hist"]
        with h._lock:
            counts = list(h._counts)
            hcount, htotal, hmax = h.count, h.total, h.max
        out.append({
            "digest": e["digest"],
            "shape": e["shape"],
            "count": e["count"],
            "total_ms": e["total_ms"],
            "total_cells": e["total_cells"],
            "hist": {
                "counts": counts, "count": hcount,
                "total": htotal, "max": hmax,
            },
        })
    return out


def restore_digest_records(table: DigestTable, records) -> int:
    """Merge persisted records into a live table (existing entries win —
    fresh in-process measurements outrank a stale file). Malformed
    records are skipped; returns how many were loaded."""
    from janusgraph_tpu.observability.metrics_core import Histogram

    loaded = 0
    for r in records or ():
        try:
            digest = str(r["digest"])
            hist = Histogram()
            hd = r.get("hist") or {}
            counts = list(hd.get("counts") or ())
            if len(counts) == len(hist._counts):
                hist._counts = [int(c) for c in counts]
            hist.count = int(hd.get("count", r["count"]))
            hist.total = float(hd.get("total", r["total_ms"]))
            hist.max = float(hd.get("max", 0.0))
            entry = {
                "digest": digest,
                "shape": str(r.get("shape", "")),
                "count": int(r["count"]),
                "total_ms": float(r["total_ms"]),
                "total_cells": int(r.get("total_cells", 0)),
                "hist": hist,
            }
        except (KeyError, TypeError, ValueError):
            continue
        with table._lock:
            if digest in table._entries:
                continue
            table._entries[digest] = entry
            loaded += 1
            if len(table._entries) > table.capacity:
                victim = min(
                    table._entries,
                    key=lambda d: table._entries[d]["total_ms"],
                )
                del table._entries[victim]
    return loaded


def save_price_book(path: str, tables: Dict[str, DigestTable]) -> None:
    """Atomically persist the named tables (tmp + rename, the autotune
    record's discipline), preserving any OTHER table already in the file.
    Persistence must never fail the caller — I/O errors are swallowed."""
    import json
    import os
    import tempfile

    try:
        payload_tables = dict(load_price_book(path))
        for name, table in tables.items():
            payload_tables[name] = digest_records(table)
        payload = {"version": _PRICE_BOOK_VERSION, "tables": payload_tables}
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    except OSError:
        return


def load_price_book(path: str) -> Dict[str, List[dict]]:
    """{table name: [records]} from a persisted price book; {} when the
    file is missing, unreadable, or from an unknown version."""
    import json

    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(payload, dict) or payload.get(
        "version"
    ) != _PRICE_BOOK_VERSION:
        return {}
    tables = payload.get("tables")
    return tables if isinstance(tables, dict) else {}


# --------------------------------------------------------------------------
# Roofline cost model
# --------------------------------------------------------------------------

#: (device_kind substring, peak flops/s, peak HBM bytes/s, peak MXU
#: flops/s). Order matters: first match wins. Conservative public figures;
#: override exactly via metrics.roofline-peak-flops /
#: metrics.roofline-peak-bytes-per-s / metrics.roofline-peak-mxu-flops.
#: The MXU column is the dense-matmul (systolic-array) ceiling the
#: dense-feature tier's `mxu_utilization` divides by — the TPU marketing
#: numbers ARE the MXU peaks, so those columns coincide; CPU gets a
#: modest BLAS-class figure so the ratio stays meaningful on every
#: backend (relative shape, not absolute truth).
_DEVICE_PEAKS: Tuple[Tuple[str, float, float, float], ...] = (
    ("v5e", 197e12, 819e9, 197e12),
    ("v5p", 459e12, 2765e9, 459e12),
    ("v4", 275e12, 1228e9, 275e12),
    ("v3", 123e12, 900e9, 123e12),
    ("v2", 45e12, 700e9, 45e12),
    # CPU fallback: a generous server-class core count; the point on CPU
    # is the RELATIVE utilization shape, not absolute truth
    ("cpu", 5e11, 5e10, 1e11),
)

_ROOFLINE_OVERRIDE = {
    "peak_flops": 0.0, "peak_bytes_per_s": 0.0, "peak_mxu_flops": 0.0,
}


def configure_roofline(
    peak_flops: Optional[float] = None,
    peak_bytes_per_s: Optional[float] = None,
    peak_mxu_flops: Optional[float] = None,
) -> None:
    """Operator override of the device-peak table (0 = auto-detect)."""
    if peak_flops is not None:
        _ROOFLINE_OVERRIDE["peak_flops"] = float(peak_flops)
    if peak_bytes_per_s is not None:
        _ROOFLINE_OVERRIDE["peak_bytes_per_s"] = float(peak_bytes_per_s)
    if peak_mxu_flops is not None:
        _ROOFLINE_OVERRIDE["peak_mxu_flops"] = float(peak_mxu_flops)


def device_peaks(device_kind: Optional[str] = None) -> dict:
    """{peak_flops, peak_bytes_per_s, peak_mxu_flops, device_kind, source}
    for the current (or named) device. Host-side metadata only — no
    device sync."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 - jax may be absent/uninitialized
            device_kind = "cpu"
    kind = (device_kind or "cpu").lower()
    flops, bw, mxu, source = 0.0, 0.0, 0.0, "default"
    for sub, pf, pb, pm in _DEVICE_PEAKS:
        if sub in kind:
            flops, bw, mxu, source = pf, pb, pm, f"table:{sub}"
            break
    if not flops:
        flops, bw, mxu = (
            _DEVICE_PEAKS[-1][1], _DEVICE_PEAKS[-1][2], _DEVICE_PEAKS[-1][3]
        )
    if _ROOFLINE_OVERRIDE["peak_flops"]:
        flops, source = _ROOFLINE_OVERRIDE["peak_flops"], "config"
    if _ROOFLINE_OVERRIDE["peak_bytes_per_s"]:
        bw = _ROOFLINE_OVERRIDE["peak_bytes_per_s"]
        source = "config"
    if _ROOFLINE_OVERRIDE["peak_mxu_flops"]:
        mxu = _ROOFLINE_OVERRIDE["peak_mxu_flops"]
        source = "config"
    return {
        "peak_flops": flops,
        "peak_bytes_per_s": bw,
        "peak_mxu_flops": mxu,
        "device_kind": device_kind,
        "source": source,
    }


def harvest_cost(lowered) -> Optional[dict]:
    """Harvest {flops, bytes_accessed} from a ``jax.stages.Lowered`` (or
    ``Compiled``) via XLA's cost analysis. Returns None when the backend
    exposes nothing usable — callers fall back to the host estimator.
    Host-side only: lowering metadata, never a dispatch."""
    try:
        ca = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent API
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and bytes_accessed <= 0.0:
        return None
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "cost_source": "xla",
    }


def estimate_superstep_cost(
    num_vertices: int,
    num_edges: int,
    msg_cols: int = 1,
    weighted: bool = False,
    arg_bytes: int = 0,
) -> dict:
    """Host-side fallback when XLA cost analysis is unavailable: one BSP
    superstep gathers a message per edge (one multiply when weighted),
    combines at the destination (one op per edge) and applies elementwise
    per vertex. Byte traffic = the shipped argument pytree (or an index +
    message estimate when unknown) plus state in/out."""
    cols = max(1, int(msg_cols))
    flops = float(num_edges) * cols * (2.0 if weighted else 1.0)
    flops += 5.0 * float(num_vertices) * cols
    if arg_bytes <= 0:
        arg_bytes = 8 * num_edges + 4 * num_vertices
    bytes_accessed = float(arg_bytes) + 8.0 * float(num_vertices) * cols
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "cost_source": "estimate",
    }


def roofline_point(
    flops: float, bytes_accessed: float, wall_ms: float, peaks: dict
) -> dict:
    """Operational intensity + utilization for one measured kernel wall.
    Utilization = achieved flops/s over the roofline ceiling at this OI
    (``min(peak_flops, oi * peak_bw)`` — the classic two-segment roof)."""
    oi = flops / bytes_accessed if bytes_accessed > 0 else 0.0
    out = {"operational_intensity": round(oi, 5)}
    if wall_ms and wall_ms > 0 and flops > 0:
        achieved = flops / (wall_ms / 1e3)
        roof = min(peaks["peak_flops"], oi * peaks["peak_bytes_per_s"])
        out["roofline_utilization"] = (
            round(achieved / roof, 6) if roof > 0 else 0.0
        )
    else:
        out["roofline_utilization"] = None
    return out


def attach_roofline(records: List[dict], cost: dict, peaks: dict) -> dict:
    """Stamp per-superstep records with flops / bytes / OI / utilization
    and return the per-E_cap-tier aggregation. ``cost`` is one kernel's
    {flops, bytes_accessed, cost_source} (the same executable serves every
    superstep, so the cost is per dispatch); walls come from each record."""
    tiers: Dict[object, dict] = {}
    for r in records:
        r.setdefault("flops", cost["flops"])
        r.setdefault("bytes_accessed", cost["bytes_accessed"])
        r.setdefault("cost_source", cost["cost_source"])
        point = roofline_point(
            r["flops"], r["bytes_accessed"], r.get("wall_ms", 0.0), peaks
        )
        r.update(point)
        tier = r.get("e_cap", "dense")
        t = tiers.setdefault(
            tier, {"supersteps": 0, "oi_sum": 0.0, "util_sum": 0.0,
                   "util_n": 0},
        )
        t["supersteps"] += 1
        t["oi_sum"] += point["operational_intensity"]
        if point["roofline_utilization"] is not None:
            t["util_sum"] += point["roofline_utilization"]
            t["util_n"] += 1
    out = {}
    for tier, t in tiers.items():
        out[str(tier)] = {
            "supersteps": t["supersteps"],
            "operational_intensity": round(t["oi_sum"] / t["supersteps"], 5),
            "roofline_utilization": (
                round(t["util_sum"] / t["util_n"], 6) if t["util_n"] else None
            ),
        }
    return out


def attach_mxu(records: List[dict], mxu_flops: float, peaks: dict) -> dict:
    """Stamp per-superstep records with the dense tier's MXU accounting:
    ``mxu_flops`` (matmul-attributable flops per superstep — dense layers
    + sddmm dots, from the program's ``matmul_flops``) and
    ``mxu_utilization`` (achieved matmul flops/s over the device's MXU
    peak). Returns the run-level summary block (``run_info["mxu"]``)."""
    peak = float(peaks.get("peak_mxu_flops") or 0.0)
    utils = []
    for r in records:
        r["mxu_flops"] = mxu_flops
        wall = r.get("wall_ms")
        if not mxu_flops:
            r["mxu_utilization"] = 0.0
        elif wall and wall > 0 and peak > 0:
            u = round((mxu_flops / (wall / 1e3)) / peak, 6)
            r["mxu_utilization"] = u
            utils.append(u)
        else:
            r["mxu_utilization"] = None
    return {
        "peak_mxu_flops": peak,
        "per_superstep_flops": mxu_flops,
        "mean_utilization": (
            round(sum(utils) / len(utils), 6) if utils else None
        ),
    }


# --------------------------------------------------------------------------
# Flamegraph export
# --------------------------------------------------------------------------

_FRAME_SANITIZE = re.compile(r"[;\s]+")


def _frame_name(span) -> str:
    """One collapsed-stack frame: the span name, with ledger annotations
    folded in (semicolons and whitespace are the format's separators, so
    they are squeezed out)."""
    name = _FRAME_SANITIZE.sub("_", span.name)
    led = sorted(
        (k[len("ledger."):], v)
        for k, v in span.attrs.items()
        if k.startswith("ledger.")
    )
    if led:
        name += "(" + ",".join(f"{k}:{v}" for k, v in led) + ")"
    return name


def flame_lines(roots) -> List[str]:
    """Render a trace's span trees to collapsed-stack lines
    (``frame;frame;frame weight``, weight = self-time in µs). Roots that
    joined a remote parent (``parent_span_id``) are grafted under that
    span when it is retained locally, so a stitched cross-process trace
    folds into one flame."""
    by_id: Dict[int, List[str]] = {}

    def index(span, prefix: List[str]):
        path = prefix + [_frame_name(span)]
        by_id[span.span_id] = path
        for c in span.children:
            index(c, path)

    attached: List[object] = []
    pending = list(roots)
    # multi-pass graft: a remote-parented root can only be placed once its
    # parent's tree is indexed, whatever order the ring returned them in
    while pending:
        progressed = False
        rest = []
        for r in pending:
            parent_path = by_id.get(r.parent_span_id) if r.parent_span_id else []
            if parent_path is not None:
                index(r, parent_path or [])
                attached.append(r)
                progressed = True
            else:
                rest.append(r)
        if not progressed:
            for r in rest:  # orphaned remote roots: emit as separate stacks
                index(r, [])
                attached.append(r)
            rest = []
        pending = rest

    lines: List[str] = []

    def emit(span, prefix: List[str]):
        path = prefix + [_frame_name(span)]
        child_ms = sum(c.duration_ms for c in span.children)
        self_us = max(0, int(round((span.duration_ms - child_ms) * 1000)))
        lines.append(f"{';'.join(path)} {self_us}")
        for c in span.children:
            emit(c, path)

    for r in attached:
        prefix = by_id[r.span_id][:-1]
        emit(r, prefix)
    return lines


def flame_text(tracer, trace_id) -> str:
    """Collapsed-stack rendering of one retained trace (newline-joined;
    empty string when the trace is not retained)."""
    roots = tracer.find_trace(trace_id)
    return "\n".join(flame_lines(roots))
