"""Exposition renderers: Prometheus text format + JSON snapshot.

``prometheus_text`` renders the registry in the text exposition format
(one ``# TYPE`` per family; counters as ``_total``, timers as
``_seconds`` histograms with cumulative ``le`` buckets, value histograms
raw, gauges as-is). Served at ``GET /metrics`` by the query server and by
``python -m janusgraph_tpu telemetry``.

``json_snapshot`` bundles the metric snapshot, recent span trees, the
slow-op log and the structured run records — the ``GET /telemetry``
payload and what ``bench.py`` attaches to its artifacts.
"""

from __future__ import annotations

import re
from typing import Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _pname(prefix: str, name: str) -> str:
    out = _NAME_RE.sub("_", f"{prefix}_{name}" if prefix else name)
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    # integral values print as ints: keeps counter samples exact
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _histogram_lines(lines, name, buckets, total_count, total_sum, scale=1.0):
    """Cumulative `le` buckets + +Inf + _sum/_count for one histogram.
    `scale` converts the stored unit (e.g. ns -> seconds: 1e-9)."""
    lines.append(f"# TYPE {name} histogram")
    for le, cum in buckets:
        lines.append(f'{name}_bucket{{le="{repr(le * scale)}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {total_count}')
    lines.append(f"{name}_sum {repr(total_sum * scale)}")
    lines.append(f"{name}_count {total_count}")


def prometheus_text(registry, prefix: str = "janusgraph") -> str:
    from janusgraph_tpu.observability.identity import replica_name

    counters, timers, histograms, gauges = registry.metric_objects()
    lines = []
    replica = replica_name()
    if replica:
        # the fleet identity rides /metrics as a Prometheus info metric
        # (the k8s `*_info` convention): scrapes from N replicas stay
        # distinguishable even behind one relabeling-free scrape target
        n = _pname(prefix, "replica_info")
        lines.append(f"# TYPE {n} gauge")
        lines.append(
            f'{n}{{replica="{_NAME_RE.sub("_", replica)}"}} 1'
        )
    for name in sorted(counters):
        n = _pname(prefix, name) + "_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {counters[name].count}")
    for name in sorted(gauges):
        n = _pname(prefix, name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(gauges[name].value)}")
    for name in sorted(timers):
        t = timers[name]
        _histogram_lines(
            lines, _pname(prefix, name) + "_seconds",
            t.cumulative_buckets(), t.count, t.total, scale=1e-9,
        )
    for name in sorted(histograms):
        h = histograms[name]
        _histogram_lines(
            lines, _pname(prefix, name),
            h.cumulative_buckets(), h.count, h.total,
        )
    return "\n".join(lines) + "\n"


def json_snapshot(registry, tracer=None, span_limit: int = 32) -> dict:
    """Everything in one JSON-friendly dict: metric snapshot, recent span
    trees (newest last, bounded), slow-op events, structured run logs."""
    out = {"metrics": registry.snapshot()}
    runs = {}
    for kind in ("olap",):
        rs = registry.runs(kind)
        if rs:
            runs[kind] = rs
    out["runs"] = runs
    if tracer is not None:
        roots = tracer.recent()
        out["spans"] = [r.to_dict() for r in roots[-span_limit:]]
        out["slow_ops"] = tracer.slow_ops()
    return out


def validate_prometheus_text(text: str) -> Optional[str]:
    """Light validity check used by tests/CLI: returns an error string or
    None. Checks sample-line syntax, histogram bucket monotonicity and
    that `+Inf` matches `_count`."""
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+(Inf|nan)?$"
    )
    buckets: dict = {}
    counts: dict = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        if not sample_re.match(ln):
            return f"malformed sample line: {ln!r}"
        name_part, value = ln.rsplit(" ", 1)
        if "_bucket{" in name_part:
            base = name_part.split("_bucket{", 1)[0]
            buckets.setdefault(base, []).append(float(value))
        elif name_part.endswith("_count") and base_of(name_part) in buckets:
            counts[base_of(name_part)] = float(value)
    for base, cums in buckets.items():
        if any(lo > hi for lo, hi in zip(cums, cums[1:])):
            return f"non-monotone buckets for {base}"
        if base in counts and cums and cums[-1] != counts[base]:
            return f"+Inf bucket != _count for {base}"
    return None


def base_of(name_part: str) -> str:
    return name_part[: -len("_count")]
