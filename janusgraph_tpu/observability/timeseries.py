"""Time-series metrics history: a bounded ring of periodic snapshots.

``/metrics`` and ``/telemetry`` (PR 2) expose the registry *now*; the
flight recorder (PR 4) keeps salient events; the profiler (PR 5) prices
single runs. None of them can answer the questions the serving rung
lives on — "is p99 degrading over the last five minutes?", "is the AIMD
limit oscillating?" — because nothing retains history. This module does:

- :class:`MetricsHistory` samples the process registry at a fixed
  interval (injectable clock; the server owns the sampling thread) into
  a bounded ring of **windows**. Counters and timers are cumulative at
  the source, so each window stores the **delta** against the previous
  sample — the rate the operator actually wants — while gauges store the
  sampled value. Histogram/timer windows keep the per-window bucket
  delta vector, so window percentiles (p50/p95/p99 *of that window*, not
  of process lifetime) and threshold fractions ("what fraction of this
  window's requests ran over 250 ms") are exact to the shared log2
  bucket ladder.

- Every per-metric read goes through ``Histogram.state()`` — one lock
  acquisition per metric — so a window can never be torn by a concurrent
  ``observe`` (sum of bucket deltas == count delta, always; the
  test_telemetry hammer asserts this against a live sampler).

- Sampling cost is measured into the ``observability.history.overhead_ms``
  gauge (last sample) and the ``observability.history.sample``
  timer. The sampler never touches request paths: it reads the same
  per-metric locks request threads use for nanoseconds each, nothing
  more.

- Retention is ``metrics.history-retention`` windows of
  ``metrics.history-interval-s`` seconds (defaults: 360 x 5 s = 30 min).
  ``GET /timeseries?name=&window=`` and ``janusgraph_tpu timeseries``
  query it; :meth:`MetricsHistory.export_jsonl` writes one JSON line per
  window for offline analysis.

Listeners (the SLO engine) run after each sample on the sampler thread,
so burn-rate evaluation is clocked by the same windows it reads —
deterministic under a fake clock with manual :meth:`sample` calls.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from janusgraph_tpu.observability.metrics_core import (
    BUCKET_BOUNDS,
    Histogram,
)

OVERHEAD_GAUGE = "observability.history.overhead_ms"


class MetricsHistory:
    """Bounded ring of periodic registry snapshots (delta windows)."""

    def __init__(
        self,
        registry=None,
        capacity: int = 360,
        interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self._registry = registry
        self.interval_s = float(interval_s)
        self._clock = clock
        self._wall = wall_clock
        self._ring: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._seq = 0
        #: previous cumulative values per metric, for window deltas
        self._prev_counters: Dict[str, int] = {}
        self._prev_hist: Dict[str, tuple] = {}  # name -> (count, total, counts)
        self._listeners: List[Callable[[dict], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- lifecycle
    def configure(
        self,
        capacity: Optional[int] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=int(capacity))
            if interval_s is not None and interval_s > 0:
                self.interval_s = float(interval_s)

    def bind(self, registry) -> "MetricsHistory":
        self._registry = registry
        return self

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Register a per-window hook (the SLO engine); runs on the
        sampling thread after each window lands."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def start(self, interval_s: Optional[float] = None) -> None:
        """Start the background sampler (idempotent). The server calls
        this at start(); embedded use can call it directly."""
        if interval_s is not None:
            self.configure(interval_s=interval_s)
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception as e:  # noqa: BLE001 - sampling must not die
                    # record before continuing (JG112): a silently
                    # failing sampler leaves a stale ring that reads as
                    # a healthy-but-frozen process
                    from janusgraph_tpu.observability.flight import (
                        recorder,
                    )

                    recorder.record(
                        "thread_error", thread="metrics-history",
                        error=repr(e),
                    )

        self._thread = threading.Thread(
            target=_loop, name="metrics-history", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -------------------------------------------------------------- sampling
    def sample(self) -> dict:
        """Take one window: read the registry (consistent per-metric
        ``state()`` reads), diff against the previous cumulative values,
        append the delta window, notify listeners. Returns the window."""
        registry = self._registry
        if registry is None:
            from janusgraph_tpu.observability import registry as _r

            registry = self._registry = _r
        t0 = time.perf_counter()
        counters, timers, histograms, gauges = registry.metric_objects()
        counter_deltas: Dict[str, int] = {}
        hist_windows: Dict[str, dict] = {}
        # the prev-cumulative maps are shared with reset(), which clears
        # them under _lock from the caller's thread while this runs on the
        # sampler thread — diff and update them under the same lock (the
        # reads here are in-memory registry state, never blocking)
        with self._lock:
            for name, c in counters.items():
                cur = c.count
                prev = self._prev_counters.get(name)
                self._prev_counters[name] = cur
                # first sight of a counter: the whole cumulative value is
                # the window's delta (a restart-reset registry behaves the
                # same — deltas never go negative, matching Prometheus
                # rate() resets)
                delta = (
                    cur - prev if prev is not None and cur >= prev else cur
                )
                if delta:
                    counter_deltas[name] = delta
            for name, h in list(timers.items()) + list(histograms.items()):
                count, total, hi, counts = h.state()
                prev = self._prev_hist.get(name)
                self._prev_hist[name] = (count, total, counts)
                if prev is not None and count >= prev[0]:
                    dcount = count - prev[0]
                    dtotal = total - prev[1]
                    dcounts = [a - b for a, b in zip(counts, prev[2])]
                else:
                    dcount, dtotal, dcounts = count, total, counts
                if dcount <= 0:
                    continue
                hist_windows[name] = {
                    "kind": "timer" if name in timers else "histogram",
                    "count": dcount,
                    "sum": dtotal,
                    # max is cumulative (windowed max is not derivable)
                    "max": hi,
                    "buckets": dcounts,
                    "p50": Histogram.percentile_of(dcounts, 0.50, hi),
                    "p95": Histogram.percentile_of(dcounts, 0.95, hi),
                    "p99": Histogram.percentile_of(dcounts, 0.99, hi),
                }
        gauge_values = {
            name: g.value for name, g in gauges.items()
        }
        with self._lock:
            self._seq += 1
            window = {
                "seq": self._seq,
                "t": self._clock(),
                "ts": self._wall(),
                "interval_s": self.interval_s,
                "counters": counter_deltas,
                "series": hist_windows,
                "gauges": gauge_values,
            }
            self._ring.append(window)
            listeners = list(self._listeners)
        overhead_ms = (time.perf_counter() - t0) * 1000.0
        registry.set_gauge(OVERHEAD_GAUGE, round(overhead_ms, 4))
        registry.timer("observability.history.sample").update(
            int(overhead_ms * 1e6)
        )
        for fn in listeners:
            try:
                fn(window)
            except Exception:  # noqa: BLE001 - a listener must not kill sampling
                pass
        return window

    # ------------------------------------------------------------- querying
    def last_seq(self) -> int:
        """Sequence of the newest sealed window — the ``window``
        stream's cursor position (telemetry bus / ``/watch/info``),
        the same cursor vocabulary the federation scrape uses."""
        with self._lock:
            return self._seq

    def windows(self, last: int = 0) -> List[dict]:
        """The most recent ``last`` windows (0 = all retained), oldest
        first."""
        with self._lock:
            ws = list(self._ring)
        return ws[-last:] if last > 0 else ws

    def series(self, name: str, last: int = 0) -> List[dict]:
        """Per-window points for ONE metric name (exact match), oldest
        first. Counter points carry ``delta``; histogram/timer points the
        window summary; gauge points ``value``."""
        out = []
        for w in self.windows(last):
            point = {"seq": w["seq"], "ts": w["ts"]}
            if name in w["counters"]:
                point["delta"] = w["counters"][name]
            elif name in w["series"]:
                point.update(w["series"][name])
                point.pop("buckets", None)
            elif name in w["gauges"]:
                point["value"] = w["gauges"][name]
            else:
                continue
            out.append(point)
        return out

    def names(self) -> List[str]:
        """Every metric name seen in any retained window (sorted)."""
        seen = set()
        for w in self.windows():
            seen.update(w["counters"])
            seen.update(w["series"])
            seen.update(w["gauges"])
        return sorted(seen)

    def query(self, name: str = "", window: int = 0) -> dict:
        """The ``GET /timeseries`` payload: windows retained, interval,
        and one series per metric whose name starts with ``name``
        (empty = all), each bounded to the last ``window`` windows
        (0 = all retained)."""
        ws = self.windows(window)
        names = [n for n in self.names() if n.startswith(name)]
        return {
            "interval_s": self.interval_s,
            "retention": self._ring.maxlen,
            "windows": len(ws),
            "first_seq": ws[0]["seq"] if ws else 0,
            "last_seq": ws[-1]["seq"] if ws else 0,
            "series": {
                n: self.series(n, window) for n in names
            },
        }

    def scrape(self, last: int = 0) -> dict:
        """The federation scrape payload (``GET /timeseries?raw=1``):
        full retained windows WITH their bucket delta vectors (what
        :meth:`query` strips), plus the sampling clocks' *now* so the
        scraper can window by sequence and estimate this replica's
        wall-clock offset from the request round-trip. Consumed by
        ``observability/federation.py``; per-replica merge semantics
        (counters sum, buckets add) need the raw vectors."""
        from janusgraph_tpu.observability.identity import replica_name

        ws = self.windows(last)
        return {
            "replica": replica_name(),
            "now": self._wall(),
            "mono": self._clock(),
            "interval_s": self.interval_s,
            "retention": self._ring.maxlen,
            "first_seq": ws[0]["seq"] if ws else 0,
            "last_seq": ws[-1]["seq"] if ws else 0,
            "windows": ws,
        }

    # -------------------------------------------------------------- export
    def export_jsonl(self, path: str, last: int = 0) -> int:
        """One JSON line per retained window (full bucket vectors
        included) for offline analysis; returns the line count."""
        ws = self.windows(last)
        with open(path, "w") as f:
            for w in ws:
                f.write(json.dumps(w, default=str) + "\n")
        return len(ws)

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self._ring.clear()
            self._prev_counters.clear()
            self._prev_hist.clear()
            self._seq = 0
            self._listeners.clear()


def bucket_upper_index(threshold: float) -> int:
    """Index of the first bucket whose upper bound exceeds ``threshold``
    (observations in buckets >= this index may exceed the threshold).
    Shared by the SLO engine's latency evaluation."""
    for i, b in enumerate(BUCKET_BOUNDS):
        if b > threshold:
            return i
    return len(BUCKET_BOUNDS)


#: process-wide history; the server starts its sampler, ``GET
#: /timeseries`` / `janusgraph_tpu timeseries` read it back
history = MetricsHistory()
