"""Streaming telemetry bus: the push transport under the pull planes.

Every observability surface before this module is pull-driven: the
federation (PR 17) scrapes ``/timeseries`` on a tick, forensics bundles
(PR 19) wait on disk for someone to ask, and an operator tailing an
incident refreshes ``/flight`` by hand.  Polling bounds freshness by
the poll interval and burns a full scrape per tick even when nothing
happened — the on-chip-communication argument (PAPERS.md 2108.11521:
event-driven delivery beats periodic bulk exchange when events are
sparse relative to the polling budget) applies verbatim to telemetry
transport.  This module is the in-process half of the fix:

``TelemetryBus``
    A typed pub/sub hub over the existing retained planes.  Five
    streams, each keyed by the PRODUCER's own monotonic sequence — the
    same cursor vocabulary the federation already uses for scrape
    windows, so one resume protocol covers both transports:

    ==========  =========================================  ============
    stream      source                                     seq
    ==========  =========================================  ============
    ``flight``  every :class:`FlightRecorder` event        flight seq
    ``window``  every sealed :class:`MetricsHistory`       window seq
                window (bucket vectors included)
    ``slo``     flight events of category ``slo_burn``     flight seq
                (the SLO engine's ladder transitions)
    ``flame``   history-aligned profiler flame-window      window seq
                seals (fallback seals, ``seq=-1``, have
                no stable cursor and are not streamed)
    ``bundle``  flight events of category ``bundle``       flight seq
                (BundleWriter episode announcements —
                what off-host shipping rides)
    ==========  =========================================  ============

    The bus taps its sources through their listener hooks
    (:meth:`FlightRecorder.add_listener`,
    :meth:`MetricsHistory.add_listener`,
    :meth:`SamplingProfiler.add_seal_listener`), all of which fire
    AFTER the source's ring lock is released — publishing never runs
    under a producer lock, and the bus fan-out itself holds only the
    bus lock for dict/deque work (JG203 clean).

``Subscription``
    Bounded per-subscriber queues with DROP-OLDEST overflow and a
    per-subscriber ``dropped`` counter (graphlint JG113, added with
    this module: a fan-out publish into subscriber queues without a
    drop/accounting path is a convoy hazard — one slow subscriber must
    cost itself data, never stall the producers).  Each subscription
    tracks per-stream cursors of the last sequence it was offered, so:

    - a reconnecting subscriber passes its cursors back and the bus
      REPLAYS the retained tail past them (no duplicates, no full
      re-bootstrap — the bounded source rings are the replay log);
    - a cursor older than the ring's first retained seq shows up as a
      seq gap at the consumer, exactly like a federation bounded-tail
      gap, and heals the same way (one full re-fetch);
    - passing no cursor for a stream means LIVE-ONLY: the floor is
      seeded at the source's current seq and history is skipped.

    Subscriber drains auto-register with the stall watchdog (a queue
    with work whose ``delivered`` count stops moving is a wedged
    consumer), and deregister on :meth:`TelemetryBus.unsubscribe`.

Self-cost is accounted on BOTH clocks (the PR 17 discipline): publish
fan-out wall and CPU seconds accumulate into
``observability.stream.overhead_wall_ms`` / ``overhead_cpu_ms`` gauges,
and a publish with zero subscribers costs one lock acquire and nothing
else.  Consumers: the server's ``/watch`` WebSocket endpoint (live
tail + push federation), ``janusgraph_tpu watch``, and the fleet
frontend's push-mode scraper (observability/federation.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "STREAMS",
    "Subscription",
    "TelemetryBus",
    "telemetry_bus",
]

#: the bus taxonomy, in documentation order
STREAMS = ("flight", "window", "slo", "flame", "bundle")

#: flight-event categories re-published as their own typed streams
_DERIVED = {"slo_burn": "slo", "bundle": "bundle"}


class Subscription:
    """One subscriber's bounded drop-oldest queue plus its cursors.

    Created by :meth:`TelemetryBus.subscribe`; consumers call
    :meth:`pop` (blocking, for the ``/watch`` handler's event loop) or
    :meth:`drain` (non-blocking batch, for the push federation's
    reader).  Envelopes are ``{"stream", "seq", "data"}``.
    """

    def __init__(
        self,
        name: str,
        streams: Iterable[str],
        names: Iterable[str] = (),
        depth: int = 256,
    ):
        self.name = name
        self.streams = frozenset(streams)
        #: optional name-prefix filters: flight-family events match on
        #: their category, windows are trimmed to matching metric names
        self.names: Tuple[str, ...] = tuple(names or ())
        self.depth = max(1, int(depth))
        # maxlen is a backstop only: _offer pops-and-counts at depth
        # BEFORE appending, so eviction is always accounted (JG113)
        self._q: deque = deque(maxlen=self.depth)
        self._cond = threading.Condition()
        self.closed = False
        #: events discarded to keep the queue bounded (drop-oldest)
        self.dropped = 0
        self.enqueued = 0
        self.delivered = 0
        #: per-stream last OFFERED seq — the resume cursor. Advanced
        #: even for name-filtered-out events, so a filtered stream is
        #: not gap-free by design (documented in observability.md).
        self.cursors: Dict[str, int] = {}

    # ---------------------------------------------------------- filtering
    def _filter(self, stream: str, data: dict) -> Optional[dict]:
        """Apply the name-prefix filter; None = not for this subscriber."""
        if not self.names:
            return data
        if stream in ("flight", "slo", "bundle"):
            cat = str(data.get("category", ""))
            if any(cat.startswith(p) for p in self.names):
                return data
            return None
        if stream == "window":
            counters = {
                k: v for k, v in (data.get("counters") or {}).items()
                if any(k.startswith(p) for p in self.names)
            }
            series = {
                k: v for k, v in (data.get("series") or {}).items()
                if any(k.startswith(p) for p in self.names)
            }
            gauges = {
                k: v for k, v in (data.get("gauges") or {}).items()
                if any(k.startswith(p) for p in self.names)
            }
            if not (counters or series or gauges):
                return None
            return {
                **data,
                "counters": counters,
                "series": series,
                "gauges": gauges,
            }
        return data

    # ------------------------------------------------------------ enqueue
    def _offer(self, stream: str, seq: int, data: dict) -> Tuple[bool, bool]:
        """Offer one event; returns ``(enqueued, dropped_one)``.  The
        per-stream cursor makes offers idempotent: a replayed tail and
        a racing live publish of the same seq enqueue exactly once."""
        with self._cond:
            if self.closed or stream not in self.streams:
                return False, False
            last = self.cursors.get(stream)
            if last is not None and seq <= last:
                return False, False
            self.cursors[stream] = seq
            payload = self._filter(stream, data)
            if payload is None:
                return False, False
            dropped_one = False
            if len(self._q) >= self.depth:
                # drop-oldest: the slow consumer pays, producers never
                # block (the JG113 contract — accounted, not silent)
                self._q.popleft()
                self.dropped += 1
                dropped_one = True
            self._q.append({"stream": stream, "seq": seq, "data": payload})
            self.enqueued += 1
            self._cond.notify()
            return True, dropped_one

    # ------------------------------------------------------------ consume
    def pop(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Dequeue one envelope, waiting up to ``timeout`` seconds;
        None on timeout or when closed with an empty queue (the
        ``/watch`` handler turns that into a heartbeat)."""
        with self._cond:
            if not self._q and not self.closed and timeout:
                self._cond.wait(timeout)
            if not self._q:
                return None
            self.delivered += 1
            return self._q.popleft()

    def drain(self, max_events: int = 0) -> List[dict]:
        """Dequeue everything queued right now (bounded by
        ``max_events`` when > 0) without waiting."""
        with self._cond:
            n = len(self._q)
            if max_events > 0:
                n = min(n, max_events)
            out = [self._q.popleft() for _ in range(n)]
            self.delivered += n
            return out

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    # ----------------------------------------------------------- plumbing
    def _progress(self) -> dict:
        """Stall-watchdog progress source: queued work whose delivered
        count stops moving is a wedged consumer."""
        with self._cond:
            return {
                "active": 1 if self._q and not self.closed else 0,
                "progress": self.delivered,
            }

    def stats(self) -> dict:
        with self._cond:
            return {
                "name": self.name,
                "streams": sorted(self.streams),
                "names": list(self.names),
                "depth": self.depth,
                "queued": len(self._q),
                "enqueued": self.enqueued,
                "delivered": self.delivered,
                "dropped": self.dropped,
                "cursors": dict(self.cursors),
                "closed": self.closed,
            }


class TelemetryBus:
    """The process-wide pub/sub hub; see the module docstring for the
    stream taxonomy and cursor protocol.  Sources are injectable for
    tests (a fake replica builds a bus over its own history/recorder);
    the module singleton taps the process singletons lazily."""

    def __init__(
        self,
        depth: int = 256,
        history=None,
        recorder=None,
        profiler=None,
    ):
        self.depth = int(depth)
        self._history = history
        self._recorder = recorder
        self._profiler = profiler
        self._lock = threading.Lock()
        self._subs: List[Subscription] = []
        self._serial = 0
        self._attached = False
        self.published = 0
        self.dropped = 0
        self._overhead_wall_s = 0.0
        self._overhead_cpu_s = 0.0

    # ------------------------------------------------------------- sources
    def _sources(self) -> tuple:
        recorder = self._recorder
        history = self._history
        profiler = self._profiler
        if recorder is None:
            from janusgraph_tpu.observability.flight import (
                recorder as _rec,
            )

            recorder = self._recorder = _rec
        if history is None:
            from janusgraph_tpu.observability.timeseries import (
                history as _hist,
            )

            history = self._history = _hist
        if profiler is None:
            from janusgraph_tpu.observability.continuous import (
                sampling_profiler as _prof,
            )

            profiler = self._profiler = _prof
        return recorder, history, profiler

    def configure(self, depth: Optional[int] = None) -> None:
        if depth is not None and depth > 0:
            self.depth = int(depth)

    def attach(self) -> None:
        """Tap the sources (idempotent — the listener hooks dedup, so
        re-attaching after a source reset cleared its listeners simply
        heals the tap)."""
        recorder, history, profiler = self._sources()
        recorder.add_listener(self._on_flight)
        history.add_listener(self._on_window)
        profiler.add_seal_listener(self._on_flame)
        with self._lock:
            self._attached = True

    def detach(self) -> None:
        recorder, history, profiler = self._sources()
        recorder.remove_listener(self._on_flight)
        history.remove_listener(self._on_window)
        profiler.remove_seal_listener(self._on_flame)
        with self._lock:
            self._attached = False

    # ---------------------------------------------------------- publishers
    def _on_flight(self, event: dict) -> None:
        seq = int(event.get("seq", 0))
        self.publish("flight", seq, event)
        derived = _DERIVED.get(str(event.get("category", "")))
        if derived is not None:
            self.publish(derived, seq, event)

    def _on_window(self, window: dict) -> None:
        self.publish("window", int(window.get("seq", 0)), window)

    def _on_flame(self, window: dict) -> None:
        seq = int(window.get("seq", -1))
        if seq > 0:
            self.publish("flame", seq, window)

    def publish(self, stream: str, seq: int, data: dict) -> int:
        """Fan one event out to every matching subscriber; returns the
        number of queues it landed in.  Runs under the bus lock so a
        concurrent :meth:`subscribe` replay and this live publish can
        never lose an event between them (the cursor dedup in
        ``_offer`` collapses the overlap)."""
        with self._lock:
            if not self._subs:
                return 0
            w0 = time.perf_counter()
            c0 = time.thread_time()
            landed = 0
            dropped = 0
            for sub in self._subs:
                ok, dropped_one = sub._offer(stream, seq, data)
                if ok:
                    landed += 1
                if dropped_one:
                    dropped += 1
            self.published += 1
            self.dropped += dropped
            self._overhead_wall_s += time.perf_counter() - w0
            self._overhead_cpu_s += time.thread_time() - c0
            wall_ms = self._overhead_wall_s * 1000.0
            cpu_ms = self._overhead_cpu_s * 1000.0
        from janusgraph_tpu.observability import registry

        registry.counter("observability.stream.published").inc()
        if dropped:
            registry.counter("observability.stream.dropped").inc(dropped)
        registry.set_gauge(
            "observability.stream.overhead_wall_ms", round(wall_ms, 4)
        )
        registry.set_gauge(
            "observability.stream.overhead_cpu_ms", round(cpu_ms, 4)
        )
        return landed

    # ------------------------------------------------------------- cursors
    def cursors(self) -> Dict[str, int]:
        """Current last-published seq per stream, read from the SOURCES
        (authoritative even before the first publish) — the
        ``/watch/info`` payload and every hello frame carry this, so a
        subscriber knows where live begins."""
        recorder, history, profiler = self._sources()
        flight_seq = int(recorder.last_seq)
        return {
            "flight": flight_seq,
            "window": int(history.last_seq()),
            "slo": flight_seq,
            "flame": int(profiler.last_seal_seq()),
            "bundle": flight_seq,
        }

    # ----------------------------------------------------------- subscribe
    def subscribe(
        self,
        streams: Optional[Iterable[str]] = None,
        names: Iterable[str] = (),
        cursors: Optional[Dict[str, int]] = None,
        depth: Optional[int] = None,
        name: str = "",
    ) -> Subscription:
        """Register a subscriber.  ``cursors`` maps stream -> last seq
        already seen: the retained tail past each cursor is replayed
        into the queue before live events flow (resume-after-reconnect
        without duplicates); streams without a cursor start LIVE-ONLY.
        ``streams=None`` subscribes to the full taxonomy."""
        wanted = frozenset(streams) if streams else frozenset(STREAMS)
        unknown = wanted - set(STREAMS)
        if unknown:
            raise ValueError(
                "unknown streams %s (taxonomy: %s)"
                % (sorted(unknown), ", ".join(STREAMS))
            )
        self.attach()
        cursors = dict(cursors or {})
        floors = self.cursors()
        with self._lock:
            self._serial += 1
            sub = Subscription(
                name=name or "sub-%d" % self._serial,
                streams=wanted,
                names=names,
                depth=depth if depth else self.depth,
            )
            for stream in wanted:
                given = cursors.get(stream)
                if given is not None:
                    # resume floor: replay everything retained past it
                    sub.cursors[stream] = int(given)
                else:
                    # live-only: floor at the source's current seq
                    sub.cursors[stream] = int(floors.get(stream, 0))
            self._replay(sub)
            self._subs.append(sub)
        self._register_drain(sub)
        return sub

    def _replay(self, sub: Subscription) -> None:
        """Feed the retained source tails past the subscriber's floors
        into its queue (called under the bus lock, before the sub is
        visible to live publishes — ``_offer``'s cursor check collapses
        any overlap with events racing in behind us)."""
        recorder, history, profiler = self._sources()
        if sub.streams & {"flight", "slo", "bundle"}:
            for event in recorder.events():
                seq = int(event.get("seq", 0))
                sub._offer("flight", seq, event)
                derived = _DERIVED.get(str(event.get("category", "")))
                if derived is not None:
                    sub._offer(derived, seq, event)
        if "window" in sub.streams:
            for window in history.windows():
                sub._offer("window", int(window.get("seq", 0)), window)
        if "flame" in sub.streams:
            for window in profiler.windows():
                seq = int(window.get("seq", -1))
                if seq > 0:
                    sub._offer("flame", seq, window)

    def _register_drain(self, sub: Subscription) -> None:
        """Satellite of the watchdog plane: every subscriber drain is a
        progress source with no manual wiring — a queue holding events
        whose delivered count froze is a wedged consumer."""
        from janusgraph_tpu.observability.continuous import (
            watchdog_singleton,
        )

        watchdog_singleton().register_progress(
            "stream.%s" % sub.name, sub._progress
        )

    def unsubscribe(self, sub: Subscription) -> None:
        sub.close()
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
        from janusgraph_tpu.observability.continuous import (
            watchdog_singleton,
        )

        watchdog_singleton().unregister_progress("stream.%s" % sub.name)

    # ------------------------------------------------------------ querying
    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def status(self) -> dict:
        """The ``/watch/info`` body (minus transport negotiation) and
        the CLI's status view."""
        with self._lock:
            subs = [s.stats() for s in self._subs]
            published = self.published
            dropped = self.dropped
            wall_ms = self._overhead_wall_s * 1000.0
            cpu_ms = self._overhead_cpu_s * 1000.0
            attached = self._attached
        return {
            "streams": list(STREAMS),
            "attached": attached,
            "depth": self.depth,
            "published": published,
            "dropped": dropped,
            "overhead_wall_ms": round(wall_ms, 4),
            "overhead_cpu_ms": round(cpu_ms, 4),
            "subscribers": subs,
            "cursors": self.cursors(),
        }

    def reset(self) -> None:
        """Test hook: detach the taps, close every subscriber, zero the
        accounting."""
        try:
            self.detach()
        except Exception:  # noqa: BLE001 - a reset must always complete
            pass
        with self._lock:
            subs = list(self._subs)
            self._subs = []
        for sub in subs:
            sub.close()
            try:
                from janusgraph_tpu.observability.continuous import (
                    watchdog_singleton,
                )

                watchdog_singleton().unregister_progress(
                    "stream.%s" % sub.name
                )
            except Exception:  # noqa: BLE001 - a reset must always complete
                pass
        with self._lock:
            self._serial = 0
            self.published = 0
            self.dropped = 0
            self._overhead_wall_s = 0.0
            self._overhead_cpu_s = 0.0


#: process-wide bus; the server's /watch endpoint and the push-mode
#: federation subscribe here, `janusgraph_tpu watch` tails it remotely
telemetry_bus = TelemetryBus()
