"""Process replica identity: one tag threaded through every telemetry plane.

A serving fleet (server/fleet.py) is N near-identical replicas whose
telemetry lands in per-process sinks — flight events, structured logs,
``/metrics`` — and an incident reconstructed across replicas needs every
record to say WHICH replica produced it. This module is the one place the
tag lives: ``set_replica()`` once at process start (the ``server`` /
``fleet`` CLI runners do it), and the flight recorder, structured logger,
and Prometheus exposition all stamp their output from here.

Deliberately dependency-free (flight.py and logging.py import it, and
they are imported by everything else). The default is the empty string —
single-process embedded use stays untagged, byte-identical to the
pre-fleet output.
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()
_REPLICA = ""


def set_replica(name: str) -> None:
    """Set this process's replica tag ('' clears it)."""
    global _REPLICA
    with _LOCK:
        _REPLICA = str(name or "")


def replica_name() -> str:
    """The process's replica tag ('' when untagged)."""
    with _LOCK:
        return _REPLICA
